"""Multi-tenant checking service (jepsen_tpu.service).

The acceptance contract under test:

- **Differential**: for N >= 4 concurrent tenant streams (valid,
  seeded-invalid, overflow-unknown mix) each tenant's folded service
  verdict equals offline ``check_history`` on that tenant's history
  alone — cross-tenant co-batching never changes a verdict, and the
  seeded-invalid tenant aborts (``--online-abort`` semantics, scoped
  to one tenant) without disturbing the others.
- **Admission & backpressure**: over-quota submits are rejected with a
  typed error, a stalled consumer bounds the ingest queue (no
  unbounded memory growth), and graceful drain returns per-tenant
  partial results.
- **Co-batching & fairness**: device/host rounds contain members from
  multiple tenants (``online_round`` telemetry), and a trickle
  tenant's watermark advances while a neighbour floods.

Everything runs the compile-free host engine except the device
co-batch differential, which is marked ``slow`` (tier-1 runs
``-m 'not slow'``)."""

import json
import random
import threading
import time
import urllib.error
import urllib.request

import pytest

from jepsen_tpu.models import CasRegister
from jepsen_tpu.ops import wgl
from jepsen_tpu.service import (
    AdmissionError,
    IngestQueueFullError,
    QuotaExceededError,
    Service,
    ServiceClosedError,
    TenantAbortedError,
    TenantLimitError,
)
from jepsen_tpu.service import http as shttp
from jepsen_tpu.telemetry import Registry
from jepsen_tpu.testing import (
    chunked_register_history,
    perturb_history,
    random_register_history,
)

pytestmark = pytest.mark.service


def model():
    return CasRegister(init=0)


def offline(history, **kw):
    return wgl.check_history(model(), history, backend="host", **kw)


def mk(**kw):
    """A host-engine service with the observability side effects tests
    don't want (global live source, repo ledger) turned off."""
    kw.setdefault("engine", "host")
    kw.setdefault("register_live", False)
    kw.setdefault("ledger", False)
    return Service(model(), **kw)


def feed(svc, tenant, history):
    for op in history:
        svc.submit(tenant, op)


def valid_history(seed, n_ops=200):
    return chunked_register_history(random.Random(seed), n_ops=n_ops,
                                    n_procs=2, chunk_ops=30)


# ---------------------------------------------------------------------------


class TestAdmission:
    def test_max_tenants_typed_reject(self):
        svc = mk(max_tenants=2)
        try:
            svc.submit("a", {"type": "invoke", "process": 0,
                             "f": "read", "value": None, "time": 0})
            svc.register("b")
            with pytest.raises(TenantLimitError) as e:
                svc.submit("c", {"type": "invoke", "process": 0,
                                 "f": "read", "value": None, "time": 1})
            assert isinstance(e.value, AdmissionError)
            assert e.value.http_status == 429
            # The rejected tenant was never admitted.
            assert svc.tenants() == ["a", "b"]
        finally:
            svc.drain(timeout=10)

    def test_quota_typed_reject_and_refill(self):
        # burst of 5 tokens, refilling at 50/s: the 6th back-to-back
        # submit rejects; after ~0.1 s of refill, submits flow again.
        svc = mk(quota_ops_per_s=50.0, quota_burst=5.0)
        try:
            h = valid_history(1, n_ops=20)
            ops = list(h)
            for op in ops[:5]:
                svc.submit("t", op)
            with pytest.raises(QuotaExceededError) as e:
                svc.submit("t", ops[5])
            assert e.value.http_status == 429
            time.sleep(0.12)
            svc.submit("t", ops[5])  # refilled
            snap = svc.tenant_snapshot("t")
            assert snap["rejected"]["quota"] >= 1
            assert snap["ops_ingested"] == 6
        finally:
            svc.drain(timeout=10)

    def test_draining_service_rejects_with_typed_error(self):
        svc = mk()
        svc.submit("t", {"type": "invoke", "process": 0, "f": "write",
                         "value": 1, "time": 0})
        svc.drain(timeout=10)
        with pytest.raises(ServiceClosedError) as e:
            svc.submit("t", {"type": "ok", "process": 0, "f": "write",
                             "value": 1, "time": 1})
        assert e.value.http_status == 503


class TestBackpressure:
    def test_stalled_consumer_bounds_queue_reject_mode(self, monkeypatch):
        # Stall the pump: the bounded ingest queue fills to EXACTLY
        # queue_limit and further submits reject with the typed 429 —
        # memory never grows unboundedly.
        monkeypatch.setattr(Service, "_pump_once",
                            lambda self: False)
        svc = mk(queue_limit=10)
        h = list(valid_history(2, n_ops=40))
        for op in h[:10]:
            svc.submit("t", op)
        with pytest.raises(IngestQueueFullError) as e:
            svc.submit("t", h[10])
        assert e.value.http_status == 429
        snap = svc.tenant_snapshot("t")
        assert snap["queue_depth"] == 10
        assert snap["rejected"]["queue"] >= 1
        # Graceful drain still delivers the ACCEPTED ops (the drain
        # path feeds synchronously when the pump is gone) and returns
        # the tenant's partial result.
        fin = svc.drain(timeout=20)
        t = fin["tenants"]["t"]
        assert t["ops_observed"] == 10
        assert "undelivered_ops" not in t

    def test_stalled_consumer_block_mode_times_out(self, monkeypatch):
        monkeypatch.setattr(Service, "_pump_once",
                            lambda self: False)
        svc = mk(queue_limit=2, backpressure="block",
                 block_timeout_s=0.1)
        h = list(valid_history(3, n_ops=20))
        svc.submit("t", h[0])
        svc.submit("t", h[1])
        t0 = time.monotonic()
        with pytest.raises(IngestQueueFullError):
            svc.submit("t", h[2])
        assert time.monotonic() - t0 >= 0.09  # it blocked, then gave up
        svc.drain(timeout=20)


class TestDifferentialContract:
    """The ISSUE-8 acceptance clause: N >= 4 concurrent tenants, mixed
    verdicts, each tenant's service verdict == offline check_history on
    its history alone; the seeded-invalid tenant aborts without
    disturbing the others."""

    MC = 2000  # shared budget; calibrated so the mix below lands
    # valid/invalid/unknown offline under the SAME budget

    def histories(self):
        hs = {
            "valid-a": valid_history(21),
            "valid-b": valid_history(22),
            "invalid": perturb_history(
                random.Random(7), valid_history(23)),
            # Wide concurrency + open intervals: both offline and the
            # per-segment enumerator trip the same config budget.
            "overflow": random_register_history(
                random.Random(24), n_ops=120, n_procs=10, crash_p=0.2),
        }
        return hs

    def test_four_tenant_mixed_differential(self):
        hs = self.histories()
        want = {name: offline(h, host_max_configs=self.MC)["valid"]
                for name, h in hs.items()}
        assert want == {"valid-a": True, "valid-b": True,
                        "invalid": False, "overflow": "unknown"}
        reg = Registry()
        svc = mk(metrics=reg, max_configs=self.MC,
                 abort_on_violation=True)

        def run_one(name):
            try:
                feed(svc, name, hs[name])
            except TenantAbortedError:
                pass  # the seeded-invalid stream's expected exit

        threads = [threading.Thread(target=run_one, args=(n,))
                   for n in hs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fin = svc.drain(timeout=60)
        got = {n: fin["tenants"][n]["valid"] for n in hs}
        assert got == want  # co-batching never changed a verdict
        assert fin["valid"] is False  # merge: any invalid tenant
        # The invalid tenant aborted with detection metrics; nobody
        # else did, and the valid tenants decided their full streams.
        inv = fin["tenants"]["invalid"]
        assert inv["aborted"] is True
        assert inv["ops_to_detection"] >= 1
        assert inv["seconds_to_detection"] >= 0
        assert "violation" in inv
        for n in ("valid-a", "valid-b", "overflow"):
            assert fin["tenants"][n]["aborted"] is False
        for n in ("valid-a", "valid-b"):
            assert fin["tenants"][n]["decided_through_index"] == \
                hs[n][-1].index
            assert fin["tenants"][n]["decision_latency"]["count"] > 0
        # Cross-tenant co-batching really happened: at least one
        # dispatch round held members from >= 2 tenants.
        rounds = reg.events("online_round")
        assert rounds
        assert any(len(ev["streams"]) >= 2 for ev in rounds)

    def test_per_tenant_metric_families(self):
        # The satellite: online_scheduler_backlog generalized to
        # {tenant} children while the unlabeled total stays for
        # existing dashboards; watermark + decision latency +
        # service_segments_total follow the same shape.
        reg = Registry()
        svc = mk(metrics=reg)
        try:
            feed(svc, "t-a", valid_history(31, n_ops=60))
            feed(svc, "t-b", valid_history(32, n_ops=60))
            assert svc.flush(30.0)
        finally:
            fin = svc.drain(timeout=30)
        assert fin["valid"] is True
        samples = {(s["name"], tuple(sorted(s["labels"].items())))
                   for s in reg.collect()}
        # Unlabeled totals (existing dashboards) AND per-tenant rows.
        assert ("online_scheduler_backlog", ()) in samples
        assert ("online_scheduler_backlog",
                (("tenant", "t-a"),)) in samples
        assert ("online_decided_watermark",
                (("tenant", "t-b"),)) in samples
        assert ("decision_latency_seconds", ()) in samples
        assert ("decision_latency_seconds",
                (("tenant", "t-a"),)) in samples
        assert any(n == "service_segments_total"
                   and dict(l).get("tenant") == "t-b"
                   for n, l in samples)
        # Drained: every backlog child reads 0.
        for s in reg.collect():
            if s["name"] == "online_scheduler_backlog":
                assert s["value"] == 0


class TestFairness:
    def test_trickle_tenant_advances_while_neighbour_floods(self):
        reg = Registry()
        svc = mk(metrics=reg, max_ready_per_tenant=4)
        flood = valid_history(41, n_ops=4000)
        trickle = valid_history(42, n_ops=40)
        flood_done = threading.Event()

        def run_flood():
            try:
                for i, op in enumerate(flood):
                    svc.submit("flood", op)
                    if i % 20 == 19:
                        time.sleep(0.002)  # stretch the flood window
            finally:
                flood_done.set()

        th = threading.Thread(target=run_flood)
        th.start()
        try:
            time.sleep(0.01)  # the flood is in full swing…
            feed(svc, "trickle", trickle)
            # …and the trickle tenant's watermark must advance WHILE
            # the neighbour is still flooding.
            advanced = False
            while not flood_done.is_set():
                if svc.scheduler.stream_watermark("trickle") > 0:
                    advanced = True
                    break
                time.sleep(0.001)
            th.join()
            fin = svc.drain(timeout=60)
        finally:
            flood_done.set()
            th.join(timeout=5)
        assert advanced, "trickle watermark starved behind the flood"
        assert fin["tenants"]["trickle"]["valid"] is True
        assert fin["tenants"]["flood"]["valid"] is True
        assert fin["tenants"]["trickle"]["decided_through_index"] == \
            trickle[-1].index
        # The fairness cap held: no round took more than
        # max_ready_per_tenant SEGMENTS from one tenant. (Whether a
        # round happened to mix both tenants is timing-dependent here —
        # the deterministic co-batch pin is
        # test_one_round_co_batches_distinct_streams below.)
        rounds = reg.events("online_round")
        assert rounds
        assert max(max(ev["stream_segments"].values())
                   for ev in rounds) <= 4

    def test_one_round_co_batches_distinct_streams(self, monkeypatch):
        # Deterministic co-batching pin at the scheduler layer: while
        # the worker is held inside round 1 (a gated stage-1 decide),
        # two OTHER streams enqueue — the worker's next inbox take
        # drains both opportunistically, so round 2 must carry members
        # of both streams (the cross-tenant "distinct keys pipeline"
        # generalization itself, free of pump/thread timing).
        from jepsen_tpu.online import SINGLE_KEY, SegmentScheduler
        from jepsen_tpu.online import scheduler as sched_mod
        from jepsen_tpu.online.segmenter import KeySegment

        orig = sched_mod.segment_states
        entered = threading.Event()
        gate = threading.Event()

        def gated(enc, max_configs=500_000):
            if not entered.is_set():
                entered.set()
                assert gate.wait(30.0)
            return orig(enc, max_configs=max_configs)

        monkeypatch.setattr(sched_mod, "segment_states", gated)

        def seg_of(history, seq):
            h = list(history)
            return [KeySegment(SINGLE_KEY, seq, tuple(h), h[0].index,
                               h[-1].index)]

        reg = Registry()
        sched = SegmentScheduler(model(), engine="host", metrics=reg)
        try:
            hx = valid_history(91, n_ops=8)
            sched.submit(seg_of(hx, 0), stream="x")
            assert entered.wait(30.0)  # worker is inside round 1
            ha, hb = valid_history(92, n_ops=8), valid_history(93,
                                                               n_ops=8)
            sched.submit(seg_of(ha, 0), stream="a")
            sched.submit(seg_of(hb, 0), stream="b")
            gate.set()
            assert sched.wait_idle(30.0)
        finally:
            gate.set()
            sched.close(timeout=10)
        rounds = reg.events("online_round")
        assert any({"a", "b"} <= set(ev["streams"]) for ev in rounds), \
            "round 2 did not co-batch the two waiting streams"
        for s in ("x", "a", "b"):
            assert sched.stream_result(s)["valid"] is True


class TestDrain:
    def test_drain_is_idempotent_and_returns_partials(self):
        svc = mk()
        h = list(valid_history(51, n_ops=60))
        # Cut the stream mid-flight: the tail (an open invocation) must
        # fold as a terminal segment — a PARTIAL verdict, like
        # --online's finish on an aborted run.
        feed(svc, "t", h[:len(h) - 3])
        fin = svc.drain(timeout=30)
        assert fin["tenants"]["t"]["valid"] is True
        assert fin["tenants"]["t"]["segments_decided"] >= 1
        assert svc.drain(timeout=1) is fin  # idempotent

    def test_terminal_segment_agrees_with_offline(self):
        from jepsen_tpu.history import History, Op

        svc = mk()
        base = list(valid_history(52, n_ops=40))
        t_end = base[-1].time + 1
        base.append(Op("invoke", 0, "write", 3, time=t_end))
        h = History(base, reindex=True)
        assert offline(h)["valid"] is True
        feed(svc, "t", h)
        fin = svc.drain(timeout=30)
        assert fin["tenants"]["t"]["valid"] is True
        rows = fin["tenants"]["t"]["segments"]
        assert any(r["terminal"] for r in rows)


class TestHTTPIngestion:
    @pytest.fixture()
    def served(self):
        svc = mk(quota_ops_per_s=None)
        srv = shttp.server(svc, port=0)
        threading.Thread(
            target=lambda: srv.serve_forever(poll_interval=0.05),
            daemon=True).start()
        port = srv.server_address[1]

        def post(path, body=b""):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=body,
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read().decode())

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return r.status, json.loads(r.read().decode())

        yield svc, post, get
        srv.shutdown()
        srv.server_close()
        svc.drain(timeout=10)

    @staticmethod
    def ndjson(history):
        return "".join(
            json.dumps({"type": op.type, "process": op.process,
                        "f": op.f, "value": op.value, "time": op.time})
            + "\n" for op in history).encode()

    def test_ndjson_ingest_two_tenants_and_drain(self, served):
        svc, post, get = served
        ha, hb = valid_history(61, n_ops=60), valid_history(62, n_ops=60)
        st, doc = post("/submit/alpha", self.ndjson(ha))
        assert st == 200 and doc["accepted"] == len(ha)
        st, doc = post("/submit/beta", self.ndjson(hb))
        assert st == 200 and doc["accepted"] == len(hb)
        st, doc = get("/tenants")
        assert st == 200
        assert set(doc["tenants"]) == {"alpha", "beta"}
        st, doc = get("/healthz")
        assert st == 200 and doc["ok"] is True
        st, fin = post("/drain")
        assert st == 200
        assert fin["tenants"]["alpha"]["valid"] is True
        assert fin["tenants"]["beta"]["valid"] is True
        # Post-drain ingest answers the typed 503, with the fixed
        # drain hint in Retry-After (satellite: 429/503 responses
        # carry the standard backoff header).
        with pytest.raises(urllib.error.HTTPError) as e:
            post("/submit/alpha", self.ndjson(ha[:2]))
        assert e.value.code == 503
        assert int(e.value.headers.get("Retry-After")) >= 1
        doc = json.loads(e.value.read().decode())
        assert doc["error"] == "draining"
        assert doc["retry_after_s"] >= 1

    def test_over_quota_maps_to_429_with_resume_point(self):
        svc = mk(quota_ops_per_s=50.0, quota_burst=4.0)
        srv = shttp.server(svc, port=0)
        threading.Thread(
            target=lambda: srv.serve_forever(poll_interval=0.05),
            daemon=True).start()
        port = srv.server_address[1]
        try:
            body = self.ndjson(list(valid_history(63, n_ops=20))[:10])
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/submit/q", data=body,
                method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 429
            # Retry-After rides the 429: the token bucket's own refill
            # estimate (integral seconds, never 0), next to the
            # retryable flag — a well-behaved client backs off by the
            # server's estimate instead of guessing.
            ra = e.value.headers.get("Retry-After")
            assert ra is not None and int(ra) >= 1
            doc = json.loads(e.value.read().decode())
            assert doc["error"] == "quota_exceeded"
            assert doc["accepted"] == 4  # the client's resume point
            assert doc["retryable"] is True
            assert doc["retry_after_s"] >= 0
        finally:
            srv.shutdown()
            srv.server_close()
            svc.drain(timeout=10)

    def test_oversized_body_is_413_before_buffering(self):
        # The bounded-memory contract holds at the HTTP layer too: a
        # body over the cap rejects on its Content-Length, before
        # anything is read into RAM.
        from jepsen_tpu.service import http as shttp_mod

        svc = mk()
        srv = shttp_mod.ThreadingHTTPServer(
            ("", 0), shttp_mod.make_handler(svc, max_body=1024))
        threading.Thread(
            target=lambda: srv.serve_forever(poll_interval=0.05),
            daemon=True).start()
        port = srv.server_address[1]
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/submit/big",
                data=b"x" * 2048, method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 413
            doc = json.loads(e.value.read().decode())
            assert doc["error"] == "body_too_large"
            assert doc["max_bytes"] == 1024
        finally:
            srv.shutdown()
            srv.server_close()
            svc.drain(timeout=10)

    def test_bad_json_is_400(self, served):
        _svc, post, _get = served
        with pytest.raises(urllib.error.HTTPError) as e:
            post("/submit/x", b'{"type": "invoke", \n')
        assert e.value.code == 400

    def test_malformed_op_never_kills_the_shared_pump(self, served):
        # Ingest is an external surface: a parseable-JSON line that is
        # not an op (here: a list) is accepted by admission but must be
        # DROPPED by the pump, not crash it — the tenant's own stream
        # and every other tenant keep deciding.
        svc, post, _get = served
        h = valid_history(64, n_ops=40)
        st, _ = post("/submit/m", b"[1, 2, 3]\n" + self.ndjson(h))
        assert st == 200
        assert svc.flush(30.0)
        snap = svc.tenant_snapshot("m")
        assert snap["rejected"].get("malformed") == 1
        assert snap["ops_observed"] == len(h) + 1
        assert snap["verdict"] == "True"


class TestDeviceCoBatch:
    @pytest.mark.slow
    def test_device_batch_carries_members_of_both_tenants(self):
        # The device oracle only takes what the enumerator can't —
        # terminal segments — so each tenant's stream has its
        # quiescence POISONED halfway (an ok write becomes an :info:
        # a crashed write whose effect applied — still valid), leaving
        # a substantial terminal segment per tenant; the shared closing
        # round batches BOTH tenants' terminal members into ONE
        # vmapped device program (telemetry-asserted), and the
        # verdicts still match offline.
        from jepsen_tpu.history import History

        reg = Registry()
        svc = Service(model(), engine="device", batch_f=64,
                      metrics=reg, register_live=False, ledger=False)
        hs = {}
        for i, name in enumerate(("dev-a", "dev-b")):
            base = list(chunked_register_history(
                random.Random(71 + i), n_ops=100, n_procs=2,
                chunk_ops=30))
            k = next(j for j in range(len(base) // 2, len(base))
                     if base[j].is_ok and base[j].f == "write")
            base[k] = base[k].with_(type="info")
            hs[name] = History(base, reindex=True)
        # Feed fully, wait for the quiescent segments to decide, then
        # drain — the two terminal segments land in one closing round.
        for name, h in hs.items():
            feed(svc, name, h)
        assert svc.flush(120.0)
        fin = svc.drain(timeout=120)
        for name, h in hs.items():
            assert fin["tenants"][name]["valid"] is \
                offline(h)["valid"] is True
        rounds = [ev for ev in reg.events("online_round")
                  if ev["engine"] == "device"]
        assert rounds, "no device round dispatched"
        assert any(len(ev["oracle_streams"]) >= 2 for ev in rounds), \
            "no device batch co-batched members of both tenants"
        # The PR-2 batch pipeline really ran ONE shared program wide
        # enough for both tenants: batch-chunk events exist and their
        # batch dimension carried >= 2 members (the batch-occupancy
        # telemetry; the occupancy gauge itself drains to 0 once every
        # member decides).
        chunks = reg.events("wgl_batch_chunk")
        assert chunks, "the PR-2 batch pipeline never ran"
        assert any(ev["batch"] >= 2 for ev in chunks)


class TestLiveSnapshot:
    def test_snapshot_lists_tenants_in_registration_order(self):
        svc = mk()
        try:
            feed(svc, "zeta", valid_history(81, n_ops=40))
            feed(svc, "alpha", valid_history(82, n_ops=40))
            assert svc.flush(30.0)
            snap = svc.live_snapshot()
            assert snap["service"] is True
            assert list(snap["tenants"]) == ["zeta", "alpha"]
            row = snap["tenants"]["zeta"]
            assert row["watermark"] >= 0
            assert row["verdict"] == "True"
            assert "p99_s" in row["decision_latency"]
            assert row["queue_depth"] == 0
        finally:
            svc.drain(timeout=30)

    def test_ledger_records_one_row_per_tenant(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv("JEPSEN_LEDGER_PATH",
                           str(tmp_path / "ledger.jsonl"))
        svc = mk(ledger=True)
        ha, hb = valid_history(83, n_ops=40), valid_history(84, n_ops=40)
        feed(svc, "la", ha)
        feed(svc, "lb", hb)
        fin = svc.drain(timeout=30)
        assert fin["valid"] is True
        from jepsen_tpu.telemetry import ledger as jledger

        recs = jledger.load(tmp_path / "ledger.jsonl")
        by_run = {r["run"]: r for r in recs}
        assert set(by_run) == {"service/la", "service/lb"}
        assert by_run["service/la"]["ops"] == len(ha)
        assert by_run["service/lb"]["ops"] == len(hb)
        for r in recs:
            assert r["kind"] == "service"
            assert r["verdict"] == "True"
            assert "ops_per_s" in r
