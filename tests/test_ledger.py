"""Cross-run perf ledger: append/load, record builders, the trend CLI,
the --check regression gate on a synthetic ledger with an injected
regression, and the per-run append through core.run."""

from __future__ import annotations

import json

import pytest

from jepsen_tpu.telemetry import Registry
from jepsen_tpu.telemetry import ledger


def _rec(ts, workload="cas-register", engine="native", **metrics):
    return {"ts": ts, "kind": "run", "run": f"{workload}/{ts}",
            "workload": workload, "engine": engine, "verdict": "True",
            **metrics}


class TestAppendLoad:
    def test_roundtrip_appends_one_line_per_record(self, tmp_path):
        p = tmp_path / "ledger.jsonl"
        assert ledger.append(_rec(1, checker_seconds=0.5), path=p)
        assert ledger.append(_rec(2, checker_seconds=0.4), path=p)
        assert len(p.read_text().splitlines()) == 2
        recs = ledger.load(p)
        assert [r["ts"] for r in recs] == [1, 2]

    def test_ts_is_stamped_when_absent(self, tmp_path):
        p = tmp_path / "ledger.jsonl"
        ledger.append({"kind": "run", "workload": "w", "engine": "h"},
                      path=p)
        (r,) = ledger.load(p)
        assert r["ts"] > 1_700_000_000

    def test_unparseable_lines_are_skipped_not_fatal(self, tmp_path):
        p = tmp_path / "ledger.jsonl"
        p.write_text('not json\n' + json.dumps(_rec(5)) + '\n')
        assert [r["ts"] for r in ledger.load(p)] == [5]
        assert ledger.load(tmp_path / "missing.jsonl") == []

    def test_append_never_raises(self, tmp_path):
        # Unwritable target (a directory in the file's place).
        bad = tmp_path / "dir"
        bad.mkdir()
        assert ledger.append(_rec(1), path=bad) is None

    def test_env_override_wins(self, tmp_path, monkeypatch):
        monkeypatch.setenv("JEPSEN_LEDGER_PATH",
                           str(tmp_path / "ci.jsonl"))
        assert ledger.default_path("/elsewhere") == \
            tmp_path / "ci.jsonl"


class TestRecordBuilders:
    def test_record_of_run_compacts_the_test_map(self):
        reg = Registry()
        reg.gauge("checker_seconds", "s", labelnames=("checker",
                                                      "backend")) \
            .labels(checker="linearizable", backend="native").set(0.123)
        test = {
            "name": "cas-register", "start-time": "2026",
            "history": [1] * 40,
            "results": {"valid": True,
                        "linearizable": {"valid": True,
                                         "backend": "native"}},
            "telemetry-registry": reg,
            "online-results": {"decision_latency": {"p99_s": 0.5}},
        }
        r = ledger.record_of_run(test)
        assert r["kind"] == "run"
        assert r["workload"] == "cas-register"
        assert r["engine"] == "native"  # dug out of the nested results
        assert r["ops"] == 40
        assert r["verdict"] == "True"
        assert r["checker_seconds"] == 0.123
        assert r["p99_decision_latency_s"] == 0.5
        assert "utilization_pct" not in r  # no chunk events recorded

    def test_record_of_run_without_telemetry_still_records(self):
        r = ledger.record_of_run({"name": "w", "start-time": "t",
                                  "results": {"valid": False}})
        assert r["verdict"] == "False" and r["engine"] == "host"

    def test_records_of_bench_one_per_leg_that_produced_numbers(self):
        out = {
            "value": 0.05, "ops_per_s": 200000.0,
            "invalid_s": 0.4,
            "online_10k": {"online_s": 1.5, "n_ops": 10000,
                           "valid": False,
                           "p99_decision_latency_s": 0.2},
            "batch_replay_100": {"skipped": "budget"},
            "batch_replay_large": {
                "value_s": 3.0,
                "smoke_8x10k": {"value_s": 60.0, "decided": 4,
                                "utilization_pct": 41.5}},
            "mutex_5k": {"error": "boom"},
        }
        recs = {r["workload"]: r for r in ledger.records_of_bench(out)}
        assert recs["headline"]["value_s"] == 0.05
        assert recs["headline"]["engine"] == "native"
        assert recs["online_10k"]["p99_decision_latency_s"] == 0.2
        assert recs["online_10k"]["verdict"] == "False"
        assert recs["smoke_8x10k"]["utilization_pct"] == 41.5
        # Skipped/errored legs leave no record.
        assert "batch_replay_100" not in recs
        assert "mutex_5k" not in recs


class TestTrendAndCheck:
    def test_groups_compare_only_like_runs(self, tmp_path):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, engine="native", checker_seconds=0.4),
                      path=p)
        ledger.append(_rec(2, engine="device", checker_seconds=9.0),
                      path=p)  # different engine: NOT comparable
        blocks = ledger.trend(ledger.load(p))
        assert len(blocks) == 2
        assert all("deltas" not in b for b in blocks)  # 1 record each

    def test_check_flags_an_injected_regression(self, tmp_path):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, checker_seconds=0.40,
                           utilization_pct=80.0), path=p)
        ledger.append(_rec(2, checker_seconds=0.41,
                           utilization_pct=79.0), path=p)  # noise, ok
        assert ledger.check(ledger.load(p)) == []
        ledger.append(_rec(3, checker_seconds=0.80,
                           utilization_pct=79.0), path=p)  # 2x slower
        (flagged,) = ledger.check(ledger.load(p))
        assert flagged["regressions"] == ["checker_seconds"]

    def test_info_metrics_never_gate(self, tmp_path):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, ops=1000), path=p)
        ledger.append(_rec(2, ops=10), path=p)  # ops is info-only
        assert ledger.check(ledger.load(p)) == []


class TestCli:
    def test_cli_renders_trend_and_exits_zero_without_check(
            self, tmp_path, capsys):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, checker_seconds=0.4), path=p)
        ledger.append(_rec(2, checker_seconds=0.9), path=p)
        assert ledger.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "cas-register" in out and "checker_seconds" in out
        assert "** REGRESSION" in out  # shown, but not gated

    def test_cli_check_exits_nonzero_on_regression(self, tmp_path,
                                                   capsys):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, checker_seconds=0.4), path=p)
        ledger.append(_rec(2, checker_seconds=0.9), path=p)
        assert ledger.main([str(p), "--check"]) == 1
        assert "REGRESSIONS past 10%" in capsys.readouterr().out
        # A looser threshold passes the same ledger.
        assert ledger.main([str(p), "--check", "--threshold", "2"]) == 0

    def test_cli_check_passes_on_a_clean_ledger(self, tmp_path, capsys):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, checker_seconds=0.4), path=p)
        ledger.append(_rec(2, checker_seconds=0.39), path=p)
        assert ledger.main([str(p), "--check"]) == 0
        assert "no regressions" in capsys.readouterr().out

    def test_cli_json_and_workload_filter(self, tmp_path, capsys):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, checker_seconds=0.4), path=p)
        ledger.append(_rec(2, workload="other", checker_seconds=1.0),
                      path=p)
        assert ledger.main([str(p), "--json", "--workload",
                            "cas-register"]) == 0
        doc = json.loads(capsys.readouterr().out)
        (g,) = doc["groups"]
        assert g["key"]["workload"] == "cas-register"

    def test_module_shim_is_invocable(self):
        import subprocess
        import sys

        r = subprocess.run(
            [sys.executable, "-m", "jepsen_tpu.ledger", "--help"],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0
        assert "--check" in r.stdout
        assert "--alerts" in r.stdout


@pytest.mark.alerts
class TestAlertsEmission:
    """--check --alerts PATH bridges ledger regressions into the durable
    alert stream: each flagged trend group appends one perf_regression
    record that jepsen_tpu.telemetry.alerts.replay folds back into the
    firing set, with per-rule generations continuing across invocations."""

    def _regressing(self, tmp_path):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, checker_seconds=0.4), path=p)
        ledger.append(_rec(2, checker_seconds=0.9), path=p)
        return p

    def test_check_alerts_appends_a_perf_regression_record(
            self, tmp_path, capsys):
        from jepsen_tpu.telemetry import alerts
        p = self._regressing(tmp_path)
        ap = tmp_path / "alerts.jsonl"
        assert ledger.main([str(p), "--check", "--alerts", str(ap)]) == 1
        capsys.readouterr()
        recs = [json.loads(l) for l in ap.read_text().splitlines()]
        assert len(recs) == 1
        (rec,) = recs
        assert rec["rule"] == "perf_regression"
        assert rec["severity"] == "medium"
        assert rec["state"] == "firing"
        assert rec["source"] == "ledger"
        assert rec["generation"] == 1
        assert "checker_seconds" in rec["evidence"]["regressions"]
        assert rec["evidence"]["key"]["workload"] == "cas-register"
        rep = alerts.replay(ap)
        assert "perf_regression" in rep["firing"]
        assert rep["torn"] is False

    def test_generations_continue_across_invocations(
            self, tmp_path, capsys):
        p = self._regressing(tmp_path)
        ap = tmp_path / "alerts.jsonl"
        assert ledger.main([str(p), "--check", "--alerts", str(ap)]) == 1
        assert ledger.main([str(p), "--check", "--alerts", str(ap)]) == 1
        capsys.readouterr()
        gens = [json.loads(l)["generation"]
                for l in ap.read_text().splitlines()]
        assert gens == [1, 2]

    def test_clean_ledger_writes_no_alerts(self, tmp_path, capsys):
        p = tmp_path / "l.jsonl"
        ledger.append(_rec(1, checker_seconds=0.4), path=p)
        ledger.append(_rec(2, checker_seconds=0.39), path=p)
        ap = tmp_path / "alerts.jsonl"
        assert ledger.main([str(p), "--check", "--alerts", str(ap)]) == 0
        capsys.readouterr()
        assert not ap.exists()

    def test_alerts_without_check_is_inert(self, tmp_path, capsys):
        p = self._regressing(tmp_path)
        ap = tmp_path / "alerts.jsonl"
        # Trend display only; nothing gates, nothing is emitted.
        assert ledger.main([str(p), "--alerts", str(ap)]) == 0
        capsys.readouterr()
        assert not ap.exists()


class TestCoreRunAppends:
    def test_every_persisted_run_appends_one_record(self, tmp_path):
        from jepsen_tpu import checker as jchecker
        from jepsen_tpu import core
        from jepsen_tpu import generator as gen
        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.workloads import (AtomClient, AtomDB, AtomState,
                                          noop_test)

        state = AtomState()
        test = dict(noop_test())
        test.update(
            name="ledger-smoke", db=AtomDB(state),
            client=AtomClient(state), model=CasRegister(init=0),
            concurrency=2, **{"telemetry?": True},
            checker=jchecker.linearizable(model=CasRegister(init=0)),
            generator=gen.clients(gen.limit(20, gen.mix([
                lambda: {"f": "read"},
                lambda: {"f": "write", "value": gen.rand_int(5)},
            ]))))
        test["store-root"] = str(tmp_path)
        res = core.run(test)
        assert res["results"]["valid"] is True
        (rec,) = ledger.load(tmp_path / "ledger.jsonl")
        assert rec["kind"] == "run"
        assert rec["workload"] == "ledger-smoke"
        assert rec["verdict"] == "True"
        assert rec["ops"] == len(res["history"])
        assert rec["checker_seconds"] >= 0
