"""Differential tests: device WGL kernel vs host oracle + golden corpus.

This is the fourth test tier SURVEY.md §4 calls for — CPU-checker vs
TPU-checker agreement on valid AND invalid histories (run on the CPU
backend here; same XLA program runs on the chip).
"""

import random

import pytest

from jepsen_tpu.models import CasRegister
from jepsen_tpu.ops import wgl, wgl_host
from jepsen_tpu.testing import corpus, perturb_history, random_register_history

DEVICE_CASES = [c for c in corpus() if c.model.device_capable]


@pytest.mark.parametrize("case", DEVICE_CASES, ids=lambda c: c.name)
def test_corpus_device(case):
    res = wgl.check_history_device(case.model, case.history)
    assert res["valid"] == case.valid, res


def test_random_valid_histories_device():
    rng = random.Random(11)
    for _ in range(10):
        h = random_register_history(rng, n_ops=30, n_procs=4, crash_p=0.15)
        res = wgl.check_history_device(CasRegister(init=0), h)
        assert res["valid"] is True, res


def test_perturbed_histories_agree_with_host():
    rng = random.Random(12)
    agree = disagree = 0
    for _ in range(12):
        h = perturb_history(
            rng, random_register_history(rng, n_ops=24, n_procs=3, crash_p=0.1)
        )
        model = CasRegister(init=0)
        host = wgl_host.check_history_host(model, h)
        dev = wgl.check_history_device(model, h)
        assert dev["valid"] == host["valid"], (dev, host)
        agree += 1
        disagree += host["valid"] is False
    assert agree == 12
    assert disagree > 0  # perturbation must actually produce invalid cases


def test_frontier_escalation_path():
    # A tiny frontier cap forces the overflow -> larger-capacity retry path.
    rng = random.Random(13)
    h = random_register_history(rng, n_ops=24, n_procs=6, crash_p=0.3)
    model = CasRegister(init=0)
    res = wgl.check_history_device(model, h, f_schedule=(2, 4096))
    assert res["valid"] is True
    assert len(res["attempts"]) >= 1


def test_unified_dispatch():
    rng = random.Random(14)
    h = random_register_history(rng, n_ops=20, n_procs=3)
    model = CasRegister(init=0)
    assert wgl.check_history(model, h, backend="host")["valid"] is True
    auto = wgl.check_history(model, h, backend="auto")
    assert auto["valid"] is True
    # auto prefers the native C engine when available, else the device.
    assert auto.get("backend") == "native" or auto.get("device")
    dev = wgl.check_history(model, h, backend="device")
    assert dev["valid"] is True and dev.get("device")


def test_host_fallback_for_host_only_models():
    from jepsen_tpu.models import FIFOQueue
    from jepsen_tpu.testing import build

    h = build(
        [
            ("invoke", 0, "enqueue", 1),
            ("ok", 0, "enqueue", 1),
            ("invoke", 0, "dequeue", None),
            ("ok", 0, "dequeue", 1),
        ]
    )
    res = wgl.check_history(FIFOQueue(), h, backend="auto")
    assert res["valid"] is True and not res.get("device")


def test_many_open_ops_returns_unknown():
    rng = random.Random(15)
    h = random_register_history(rng, n_ops=30, n_procs=4, crash_p=0.9)
    res = wgl.check_history_device(CasRegister(init=0), h, max_open=1)
    assert res["valid"] in (True, "unknown")


class TestTwoStageCompaction:
    def test_wintab_fallback_matches_host(self, monkeypatch):
        """Shrink the sliding-window-table budget so the kernel takes
        the element-gather fallback, and check differential agreement
        (the guard that keeps 1M-op histories from materializing a
        chip-sized table)."""
        import random

        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.ops import wgl, wgl_host
        from jepsen_tpu.ops.encode import encode_history
        from jepsen_tpu.testing import perturb_history, random_register_history

        monkeypatch.setattr(wgl, "WINTAB_MAX_BYTES", 0)
        wgl._build_kernel.cache_clear()
        try:
            model = CasRegister(init=0)
            rng = random.Random(23)
            for i in range(6):
                h = random_register_history(
                    rng, n_ops=30, n_procs=4, cas=True, crash_p=0.05)
                if i % 2:
                    h = perturb_history(rng, h)
                dev = wgl.check_encoded_device(
                    encode_history(model, h), f_schedule=(16, 64))
                host = wgl_host.check_history_host(model, h)
                if dev["valid"] == "unknown":
                    continue
                assert dev["valid"] == host["valid"], (i, dev, host)
        finally:
            wgl._build_kernel.cache_clear()

    def test_two_stage_matches_host(self, monkeypatch):
        """Force the big-M pre-compaction path on tiny shapes and check
        differential agreement with the host oracle."""
        import random

        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.ops import wgl, wgl_host
        from jepsen_tpu.ops.encode import encode_history
        from jepsen_tpu.testing import perturb_history, random_register_history

        monkeypatch.setattr(wgl, "BIG_M_THRESHOLD", 256)
        wgl._build_kernel.cache_clear()
        try:
            model = CasRegister(init=0)
            rng = random.Random(21)
            for i in range(6):
                h = random_register_history(
                    rng, n_ops=24, n_procs=4, cas=True, crash_p=0.08)
                if i % 2:
                    h = perturb_history(rng, h)
                dev = wgl.check_encoded_device(
                    encode_history(model, h), f_schedule=(16, 64))
                host = wgl_host.check_history_host(model, h)
                if dev["valid"] == "unknown":
                    continue  # tiny schedule may exhaust; soundness only
                assert dev["valid"] == host["valid"], (i, dev, host)
        finally:
            wgl._build_kernel.cache_clear()


class TestOptimisticBeam:
    def test_optimistic_agrees_with_host(self):
        """Force the optimistic beam phase on small histories: accepts are
        sound, refutations fall back to the exhaustive search, so verdicts
        must match the host oracle exactly."""
        import random

        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.ops import wgl, wgl_host
        from jepsen_tpu.ops.encode import encode_history
        from jepsen_tpu.testing import perturb_history, random_register_history

        model = CasRegister(init=0)
        rng = random.Random(31)
        for i in range(10):
            h = random_register_history(
                rng, n_ops=40, n_procs=5, cas=True, crash_p=0.08)
            if i % 2:
                h = perturb_history(rng, h)
            dev = wgl.check_encoded_device(
                encode_history(model, h), f_schedule=(16, 64, 256),
                optimistic=True)
            host = wgl_host.check_history_host(model, h)
            assert dev["valid"] == host["valid"], (i, dev, host)


class TestDiskCheckpoint:
    """Mid-run checkpoint/resume of the device search (the reference
    restarts failed multi-hour analyses from zero; checker.clj:210-213)."""

    def _enc(self, seed=11, n_ops=120):
        import random

        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.ops.encode import encode_history
        from jepsen_tpu.testing import random_register_history

        model = CasRegister(init=0)
        h = random_register_history(random.Random(seed), n_ops=n_ops,
                                    n_procs=5, cas=True, crash_p=0.05)
        return model, h, encode_history(model, h)

    def test_checkpoint_written_and_cleaned(self, tmp_path):
        from jepsen_tpu.ops import wgl, wgl_host

        model, h, enc = self._enc()
        ck = str(tmp_path / "search.npz")
        chunks = []
        res = wgl.check_encoded_device(
            enc, levels_per_call=10, checkpoint_path=ck,
            chunk_callback=chunks.append)
        assert res["valid"] == wgl_host.check_history_host(model, h)["valid"]
        assert len(chunks) >= 2  # really ran chunked
        assert all(c["level"] >= 0 and "wall_s" in c for c in chunks)
        import os

        assert not os.path.exists(ck)  # deleted on a definite verdict

    def test_interrupt_and_resume(self, tmp_path):
        import os

        import pytest

        from jepsen_tpu.ops import wgl, wgl_host

        model, h, enc = self._enc(seed=13)
        ck = str(tmp_path / "search.npz")

        calls = [0]

        def bomb(info):
            calls[0] += 1
            if calls[0] == 2:
                raise KeyboardInterrupt  # simulate an interrupted run

        with pytest.raises(KeyboardInterrupt):
            wgl.check_encoded_device(enc, levels_per_call=5,
                                     checkpoint_path=ck,
                                     chunk_callback=bomb)
        assert os.path.exists(ck)  # partial state survived

        res = wgl.check_encoded_device(enc, levels_per_call=5,
                                       checkpoint_path=ck)
        assert res.get("resumed_from_level", 0) > 0
        assert res["valid"] == wgl_host.check_history_host(model, h)["valid"]
        assert not os.path.exists(ck)

    def test_stale_checkpoint_ignored(self, tmp_path):
        import os

        from jepsen_tpu.ops import wgl, wgl_host

        model1, h1, enc1 = self._enc(seed=17)
        ck = str(tmp_path / "search.npz")

        def bomb(info):
            raise KeyboardInterrupt

        try:
            wgl.check_encoded_device(enc1, levels_per_call=5,
                                     checkpoint_path=ck,
                                     chunk_callback=bomb)
        except KeyboardInterrupt:
            pass
        assert os.path.exists(ck)
        # A DIFFERENT history with the same path: fingerprint mismatch,
        # search starts from scratch and is still correct.
        model2, h2, enc2 = self._enc(seed=23)
        res = wgl.check_encoded_device(enc2, checkpoint_path=ck)
        assert "resumed_from_level" not in res
        assert res["valid"] == wgl_host.check_history_host(
            model2, h2)["valid"]

    def test_truncated_beam_checkpoint_cannot_poison_full_search(
            self, tmp_path):
        """A lossy beam frontier must never seed the exhaustive search
        (it could never refute); only its lossless companion may."""
        import numpy as np

        from jepsen_tpu.ops import wgl, wgl_host
        from jepsen_tpu.testing import perturb_history
        import random

        model, h, _ = self._enc(seed=29)
        h = perturb_history(random.Random(1), h)  # likely invalid
        from jepsen_tpu.ops.encode import encode_history

        enc = encode_history(model, h)
        want = wgl_host.check_history_host(model, h)["valid"]
        plan = wgl.plan_device(enc)
        W, KO, S, _ND, _NO = plan.dims
        ck = str(tmp_path / "search.npz")
        fp = wgl._enc_fingerprint(enc, plan)
        # Fabricate an interrupted TRUNCATED beam: a lossy current
        # frontier (empty, mid-history) + the true lossless level-0
        # frontier as companion.
        lossless = wgl.initial_frontier(16, W, KO, S, plan.init_state)
        lossy = tuple(np.asarray(a) for a in lossless[:-1]) + (
            np.int32(max(enc.n // 2, 1)),)
        wgl._save_search_checkpoint(ck, fp, "beam", True, lossy,
                                    lossless_fr=lossless)
        res = wgl.check_encoded_device(enc, checkpoint_path=ck,
                                       optimistic=False)
        assert res["valid"] == want  # not poisoned into 'unknown'

    def test_device_refutation_carries_stuck_configs(self):
        """A device-kernel False verdict includes the final frontier's
        configurations with per-op reasons (the linear.svg seam)."""
        import random

        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.ops import wgl
        from jepsen_tpu.ops.encode import encode_history
        from jepsen_tpu.testing import (perturb_history,
                                        random_register_history)

        model = CasRegister(init=0)
        rng = random.Random(3)
        seen = 0
        for _ in range(30):
            h = perturb_history(rng, random_register_history(
                rng, n_ops=40, n_procs=4, cas=True, crash_p=0.08))
            enc = encode_history(model, h)
            res = wgl.check_encoded_device(enc, optimistic=False)
            if res["valid"] is not False:
                continue
            seen += 1
            stuck = res.get("stuck_configs")
            assert stuck, res
            for cfg in stuck:
                # Device BFS levels count BOTH determinate and open
                # linearizations, one per level.
                assert len(cfg["linearized"]) == res["max_linearized"], (
                    cfg, res)
                assert cfg["pending"] and all(
                    p.get("why") for p in cfg["pending"])
            if seen >= 3:
                break
        assert seen >= 2

    def test_wide_lossless_companion_dropped_not_crashed(self, tmp_path):
        """A lossless_fr WIDER than the resuming run's top capacity (the
        beam de-escalated after truncating at a larger F) cannot seed any
        kernel — it must be dropped, not fed to a smaller static-F
        kernel."""
        import numpy as np

        from jepsen_tpu.ops import wgl, wgl_host

        model, h, enc = self._enc(seed=41)
        want = wgl_host.check_history_host(model, h)["valid"]
        plan = wgl.plan_device(enc)
        W, KO, S, _ND, _NO = plan.dims
        ck = str(tmp_path / "search.npz")
        fp = wgl._enc_fingerprint(enc, plan)
        sched = [16, 32]
        # fr fits the schedule; the lossless companion is wider than its
        # top capacity (as after a 64-wide truncation + de-escalation).
        narrow = wgl.initial_frontier(16, W, KO, S, plan.init_state)
        lossy = tuple(np.asarray(a) for a in narrow[:-1]) + (
            np.int32(max(enc.n // 2, 1)),)
        wide = wgl.initial_frontier(64, W, KO, S, plan.init_state)
        wgl._save_search_checkpoint(ck, fp, "beam", True, lossy,
                                    lossless_fr=wide)
        res = wgl.check_encoded_device(enc, f_schedule=sched,
                                       checkpoint_path=ck,
                                       optimistic=False)
        assert res["valid"] == want

    def test_sharded_checkpoint_resumes_in_optimistic_mode(self, tmp_path):
        """A checkpoint written by the sharded driver (phase 'sharded',
        always lossless) must survive the engine switch: an optimistic
        single-chip run resumes from it instead of restarting at 0."""
        import os

        import pytest

        from jepsen_tpu.ops import wgl, wgl_host

        model, h, enc = self._enc(seed=37)
        ck = str(tmp_path / "search.npz")

        calls = [0]

        def bomb(info):
            calls[0] += 1
            if calls[0] == 2:
                raise KeyboardInterrupt

        with pytest.raises(KeyboardInterrupt):
            wgl.check_encoded_device(enc, levels_per_call=5,
                                     checkpoint_path=ck, optimistic=False,
                                     chunk_callback=bomb)
        assert os.path.exists(ck)
        # Rewrite the genuine interrupted frontier as the sharded
        # driver would have saved it.
        plan = wgl.plan_device(enc)
        fp = wgl._enc_fingerprint(enc, plan)
        disk = wgl._load_search_checkpoint(ck, fp)
        assert disk is not None
        resumed_level = int(disk["fr"][-1])
        assert resumed_level > 0
        wgl._save_search_checkpoint(ck, fp, "sharded", False, disk["fr"])

        chunks = []
        res = wgl.check_encoded_device(enc, levels_per_call=5,
                                       checkpoint_path=ck, optimistic=True,
                                       chunk_callback=chunks.append)
        assert res["valid"] == wgl_host.check_history_host(model, h)["valid"]
        # The search never revisited the already-exact prefix.
        assert chunks and min(c["level"] for c in chunks) >= resumed_level


class TestCompetition:
    """The :competition analysis strategy (checker.clj:196-200): native
    DFS raced against the device BFS, first definite verdict wins."""

    def _hist(self, seed, n_ops=150, perturb=False):
        import random

        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.testing import (perturb_history,
                                        random_register_history)

        rng = random.Random(seed)
        h = random_register_history(rng, n_ops=n_ops, n_procs=5,
                                    cas=True, crash_p=0.05)
        if perturb:
            h = perturb_history(rng, h)
        return CasRegister(init=0), h

    def test_verdicts_match_oracle(self):
        from jepsen_tpu.ops import wgl, wgl_host

        seen_engines = set()
        for seed in range(8):
            model, h = self._hist(seed, perturb=seed % 2 == 1)
            want = wgl_host.check_history_host(model, h)["valid"]
            got = wgl.check_history(model, h, backend="competition")
            assert got["valid"] == want, (seed, got)
            assert got["backend"] in ("competition", "host")
            if got["backend"] == "competition":
                seen_engines.add(got["engine"])
        assert seen_engines, "competition never decided anything"

    def test_device_wins_when_native_unavailable(self, monkeypatch):
        """With the native engine knocked out, the device side still
        crosses the line."""
        from jepsen_tpu.ops import wgl, wgl_c, wgl_host

        monkeypatch.setattr(wgl_c, "check_encoded_native",
                            lambda enc, **kw: None)
        model, h = self._hist(3)
        want = wgl_host.check_history_host(model, h)["valid"]
        got = wgl.check_history(model, h, backend="competition")
        assert got["valid"] == want
        assert got.get("engine") == "device"

    def test_native_wins_when_device_stalls(self, monkeypatch):
        """With the device side forced to 'unknown' (empty capacity
        schedule), the native verdict is taken."""
        from jepsen_tpu.ops import wgl, wgl_host

        model, h = self._hist(5)
        want = wgl_host.check_history_host(model, h)["valid"]
        got = wgl.check_history(model, h, backend="competition",
                                f_schedule=())
        assert got["valid"] == want
        assert got.get("engine") == "native"

    def test_checker_dispatch(self):
        """checker_backend=competition rides the test map into the
        linearizable checker."""
        from jepsen_tpu import checker as C
        from jepsen_tpu.history import History, Op
        from jepsen_tpu.models import CasRegister

        def o(typ, p, f, value, t):
            return Op.from_dict({"type": typ, "process": p, "f": f,
                                 "value": value, "time": t})

        h = History([
            o("invoke", 0, "write", 1, 0), o("ok", 0, "write", 1, 1),
            o("invoke", 1, "read", None, 2), o("ok", 1, "read", 1, 3),
        ], reindex=True)
        chk = C.linearizable(model=CasRegister(init=0))
        res = chk.check({"checker_backend": "competition"}, h, {})
        assert res["valid"] is True


def test_multiword_open_sets_device_vs_native():
    """KO >= 2 (open-slot space past one 32-bit word): the candidate
    pre-selection's arithmetic one-hot masks must place open bits in
    the right word. Small histories PADDED to a KO=2 shape keep the
    compile cheap; differential against the native engine."""
    import random

    from jepsen_tpu.models import CasRegister
    from jepsen_tpu.ops import wgl, wgl_c
    from jepsen_tpu.ops.encode import encode_history
    from jepsen_tpu.testing import perturb_history, random_register_history

    model = CasRegister(init=0)
    rng = random.Random(91)
    exercised = word1 = 0
    for i in range(4):
        # Dense crashes so nO exceeds 32: open bits must actually LAND
        # in the second word, not just pad it with zeros. Histories are
        # valid by construction — crash-heavy REFUTATIONS explode the
        # open powerset and take minutes on the CPU backend, while a
        # misplaced word-1 bit corrupts accepts just as surely.
        h = random_register_history(rng, n_ops=80, n_procs=4,
                                    cas=True, crash_p=0.8)
        enc = encode_history(model, h)
        n_open = int(enc.skippable.sum())
        word1 += n_open > 32
        nat = wgl_c.check_encoded_native(enc)
        if nat is None or nat["valid"] == "unknown":
            continue
        assert nat["valid"] is True  # valid by construction
        # ONE shared shape bucket with a two-word open set.
        # Few capacity rungs: each rung is a separate CPU compile.
        dev = wgl.check_encoded_device(enc, pad_to=(64, 2, 128, 64),
                                       f_schedule=(64, 1024, 8192))
        if dev["valid"] == "unknown":
            continue
        assert dev["valid"] == nat["valid"], (i, dev, nat)
        exercised += 1
    assert exercised >= 3, "too few KO=2 decisions reached"
    assert word1 >= 3, "open bits never reached the second word"
