"""Differential tests: device WGL kernel vs host oracle + golden corpus.

This is the fourth test tier SURVEY.md §4 calls for — CPU-checker vs
TPU-checker agreement on valid AND invalid histories (run on the CPU
backend here; same XLA program runs on the chip).
"""

import random

import pytest

from jepsen_tpu.models import CasRegister
from jepsen_tpu.ops import wgl, wgl_host
from jepsen_tpu.testing import corpus, perturb_history, random_register_history

DEVICE_CASES = [c for c in corpus() if c.model.device_capable]


@pytest.mark.parametrize("case", DEVICE_CASES, ids=lambda c: c.name)
def test_corpus_device(case):
    res = wgl.check_history_device(case.model, case.history)
    assert res["valid"] == case.valid, res


def test_random_valid_histories_device():
    rng = random.Random(11)
    for _ in range(10):
        h = random_register_history(rng, n_ops=30, n_procs=4, crash_p=0.15)
        res = wgl.check_history_device(CasRegister(init=0), h)
        assert res["valid"] is True, res


def test_perturbed_histories_agree_with_host():
    rng = random.Random(12)
    agree = disagree = 0
    for _ in range(12):
        h = perturb_history(
            rng, random_register_history(rng, n_ops=24, n_procs=3, crash_p=0.1)
        )
        model = CasRegister(init=0)
        host = wgl_host.check_history_host(model, h)
        dev = wgl.check_history_device(model, h)
        assert dev["valid"] == host["valid"], (dev, host)
        agree += 1
        disagree += host["valid"] is False
    assert agree == 12
    assert disagree > 0  # perturbation must actually produce invalid cases


def test_frontier_escalation_path():
    # A tiny frontier cap forces the overflow -> larger-capacity retry path.
    rng = random.Random(13)
    h = random_register_history(rng, n_ops=24, n_procs=6, crash_p=0.3)
    model = CasRegister(init=0)
    res = wgl.check_history_device(model, h, f_schedule=(2, 4096))
    assert res["valid"] is True
    assert len(res["attempts"]) >= 1


def test_unified_dispatch():
    rng = random.Random(14)
    h = random_register_history(rng, n_ops=20, n_procs=3)
    model = CasRegister(init=0)
    assert wgl.check_history(model, h, backend="host")["valid"] is True
    auto = wgl.check_history(model, h, backend="auto")
    assert auto["valid"] is True
    # auto prefers the native C engine when available, else the device.
    assert auto.get("backend") == "native" or auto.get("device")
    dev = wgl.check_history(model, h, backend="device")
    assert dev["valid"] is True and dev.get("device")


def test_host_fallback_for_host_only_models():
    from jepsen_tpu.models import FIFOQueue
    from jepsen_tpu.testing import build

    h = build(
        [
            ("invoke", 0, "enqueue", 1),
            ("ok", 0, "enqueue", 1),
            ("invoke", 0, "dequeue", None),
            ("ok", 0, "dequeue", 1),
        ]
    )
    res = wgl.check_history(FIFOQueue(), h, backend="auto")
    assert res["valid"] is True and not res.get("device")


def test_many_open_ops_returns_unknown():
    rng = random.Random(15)
    h = random_register_history(rng, n_ops=30, n_procs=4, crash_p=0.9)
    res = wgl.check_history_device(CasRegister(init=0), h, max_open=1)
    assert res["valid"] in (True, "unknown")


class TestTwoStageCompaction:
    def test_two_stage_matches_host(self, monkeypatch):
        """Force the big-M pre-compaction path on tiny shapes and check
        differential agreement with the host oracle."""
        import random

        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.ops import wgl, wgl_host
        from jepsen_tpu.ops.encode import encode_history
        from jepsen_tpu.testing import perturb_history, random_register_history

        monkeypatch.setattr(wgl, "BIG_M_THRESHOLD", 256)
        wgl._build_kernel.cache_clear()
        try:
            model = CasRegister(init=0)
            rng = random.Random(21)
            for i in range(6):
                h = random_register_history(
                    rng, n_ops=24, n_procs=4, cas=True, crash_p=0.08)
                if i % 2:
                    h = perturb_history(rng, h)
                dev = wgl.check_encoded_device(
                    encode_history(model, h), f_schedule=(16, 64))
                host = wgl_host.check_history_host(model, h)
                if dev["valid"] == "unknown":
                    continue  # tiny schedule may exhaust; soundness only
                assert dev["valid"] == host["valid"], (i, dev, host)
        finally:
            wgl._build_kernel.cache_clear()


class TestOptimisticBeam:
    def test_optimistic_agrees_with_host(self):
        """Force the optimistic beam phase on small histories: accepts are
        sound, refutations fall back to the exhaustive search, so verdicts
        must match the host oracle exactly."""
        import random

        from jepsen_tpu.models import CasRegister
        from jepsen_tpu.ops import wgl, wgl_host
        from jepsen_tpu.ops.encode import encode_history
        from jepsen_tpu.testing import perturb_history, random_register_history

        model = CasRegister(init=0)
        rng = random.Random(31)
        for i in range(10):
            h = random_register_history(
                rng, n_ops=40, n_procs=5, cas=True, crash_p=0.08)
            if i % 2:
                h = perturb_history(rng, h)
            dev = wgl.check_encoded_device(
                encode_history(model, h), f_schedule=(16, 64, 256),
                optimistic=True)
            host = wgl_host.check_history_host(model, h)
            assert dev["valid"] == host["valid"], (i, dev, host)
