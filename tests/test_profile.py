"""Roofline profiler: synthetic attribution (pure host-side), CPU
consistency of the attribution with the kernel's verdict, the
zero-overhead disabled path, and the store/web integration."""

from __future__ import annotations

import json
import random

import pytest

from jepsen_tpu.models import CasRegister
from jepsen_tpu.telemetry import Registry, profile


def _chunk(reg, level0, level, F, wall_s, stage="execute"):
    reg.event("wgl_chunk", level0=level0, level=level, F=F,
              wall_s=wall_s, stage=stage)


def _levels(reg, levels, F, frontier):
    for lv in levels:
        reg.event("wgl_level", level=lv, frontier=frontier,
                  expanded=frontier * 2, overflow=False, F=F,
                  completed=True)


class TestSyntheticAttribution:
    """Hand-built registries with known arithmetic: the classifier's
    outputs are checked against closed-form expectations."""

    def test_bandwidth_bound_chunk(self):
        reg = Registry()
        # 10 levels, 1 GB floor each, 0.2 s/level at 10 GB/s peak:
        # t_bw = 0.1 s >> t_lat = 0.2 ms -> bandwidth-bound, util 0.5.
        _chunk(reg, 0, 10, 1024, 2.0)
        _levels(reg, range(1, 11), 1024, frontier=512)
        out = profile.attribute(reg, byte_floor=lambda F: 10 ** 9,
                                copy_bw_gbs=10.0)
        (c,) = out["device"]["chunks"]
        assert c["bound"] == "bandwidth"
        assert c["util"] == 0.5
        assert c["achieved_gbs"] == 5.0
        assert c["occupancy"] == 0.5
        assert c["bytes_floor"] == 10 ** 10
        assert out["device"]["summary"]["dominant_bound"] == "bandwidth"

    def test_latency_bound_chunk(self):
        reg = Registry()
        # Tiny byte floor, near-empty frontier: fixed overhead explains
        # the wall, not streaming.
        _chunk(reg, 0, 100, 8192, 0.05)  # 0.5 ms/level
        _levels(reg, range(1, 101), 8192, frontier=4)
        out = profile.attribute(reg, byte_floor=lambda F: 10 ** 4,
                                copy_bw_gbs=100.0)
        (c,) = out["device"]["chunks"]
        assert c["bound"] == "latency"
        assert c["latency_share"] == pytest.approx(0.4)
        assert c["occupancy"] < 0.01

    def test_compile_chunk_attributed_separately(self):
        reg = Registry()
        _chunk(reg, 0, 5, 16, 30.0, stage="compile")
        _chunk(reg, 5, 10, 16, 0.01)
        _levels(reg, range(1, 11), 16, frontier=8)
        out = profile.attribute(reg, byte_floor=lambda F: 10 ** 6,
                                copy_bw_gbs=100.0)
        bounds = [c["bound"] for c in out["device"]["chunks"]]
        assert bounds[0] == "compile"
        s = out["device"]["summary"]
        assert s["bound_wall_s"]["compile"] == 30.0
        # Compile wall never pollutes the achieved-GB/s figure.
        assert s["achieved_gbs"] == pytest.approx(
            10 ** 6 * 5 / 0.01 / 1e9, rel=1e-3)

    def test_occupancy_fallback_without_bandwidth(self):
        reg = Registry()
        _chunk(reg, 0, 10, 64, 0.1)
        _levels(reg, range(1, 11), 64, frontier=32)  # occ 0.5 >= 0.25
        _chunk(reg, 10, 20, 64, 0.1)
        _levels(reg, range(11, 21), 64, frontier=2)  # occ 0.03 < 0.25
        out = profile.attribute(reg, byte_floor=lambda F: 10 ** 6)
        c1, c2 = out["device"]["chunks"]
        assert c1["bound"] == "bandwidth"
        assert c2["bound"] == "latency"

    def test_zero_level_overflow_chunk(self):
        reg = Registry()
        _chunk(reg, 7, 7, 16, 0.02)  # an attempt that kept nothing
        out = profile.attribute(reg, byte_floor=lambda F: 10 ** 6)
        (c,) = out["device"]["chunks"]
        assert c["bound"] == "overflow"
        assert c["levels"] == 0

    def test_rung_aggregation_and_eliding(self):
        reg = Registry()
        for i in range(100):
            _chunk(reg, i * 2, i * 2 + 2, 128, 0.01)
        _levels(reg, range(1, 201), 128, frontier=64)
        out = profile.attribute(reg, byte_floor=lambda F: 10 ** 6,
                                copy_bw_gbs=1.0, max_chunks=10)
        d = out["device"]
        assert len(d["chunks"]) == 10
        assert d["summary"]["chunks_elided"] == 90
        (rung,) = d["rungs"]
        assert rung["F"] == 128
        assert rung["levels"] == 200  # aggregation sees ALL chunks
        assert rung["chunks"] == 100

    def test_empty_registry_attributes_nothing(self):
        assert profile.attribute(Registry()) == {}

    def test_batch_rung_attribution(self):
        reg = Registry()
        for i in range(4):
            reg.event("wgl_batch_chunk", F=256, chunk=i + 1,
                      active=8 - 2 * i, batch=8, level_max=i * 100,
                      wall_s=0.1 * (i + 1))
        reg.event("wgl_batch_rung", F=256, members=8, calls=4,
                  wall_s=0.4, decided=5, overflowed=3, lossy=False)
        reg.event("wgl_rebatch", from_F=256, to_F=1024, members=3,
                  level_min=10, level_max=90)
        out = profile.attribute(reg)
        b = out["batch"]
        (rung,) = b["rungs"]
        assert rung["decided"] == 5 and rung["overflowed"] == 3
        assert rung["occupancy_final"] == 0.25  # 2 of 8 still searching
        assert b["escalations"] == [
            {"from_F": 256, "to_F": 1024, "members": 3}]

    def test_sharded_interconnect_share(self):
        """Legacy recordings (allgather_bytes only, no exchange field)
        still attribute — the mode defaults to allgather and the old
        total key keeps reading."""
        reg = Registry()
        reg.event("wgl_sharded_chunk", level=10, F=128, n_shards=8,
                  global_capacity=1024, count=500, frontier_max=600,
                  wall_s=0.5, allgather_bytes=4_000_000)
        reg.event("wgl_sharded_chunk", level=20, F=128, n_shards=8,
                  global_capacity=1024, count=400, frontier_max=600,
                  wall_s=0.4, allgather_bytes=4_000_000)
        out = profile.attribute(reg, byte_floor=lambda F, **kw: 600_000)
        assert out["sharded"]["exchange"] == "allgather"
        ic = out["sharded"]["interconnect"]
        assert ic["allgather_bytes_total"] == 8_000_000
        assert ic["exchange_bytes_total"] == 8_000_000
        # 8 MB exchanged vs 20 levels x 0.6 MB compute floor.
        assert ic["share_of_traffic"] == pytest.approx(
            8e6 / (8e6 + 12e6), abs=1e-4)

    def test_sharded_partitioned_exchange_share(self):
        """New-style recordings: exchange mode + exchange_bytes + the
        per-shard max/min occupancy ride each chunk; the mode reaches
        the byte-floor model as a keyword."""
        reg = Registry()
        seen_kw = {}
        reg.event("wgl_sharded_chunk", level=10, F=128, n_shards=8,
                  global_capacity=1024, count=500, count_max=90,
                  count_min=40, frontier_max=600, wall_s=0.5,
                  exchange="alltoall", exchange_bytes=500_000)
        reg.event("wgl_sharded_chunk", level=20, F=128, n_shards=8,
                  global_capacity=1024, count=400, count_max=70,
                  count_min=30, frontier_max=600, wall_s=0.4,
                  exchange="alltoall", exchange_bytes=500_000)

        def floor(F, **kw):
            seen_kw.update(kw)
            return 600_000

        out = profile.attribute(reg, byte_floor=floor)
        sh = out["sharded"]
        assert sh["exchange"] == "alltoall"
        assert seen_kw.get("exchange") == "alltoall"
        ic = sh["interconnect"]
        assert ic["exchange_bytes_total"] == 1_000_000
        assert ic["allgather_bytes_total"] == 1_000_000  # legacy alias
        assert ic["share_of_traffic"] == pytest.approx(
            1e6 / (1e6 + 12e6), abs=1e-4)
        assert sh["chunks"][-1]["count_max"] == 70
        assert sh["chunks"][-1]["count_min"] == 30


@pytest.mark.slow
class TestCpuConsistency:
    """Attribution must be consistent with the verdict the same run
    produced (the committed-verdict acceptance): one CPU WGL check with
    telemetry, attributed, cross-checked field by field. Shapes chosen
    to share the compiled bucket with tests/test_telemetry.py's
    telemetry-variant tests; compile-heavy, so slow-marked like them
    (the tier-1 baseline already runs ~800 s of the 870 s budget)."""

    @pytest.fixture(scope="class")
    def run(self):
        from jepsen_tpu.ops import wgl
        from jepsen_tpu.ops.encode import encode_history
        from jepsen_tpu.testing import random_register_history

        h = random_register_history(random.Random(11), n_ops=40,
                                    n_procs=4, crash_p=0.1)
        enc = encode_history(CasRegister(init=0), h)
        reg = Registry()
        res = wgl.check_encoded_device(enc, f_schedule=(1024,),
                                       metrics=reg)
        plan = wgl.plan_device(enc)
        return res, reg, plan

    def test_attribution_matches_verdict(self, run):
        res, reg, plan = run
        assert res["valid"] is True
        out = profile.attribute(reg, plan=plan, copy_bw_gbs=50.0)
        d = out["device"]
        # Every completed level is attributed exactly once.
        assert d["summary"]["levels"] == res["levels"]
        assert sum(r["levels"] for r in d["rungs"]) == res["levels"]
        # Chunk walls sum to the summary (and stay under the verdict's
        # total wall, which includes host driving).
        assert d["summary"]["wall_s"] == pytest.approx(
            sum(c["wall_s"] for c in d["chunks"]), abs=1e-3)
        assert d["summary"]["wall_s"] <= res["wall_s"] + 1e-6
        for c in d["chunks"]:
            assert c["bound"] in ("latency", "bandwidth", "compile",
                                  "overflow")
            if "occupancy" in c:
                assert 0 <= c["occupancy"] <= 1
            if "util" in c:
                assert 0 <= c["util"] <= 1
        # The byte model prices every executing chunk.
        assert all(c["bytes_floor"] > 0 for c in d["chunks"]
                   if c["levels"] > 0)

    def test_first_chunk_carries_compile_when_fresh(self, run):
        res, reg, plan = run
        chunks = reg.events("wgl_chunk")
        assert chunks, "driver recorded no chunk events"
        stages = {c["stage"] for c in chunks}
        assert stages <= {"compile", "execute"}

    def test_occupancy_consistent_with_frontier_series(self, run):
        res, reg, plan = run
        out = profile.attribute(reg, plan=plan)
        fmax = res["frontier_max"]
        for c in out["device"]["chunks"]:
            if "frontier_mean" in c:
                assert c["frontier_mean"] <= fmax


class TestDisabledPathZeroOverhead:
    @pytest.mark.slow
    def test_disabled_check_never_touches_telemetry(self, monkeypatch):
        """metrics=None ⇒ the driver's whole telemetry surface is dead
        code: the chunk-metrics helper and registry event recording are
        poisoned, the check still decides."""
        from jepsen_tpu.ops import wgl
        from jepsen_tpu.testing import random_register_history

        def _boom(*a, **k):
            raise AssertionError("telemetry touched on disabled path")

        monkeypatch.setattr(wgl, "_note_chunk_metrics", _boom)
        monkeypatch.setattr(Registry, "event", _boom)
        monkeypatch.setattr(Registry, "counter", _boom)
        h = random_register_history(random.Random(14), n_ops=20,
                                    n_procs=3, crash_p=0.1)
        res = wgl.check_history_device(CasRegister(init=0), h,
                                       f_schedule=(16, 128))
        assert res["valid"] in (True, False)

    def test_flight_phase_disabled_allocates_nothing(self):
        import tracemalloc

        from jepsen_tpu.telemetry import flight

        with flight.phase(None, "warm"):
            pass
        tracemalloc.start()
        before = tracemalloc.get_traced_memory()[0]
        for _ in range(10_000):
            with flight.phase(None, "leg"):
                pass
        after = tracemalloc.get_traced_memory()[0]
        tracemalloc.stop()
        # One shared nullcontext: no per-call objects survive the loop.
        assert after - before < 1024


class TestCaptureAndStore:
    def test_memory_watermarks_shape(self):
        marks = profile.memory_watermarks()
        # CPU backends may report nothing; when they do, the shape holds.
        for m in marks:
            assert "device" in m

    @pytest.mark.slow  # profiler start/stop initializes the backend
    def test_trace_capture_is_exception_proof(self, tmp_path):
        # Works (or degrades to None) regardless of backend support.
        with profile.trace_capture(tmp_path / "trace") as where:
            assert where is None or str(tmp_path) in where

    def test_store_profile_and_web_page(self, tmp_path):
        from pathlib import Path

        from jepsen_tpu import web

        reg = Registry()
        _chunk(reg, 0, 10, 64, 0.1)
        _levels(reg, range(1, 11), 64, frontier=32)
        test = {"name": "prof-test", "start-time": "20260803T000000",
                "store-root": str(tmp_path),
                "telemetry-registry": reg}
        p = profile.store_profile(test)
        doc = json.loads(open(p).read())
        assert doc["attribution"]["device"]["summary"]["levels"] == 10
        html = web._profile_page(Path(tmp_path))
        assert "prof-test" in html
        assert "Device search (roofline)" in html
        assert "profile.json" in html

    def test_store_profile_requires_store_and_registry(self, tmp_path):
        assert profile.store_profile({"telemetry-registry": None}) is None
        assert profile.store_profile(
            {"name": "x", "telemetry-registry": Registry()}) is None
