"""Nemesis + net + control tests: grudge algebra ports
(jepsen/test/jepsen/nemesis_test.clj:17-60), shell escaping
(control.clj:77-120), partitioner command generation against the dummy
remote, compose routing, and a partition scheduled through the threaded
interpreter showing up in nemesis_intervals."""

import pytest

from jepsen_tpu import control as c
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nem
from jepsen_tpu import net as jnet
from jepsen_tpu.generator import fixed_rand, interpreter
from jepsen_tpu.util import nemesis_intervals
from jepsen_tpu.workloads import noop_test


class TestGrudges:
    def test_bisect(self):
        assert nem.bisect([]) == [[], []]
        assert nem.bisect([1]) == [[], [1]]
        assert nem.bisect([1, 2, 3, 4]) == [[1, 2], [3, 4]]
        assert nem.bisect([1, 2, 3, 4, 5]) == [[1, 2], [3, 4, 5]]

    def test_complete_grudge(self):
        assert nem.complete_grudge(nem.bisect([1, 2, 3, 4, 5])) == {
            1: {3, 4, 5},
            2: {3, 4, 5},
            3: {1, 2},
            4: {1, 2},
            5: {1, 2},
        }

    def test_bridge(self):
        assert nem.bridge([1, 2, 3, 4, 5]) == {
            1: {4, 5},
            2: {4, 5},
            4: {1, 2},
            5: {1, 2},
        }

    def test_split_one(self):
        assert nem.split_one([1, 2, 3], loner=2) == [[2], [1, 3]]

    def test_majorities_ring(self):
        nodes = list(range(5))
        with fixed_rand(1):
            grudge = nem.majorities_ring(nodes)
        assert len(grudge) == len(nodes)
        assert set(grudge) == set(nodes)
        # Every node drops exactly n - majority = 2 others, never itself.
        for node, snubbed in grudge.items():
            assert len(snubbed) == 2
            assert node not in snubbed
        assert len({frozenset(v) for v in grudge.values()}) == len(nodes)


class TestEscape:
    def test_escape_rules(self):
        # control.clj:77-120
        assert c.escape(None) == ""
        assert c.escape("") == '""'
        assert c.escape("simple") == "simple"
        assert c.escape("has space") == '"has space"'
        assert c.escape('say "hi"') == '"say \\"hi\\""'
        assert c.escape("$HOME") == '"\\$HOME"'
        assert c.escape([1, "two words"]) == '1 "two words"'
        assert c.escape(c.Lit("a|b")) == "a|b"
        assert c.escape(">") == ">"


def dummy_test(nodes=("n1", "n2", "n3", "n4", "n5")):
    test = dict(noop_test())
    test["nodes"] = list(nodes)
    test["net"] = jnet.iptables()
    log: list = []
    remote = c.dummy(log, responses={
        r"getent ahosts (\S+)": lambda host, action: "10.0.0.1 STREAM x\n",
    })
    c.setup_sessions(test, remote)
    return test, log


class TestPartitioner:
    def test_partition_commands(self):
        test, log = dummy_test()
        p = nem.partitioner(lambda nodes: nem.complete_grudge(
            nem.bisect(list(nodes))))
        p = p.setup(test)
        res = p.invoke(test, {"type": "info", "f": "start", "value": None})
        assert res["value"][0] == "isolated"
        cmds = [cmd for _h, cmd in log]
        drops = [cmd for cmd in cmds if "-j DROP" in cmd]
        # 5 nodes partitioned -> every node snubs the other side.
        assert len(drops) == 5
        assert any("iptables -A INPUT -s" in cmd for cmd in drops)
        res = p.invoke(test, {"type": "info", "f": "stop", "value": None})
        assert res["value"] == "network-healed"
        flushes = [cmd for cmd in cmds if "iptables -F" in cmd]
        assert flushes  # heal flushed chains

    def test_explicit_grudge_value(self):
        test, log = dummy_test(("a", "b"))
        p = nem.partitioner().setup(test)
        p.invoke(test, {"type": "info", "f": "start",
                        "value": {"a": {"b"}}})
        drops = [(h, cmd) for h, cmd in log if "DROP" in cmd]
        assert len(drops) == 1
        assert drops[0][0] == "a"


class TestCompose:
    def test_compose_set_and_rename(self):
        class Recorder(nem.Nemesis, nem.Reflection):
            def __init__(self, fs):
                self._fs = fs
                self.ops = []

            def invoke(self, test, op):
                self.ops.append(op["f"])
                return dict(op)

            def fs(self):
                return list(self._fs)

        a = Recorder(["start", "stop"])
        b = Recorder(["kill"])
        composed = nem.compose({
            frozenset(["start", "stop"]): a,
            frozenset(["kill"]): b,
        }).setup({})
        composed.invoke({}, {"f": "start"})
        composed.invoke({}, {"f": "kill"})
        assert a.ops == ["start"]
        assert b.ops == ["kill"]
        with pytest.raises(ValueError):
            composed.invoke({}, {"f": "bogus"})
        # Renaming route: split-start -> start.
        a2 = Recorder(["start"])
        renamed = nem.compose({(("split-start", "start"),): a2}).setup({})
        out = renamed.invoke({}, {"f": "split-start"})
        assert a2.ops == ["start"]
        assert out["f"] == "split-start"

    def test_compose_collection_by_reflection(self):
        class R(nem.Nemesis, nem.Reflection):
            def __init__(self, fs):
                self._fs = fs
                self.ops = []

            def invoke(self, test, op):
                self.ops.append(op["f"])
                return dict(op)

            def fs(self):
                return list(self._fs)

        a, b = R(["start", "stop"]), R(["kill"])
        composed = nem.compose([a, b]).setup({})
        composed.invoke({}, {"f": "kill"})
        assert b.ops == ["kill"]


class TestInterpreterIntegration:
    def test_partition_through_interpreter(self):
        test, log = dummy_test()
        test["concurrency"] = 2
        test["client"] = test["client"]  # atom client from noop_test
        test["nemesis"] = nem.validate(
            nem.partition_random_halves().setup(test))
        test["generator"] = gen.phases(
            gen.nemesis(
                [{"type": "info", "f": "start"},
                 gen.sleep(0.05),
                 {"type": "info", "f": "stop"}],
                gen.limit(10, gen.repeat_({"f": "read"})),
            ),
        )
        history = interpreter.run(test)
        nem_ops = [o for o in history if o["process"] == "nemesis"]
        assert {o["f"] for o in nem_ops} == {"start", "stop"}
        from jepsen_tpu.history import History, Op

        h = History([Op.from_dict(o) for o in history], reindex=True)
        intervals = nemesis_intervals(h)
        assert len(intervals) >= 1
        cmds = [cmd for _h, cmd in log]
        assert any("DROP" in cmd for cmd in cmds)
        assert any("iptables -F" in cmd for cmd in cmds)


class TestHammerTime:
    def test_hammer_commands(self):
        test, log = dummy_test()
        h = nem.hammer_time("mydb").setup(test)
        with fixed_rand(2):
            res = h.invoke(test, {"type": "info", "f": "start"})
        assert res["type"] == "info"
        assert any("killall -s STOP mydb" in cmd for _n, cmd in log)
        res = h.invoke(test, {"type": "info", "f": "stop"})
        assert any("killall -s CONT mydb" in cmd for _n, cmd in log)
        # start while running -> refuses
        with fixed_rand(2):
            h.invoke(test, {"type": "info", "f": "start"})
            res = h.invoke(test, {"type": "info", "f": "start"})
        assert "already disrupting" in str(res["value"])
        h.invoke(test, {"type": "info", "f": "stop"})


class TestProcessPause:
    """Minimal process-pause nemesis for the simulated generator
    (nemesis/pause.py) — the online monitor's no-quiescence fault."""

    def test_pause_resume_tracks_paused_set(self):
        from jepsen_tpu.nemesis.pause import ProcessPause

        p = ProcessPause()
        res = p.invoke({}, {"type": "info", "f": "pause", "value": [0, 2]})
        assert res["value"] == [0, 2] and p.paused == {0, 2}
        res = p.invoke({}, {"type": "info", "f": "resume", "value": [2]})
        assert res["value"] == [0] and p.paused == {0}
        # resume with value None clears every pause.
        p.invoke({}, {"type": "info", "f": "pause", "value": [1]})
        res = p.invoke({}, {"type": "info", "f": "resume", "value": None})
        assert res["value"] == [] and p.paused == set()

    def test_default_targets_and_reflection(self):
        from jepsen_tpu.nemesis.pause import ProcessPause

        p = ProcessPause(processes=[3])
        p.invoke({}, {"type": "info", "f": "pause", "value": None})
        assert p.paused == {3}
        assert p.fs() == ["pause", "resume"]
        p.teardown({})
        assert p.paused == set()
        with pytest.raises(ValueError):
            p.invoke({}, {"type": "info", "f": "hammer"})

    def test_stalled_completions_split_latency(self):
        from jepsen_tpu.nemesis.pause import ProcessPause, \
            stalled_completions

        p = ProcessPause()
        complete = stalled_completions(p, latency=10, stall=5000)
        p.paused = {1}
        fast = complete(None, {"process": 0, "time": 100})
        slow = complete(None, {"process": 1, "time": 100})
        assert fast["type"] == slow["type"] == "ok"
        assert fast["time"] == 110
        assert slow["time"] == 5100
