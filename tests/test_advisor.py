"""Config advisor (python -m jepsen_tpu.advisor, ISSUE 13).

Every rule is pinned CLOSED-FORM: synthetic provenance / utilization /
trend inputs → the exact recommendation ids. The committed-artifact
test then pins the acceptance criterion — the advisor over the repo's
committed BENCH rounds (newest: the r13 CPU-box round) produces at
least three distinct recommendations.
"""

import glob
import json
import os

from jepsen_tpu import advisor, benchcmp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ids(recs):
    return [r["id"] for r in recs]


class TestInputGathering:
    def test_collect_provenance_unions_nested_blocks(self):
        doc = {
            "provenance": {"causes": {"max_configs": 2}},
            "service_streams": {
                "provenance": {"causes": {"max_configs": 1,
                                          "carry_lost": 4}}},
        }
        assert advisor.collect_provenance(doc) == {
            "max_configs": 3, "carry_lost": 4}
        assert advisor.collect_provenance({}) == {}

    def test_collect_gap_shares_takes_max_per_class(self):
        doc = {
            "device_gap_share": {"starved": 0.1},
            "batch_replay_large": {
                "smoke_8x10k": {"gap_share": {"starved": 0.6,
                                              "compiling": 0.2}}},
        }
        assert advisor.collect_gap_shares(doc) == {
            "starved": 0.6, "compiling": 0.2}

    def test_collect_backend_loads_takes_max_per_backend(self):
        doc = {
            "service_router": {
                "backend_loads": {
                    "backend-0": {"load": 520.0,
                                  "scheduler_backlog": 500},
                    "backend-1": {"load": 3.0}}},
            "nested": {"backend_loads": {"backend-0": 10.0}},
        }
        assert advisor.collect_backend_loads(doc) == {
            "backend-0": 520.0, "backend-1": 3.0}
        assert advisor.collect_backend_loads({}) == {}

    def test_collect_fleet_takes_worst_deficit(self):
        doc = {
            "service_router": {"fleet": {
                "configured_backends": 2, "live_backends": 2,
                "respawn_disabled": False, "respawn_gave_up": []}},
            "nested": {"fleet": {
                "configured_backends": 3, "live_backends": 1,
                "respawn_disabled": False,
                "respawn_gave_up": ["backend-2"]}},
        }
        got = advisor.collect_fleet(doc)
        # Worst capacity deficit wins: a healthy block must not mask
        # a degraded one.
        assert got["configured_backends"] == 3
        assert got["live_backends"] == 1
        assert advisor.collect_fleet({}) == {}

    def test_collect_skipped_legs(self):
        doc = {"mutex_5k": {"skipped": "device_slow_guard"},
               "elle_txn": {"value_s": 1.0},
               "batch_replay_large": {"skipped": "budget"}}
        got = advisor.collect_skipped_legs(doc)
        assert "mutex_5k (device_slow_guard)" in got
        assert "batch_replay_large (budget)" in got
        assert not any(s.startswith("elle") for s in got)


class TestRulesClosedForm:
    def test_capacity_bound_provenance_extends_schedule(self):
        recs = advisor.advise({"provenance": {
            "causes": {"overflow_top_rung": 8, "beam_loss": 2,
                       "max_configs": 3}}})
        assert ids(recs) == ["extend_f_schedule"]
        assert recs[0]["severity"] == "high"
        assert "f_schedule" in recs[0]["advice"]

    def test_budget_bound_provenance_raises_max_configs(self):
        recs = advisor.advise({"provenance": {
            "causes": {"max_configs": 2, "carry_lost": 9,
                       "overflow_top_rung": 1}}})
        assert ids(recs) == ["raise_max_configs"]
        assert "max_configs" in recs[0]["advice"]

    def test_fault_provenance_flags_infrastructure(self):
        recs = advisor.advise({"provenance": {
            "causes": {"worker_died": 3, "journal_gap": 1}}})
        assert set(ids(recs)) == {"failover_review",
                                  "journal_durability"}
        assert all(r["severity"] == "high" for r in recs)

    def test_gap_share_rules(self):
        recs = advisor.advise({"gap_share": {
            "host-stacking": 0.4, "starved": 0.3, "compiling": 0.26,
            "no-work": 0.04}})
        assert set(ids(recs)) == {"grow_batch_f", "feed_starved",
                                  "prewarm_compiles"}
        # Shares at/below the threshold never fire.
        assert advisor.advise({"gap_share": {"starved": 0.25}}) == []

    def test_latency_tail_rule(self):
        doc = {"online_10k": {"p50_decision_latency_s": 0.01,
                              "p99_decision_latency_s": 1.0}}
        recs = advisor.advise(doc)
        assert ids(recs) == ["latency_tail"]
        ev = recs[0]["evidence"]["online_10k"]
        assert ev["ratio"] == 100.0
        # A healthy tail is quiet.
        assert advisor.advise({"online_10k": {
            "p50_decision_latency_s": 0.01,
            "p99_decision_latency_s": 0.05}}) == []

    def test_rebalance_thresholds_match_router_policy(self):
        # The advisor's literals must track the router's live policy:
        # advice computed from stale thresholds would contradict what
        # the running router actually does.
        from jepsen_tpu.service.router import RouterConfig

        cfg = RouterConfig()
        assert advisor.REBALANCE_MIN_LOAD == cfg.rebalance_min_load
        assert advisor.REBALANCE_SKEW_RATIO == cfg.rebalance_ratio

    def test_rebalance_tenants_rule(self):
        # Skew past BOTH thresholds (absolute floor + ratio) fires the
        # router-PR rule; balanced or small loads stay quiet; a single
        # backend has nothing to rebalance onto.
        skew = {"service_router": {"backend_loads": {
            "backend-0": {"load": 600.0}, "backend-1": {"load": 4.0}}}}
        recs = advisor.advise(skew)
        assert ids(recs) == ["rebalance_tenants"]
        ev = recs[0]["evidence"]
        assert ev["src"] == "backend-0" and ev["dst"] == "backend-1"
        assert ev["ratio"] == 120.0
        # Below the absolute floor: a small skew is not worth the
        # migration's outage window.
        assert advisor.advise({"service_router": {"backend_loads": {
            "b0": {"load": 100.0}, "b1": {"load": 1.0}}}}) == []
        # Within the ratio: loaded but balanced.
        assert advisor.advise({"service_router": {"backend_loads": {
            "b0": {"load": 600.0}, "b1": {"load": 400.0}}}}) == []
        # One backend: nowhere to move.
        assert advisor.advise({"service_router": {"backend_loads": {
            "b0": {"load": 9000.0}}}}) == []

    def test_segment_plan_skew_rule(self):
        # The offline planner's largest (stream × key × segment) item
        # past 2x the mean per-worker share: the serial tail floors
        # the wall clock — fires with the cut-finer advice.
        skew = {"offline_segmented": {"plan": {
            "largest_item_ops": 5000, "mean_worker_share_ops": 1000.0,
            "largest_item_key": "'k3'", "n_streams": 4}}}
        recs = advisor.advise(skew)
        assert ids(recs) == ["segment_plan_skew"]
        ev = recs[0]["evidence"]
        assert ev["ratio"] == 5.0
        assert ev["largest_item_key"] == "'k3'"
        # At/below the 2x ratio: balanced enough, quiet.
        assert advisor.advise({"offline_segmented": {"plan": {
            "largest_item_ops": 2000,
            "mean_worker_share_ops": 1000.0}}}) == []
        # A zero share (empty plan) must not divide — quiet.
        assert advisor.advise({"offline_segmented": {"plan": {
            "largest_item_ops": 10,
            "mean_worker_share_ops": 0}}}) == []
        # Collector keeps the MOST skewed block, wherever nested.
        doc = {
            "offline_segmented": {"plan": {
                "largest_item_ops": 100,
                "mean_worker_share_ops": 100.0},
                "scale_10m": {"plan": {
                    "largest_item_ops": 900,
                    "mean_worker_share_ops": 100.0}}}}
        worst = advisor.collect_plan_skew(doc)
        assert worst["largest_item_ops"] == 900

    def test_respawn_backend_rule(self):
        # Below configured N with the flap circuit tripped: fires.
        gave_up = {"service_router": {"fleet": {
            "configured_backends": 2, "live_backends": 1,
            "respawn_disabled": False,
            "respawn_gave_up": ["backend-0"]}}}
        recs = advisor.advise(gave_up)
        assert ids(recs) == ["respawn_backend"]
        assert recs[0]["severity"] == "high"
        assert "backend-0" in recs[0]["advice"]
        # Below N with respawn DISABLED: fires too.
        disabled = {"service_router": {"fleet": {
            "configured_backends": 2, "live_backends": 1,
            "respawn_disabled": True, "respawn_gave_up": []}}}
        assert ids(advisor.advise(disabled)) == ["respawn_backend"]
        # Below N but the supervisor is still WORKING on it (not
        # disabled, nobody gave up): quiet — mirrors the router,
        # which is mid-heal and needs no operator.
        healing = {"service_router": {"fleet": {
            "configured_backends": 2, "live_backends": 1,
            "respawn_disabled": False, "respawn_gave_up": []}}}
        assert advisor.advise(healing) == []
        # At capacity: quiet regardless of history.
        whole = {"service_router": {"fleet": {
            "configured_backends": 2, "live_backends": 2,
            "respawn_disabled": True,
            "respawn_gave_up": ["backend-0"]}}}
        assert advisor.advise(whole) == []

    def test_slo_burn_rule(self):
        # A healthy-capacity fleet block (deficit 0 so respawn rule
        # stays quiet) whose FAST availability window burns past the
        # 14x page threshold: fires high.
        def fleet(slo):
            return {"service_router": {"fleet": {
                "configured_backends": 2, "live_backends": 2,
                "respawn_disabled": False, "respawn_gave_up": [],
                "slo": slo}}}

        hot = fleet({"availability_target": 0.999,
                     "latency_target_s": 30.0,
                     "windows": {
                         "fast": {"availability_burn_rate": 20.0,
                                  "latency_burn_rate": 0.0},
                         "slow": {"availability_burn_rate": 2.0,
                                  "latency_burn_rate": 0.0}}})
        recs = advisor.advise(hot)
        assert ids(recs) == ["slo_burn"]
        assert recs[0]["severity"] == "high"
        assert recs[0]["evidence"]["hot_windows"] == {
            "fast_availability": {"burn_rate": 20.0,
                                  "threshold": 14.0}}
        # A sustained latency leak past the SLOW threshold fires too.
        slow_leak = fleet({"windows": {
            "fast": {"latency_burn_rate": 1.0},
            "slow": {"latency_burn_rate": 7.0}}})
        recs2 = advisor.advise(slow_leak)
        assert ids(recs2) == ["slo_burn"]
        assert "slow_latency" in recs2[0]["evidence"]["hot_windows"]
        # Burning within budget (fast 13x, slow 5x): quiet.
        ok = fleet({"windows": {
            "fast": {"availability_burn_rate": 13.0,
                     "latency_burn_rate": 13.0},
            "slow": {"availability_burn_rate": 5.0,
                     "latency_burn_rate": 5.0}}})
        assert advisor.advise(ok) == []
        # No SLO block at all (federation off): quiet.
        assert advisor.advise(fleet(None)) == []

    def test_backend_underutilized_rule(self):
        def fleet(util):
            return {"service_router": {"fleet": {
                "configured_backends": len(util),
                "live_backends": len(util),
                "respawn_disabled": False, "respawn_gave_up": [],
                "utilization": util}}}

        # One cold backend while another runs hot: fires medium.
        recs = advisor.advise(fleet({
            "b0": {"utilization_pct": 91.0, "source": "backlog"},
            "b1": {"utilization_pct": 7.5, "source": "backlog"}}))
        assert ids(recs) == ["backend_underutilized"]
        assert recs[0]["severity"] == "medium"
        assert recs[0]["evidence"]["utilization_pct"] == {
            "b0": 91.0, "b1": 7.5}
        # Every backend cold: the fleet is idle — nothing to
        # rebalance onto, quiet.
        assert advisor.advise(fleet({
            "b0": {"utilization_pct": 3.0},
            "b1": {"utilization_pct": 5.0}})) == []
        # Balanced and busy: quiet.
        assert advisor.advise(fleet({
            "b0": {"utilization_pct": 80.0},
            "b1": {"utilization_pct": 75.0}})) == []
        # A single backend has no placement alternative: quiet.
        assert advisor.advise(fleet({
            "b0": {"utilization_pct": 2.0}})) == []
        # Unmeasurable utilization (no events scraped): quiet.
        assert advisor.advise(fleet({
            "b0": {"utilization_pct": None},
            "b1": {"utilization_pct": 90.0}})) == []

    def test_scrape_stale_rule(self):
        stale = {"service_router": {"fleet": {
            "configured_backends": 2, "live_backends": 2,
            "respawn_disabled": False, "respawn_gave_up": [],
            "stale_backends": ["backend-1"],
            "federation": {
                "backend-0": {"scrape_age_s": 0.1, "stale": False},
                "backend-1": {"scrape_age_s": 42.0, "stale": True}}}}}
        recs = advisor.advise(stale)
        assert ids(recs) == ["scrape_stale"]
        assert recs[0]["severity"] == "medium"
        assert recs[0]["evidence"]["scrape_age_s"] == {
            "backend-1": 42.0}
        assert "'backend-1'" in recs[0]["advice"]
        # Fresh scrapes everywhere: quiet.
        fresh = {"service_router": {"fleet": {
            "configured_backends": 2, "live_backends": 2,
            "respawn_disabled": False, "respawn_gave_up": [],
            "stale_backends": [],
            "federation": {
                "backend-0": {"scrape_age_s": 0.1, "stale": False}}}}}
        assert advisor.advise(fresh) == []

    def test_device_baseline_and_cadence_rules(self):
        recs = advisor.advise(
            {"mutex_5k": {"skipped": "device_slow_guard"}},
            rounds=[{"label": "r05", "metrics": {}},
                    {"label": "r13", "metrics": {}}])
        assert set(ids(recs)) == {"device_baseline_missing",
                                  "round_cadence"}
        # Adjacent rounds: no cadence complaint.
        recs2 = advisor.advise({}, rounds=[
            {"label": "r04", "metrics": {}},
            {"label": "r05", "metrics": {}}])
        assert recs2 == []

    def test_trend_regressions_rule(self):
        recs = advisor.advise({}, comparison={
            "from": "r12", "to": "r13",
            "regressions": ["value_s"]})
        assert ids(recs) == ["trend_regressions"]
        assert "value_s" in recs[0]["advice"]
        assert advisor.advise({}, comparison={
            "from": "a", "to": "b", "regressions": []}) == []

    def test_elle_device_fallbacks_rule(self):
        # Above the 20% share: the elle degradation codes (bucket
        # ceiling + dispatch OOM) recommend raising the bucket ceiling.
        recs = advisor.advise({"provenance": {
            "causes": {"elle_bucket_ceiling": 2, "elle_device_oom": 2,
                       "beam_loss": 3, "max_configs": 3}}})
        assert ids(recs) == ["elle_device_fallbacks"]
        assert recs[0]["severity"] == "medium"
        assert "bucket" in recs[0]["advice"]
        assert recs[0]["evidence"]["share_pct"] == 40.0
        # The threshold literal tracks the advisor policy constant.
        assert advisor.ELLE_FALLBACK_SHARE_THRESHOLD == 0.2
        # At/below the threshold the rule is silent.
        assert advisor.advise({"provenance": {
            "causes": {"elle_device_oom": 2, "beam_loss": 4,
                       "max_configs": 4}}}) == []

    def test_ingest_unmapped_rule(self):
        # Above the 5% share: unmapped trace lines recommend fixing
        # the adapter / column mapping.
        recs = advisor.advise({"provenance": {
            "causes": {"ingest_unmapped_op": 2, "beam_loss": 9,
                       "max_configs": 9}}})
        assert ids(recs) == ["ingest_unmapped"]
        assert recs[0]["severity"] == "medium"
        assert "column mapping" in recs[0]["advice"]
        assert recs[0]["evidence"]["share_pct"] == 10.0
        assert recs[0]["evidence"]["unmapped"] == 2
        # The threshold literal tracks the advisor policy constant.
        assert advisor.INGEST_UNMAPPED_SHARE_THRESHOLD == 0.05
        # At/below the threshold the rule is silent.
        assert advisor.advise({"provenance": {
            "causes": {"ingest_unmapped_op": 1, "beam_loss": 9,
                       "max_configs": 9, "elle_device_oom": 1}}}) == []

    def test_severity_ordering(self):
        recs = advisor.advise({
            "provenance": {"causes": {"journal_gap": 1}},
            "gap_share": {"starved": 0.5},
            "mutex_5k": {"skipped": "budget"},
        })
        sevs = [r["severity"] for r in recs]
        assert sevs == sorted(
            sevs, key=lambda s: {"high": 0, "medium": 1, "info": 2}[s])

    def test_clean_inputs_give_no_recommendations(self):
        assert advisor.advise({}) == []
        assert "no recommendations" in advisor.render([])


class TestCli:
    def test_main_over_synthetic_artifact(self, tmp_path, capsys):
        art = tmp_path / "BENCH_r98.json"
        art.write_text(json.dumps({
            "provenance": {"causes": {"overflow_top_rung": 10}},
            "mutex_5k": {"skipped": "device_slow_guard"},
        }))
        rc = advisor.main([str(art)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "extend_f_schedule" in out
        assert "device_baseline_missing" in out

    def test_main_json_mode(self, tmp_path, capsys):
        art = tmp_path / "BENCH_r99.json"
        art.write_text(json.dumps({
            "provenance": {"causes": {"max_configs": 1,
                                      "carry_lost": 5}}}))
        rc = advisor.main([str(art), "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["round"] == "r99"
        assert [r["id"] for r in doc["recommendations"]] == \
            ["raise_max_configs"]

    def test_main_refuses_nothing(self, tmp_path, capsys, monkeypatch):
        monkeypatch.chdir(tmp_path)
        rc = advisor.main([str(tmp_path / "missing.json")])
        assert rc == 2


class TestCommittedArtifacts:
    @staticmethod
    def _rec_ids(paths, capsys):
        rc = advisor.main(paths)
        out = capsys.readouterr().out
        assert rc == 0
        return {line.split("(id: ")[1].rstrip(")")
                for line in out.splitlines() if "(id: " in line}

    def test_committed_rounds_yield_three_recommendations(self, capsys):
        """The ISSUE-13 acceptance pin, frozen at its own epoch:
        `python -m jepsen_tpu.advisor` over the rounds THROUGH r13
        (the r13 CPU-box round: device legs behind
        BENCH_DEVICE_SLOW_S, a cadence gap vs r05, a CPU-vs-TPU trend
        break) produces at least 3 DISTINCT recommendations."""
        paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")),
                       key=benchcmp.round_sort_key)
        assert paths, "no committed BENCH rounds in the repo"
        thru_r13 = [p for p in paths
                    if benchcmp.round_sort_key(p) <=
                    benchcmp.round_sort_key("BENCH_r13.json")]
        rec_ids = self._rec_ids(thru_r13, capsys)
        assert len(rec_ids) >= 3, rec_ids

    def test_newest_round_closed_the_cadence_gap(self, capsys):
        """r14 was committed WITH its PR — exactly what the
        round_cadence rule asks for — so over the full trajectory the
        advisor gets QUIETER: the cadence complaint is gone while the
        real signals (trend regressions, missing device baseline)
        remain. The advisor rewarding fixed hygiene is the system
        working, not a coverage loss."""
        paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")),
                       key=benchcmp.round_sort_key)
        if benchcmp.round_sort_key(paths[-1]) <= \
                benchcmp.round_sort_key("BENCH_r13.json"):
            return  # trajectory not yet past r13 (re-anchored repo)
        rec_ids = self._rec_ids(paths, capsys)
        assert "round_cadence" not in rec_ids
        assert len(rec_ids) >= 2, rec_ids
