"""Distributed tracing for client operations.

The reference's dgraph suite exports OpenCensus spans to Jaeger and wraps
client calls in ``with-trace`` (dgraph/src/jepsen/dgraph/trace.clj:9-74).
This module provides the same capability framework-wide without external
collectors: nested spans with wall-clock bounds recorded per thread, an
in-memory collector, JSON-lines export into the store directory, and a
client wrapper that spans every invoke.

Trace-context propagation (the online monitor's decision-latency chain):
the thread-local ``span()`` stack cannot express a parent on another
thread, so cross-thread causality — an op invocation observed on the
interpreter thread, its segment decided on the scheduler worker, the
device chunk that decided it — uses two explicit seams instead:

- :meth:`Collector.record` logs an already-timed span with explicit
  ``trace_id``/``parent_id``/``stage`` linkage (stages: ``op`` →
  ``segment`` → ``member`` → ``oracle``). An op's trace id is
  ``op-<history index>``; a segment span carries the
  ``start_index``/``end_index`` range it covers, so an op trace resolves
  to the one segment span whose key matches and whose range contains its
  index, then down the parent ids.
- :func:`span_tags` pushes a thread-local tag dict that
  :func:`event_tags` returns; the kernel drivers (``ops/wgl.py``,
  ``parallel/batch.py``, ``parallel/frontier.py``) merge it into their
  per-chunk telemetry events, so device chunks link back to the
  dispatching ``oracle`` span (``trace_span=<span id>``) without any new
  plumbing through the kernel entry points. With no tags pushed,
  ``event_tags()`` returns one shared empty dict — the off path
  allocates nothing.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Optional

from . import client as jclient

# Cross-process trace propagation (the fleet's one-trace spine): the
# ndjson service client stamps these headers on every POST, the router
# forwards them on the proxied request (and onto /release → /adopt
# during a migration), and the backend's HTTP layer threads them into
# ``Service.submit`` — so one tenant's life across a kill-9 + live
# migration + resume is ONE trace id, joined to the in-process
# op → segment → member → oracle chain by stream name + index range
# (the same resolution rule op traces already use).
TRACE_HEADER = "X-Trace-Id"
PARENT_HEADER = "X-Parent-Span"


def trace_headers(trace_id: Optional[str],
                  parent_id: Optional[str] = None) -> dict:
    """Propagation headers for one outbound request ({} when no trace
    context is active — callers can always ``update`` with this)."""
    if not trace_id:
        return {}
    out = {TRACE_HEADER: str(trace_id)}
    if parent_id:
        out[PARENT_HEADER] = str(parent_id)
    return out


class Collector:
    """Thread-safe span sink."""

    def __init__(self):
        self.spans: list[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()
        # Span-id source: itertools.count.__next__ is atomic under the
        # GIL, so concurrent spans can never mint colliding ids (the old
        # len(self.spans) read outside the lock could).
        self._ids = itertools.count()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def mint_id(self) -> str:
        """A fresh span id (atomic; see _ids). Public so a caller can
        hand the id to children BEFORE the parent span is recorded —
        the online scheduler mints a segment span's id up front, emits
        member spans against it, then records the parent at fold time."""
        return f"{threading.get_ident():x}-{next(self._ids)}"

    def record(self, name: str, *, start_ns: int, end_ns: int,
               span_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               stage: Optional[str] = None, **attrs: Any) -> dict:
        """Log an already-timed span with explicit linkage (the
        cross-thread seam: op → segment → member → oracle stages of the
        online monitor's decision chain; see the module docstring)."""
        rec: dict = {
            "name": name,
            "span_id": span_id or self.mint_id(),
            "parent_id": parent_id,
            "thread": threading.current_thread().name,
            "start_ns": int(start_ns),
            "end_ns": int(end_ns),
            "duration_us": (int(end_ns) - int(start_ns)) // 1000,
        }
        if trace_id is not None:
            rec["trace_id"] = trace_id
        if stage is not None:
            rec["stage"] = stage
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            self.spans.append(rec)
        return rec

    @contextmanager
    def span(self, name: str, **attrs: Any):
        """Record a span around the body (trace.clj:9-30's with-trace)."""
        stack = self._stack()
        sid = self.mint_id()
        parent = stack[-1] if stack else None
        rec = {
            "name": name,
            "span_id": sid,
            "parent_id": parent,
            "thread": threading.current_thread().name,
            "start_ns": time.monotonic_ns(),
            **({"attrs": attrs} if attrs else {}),
        }
        stack.append(sid)
        try:
            yield rec
        except Exception as e:
            rec["error"] = f"{type(e).__name__}: {e}"
            raise
        finally:
            stack.pop()
            rec["end_ns"] = time.monotonic_ns()
            rec["duration_us"] = (rec["end_ns"] - rec["start_ns"]) // 1000
            with self._lock:
                self.spans.append(rec)

    def export_jsonl(self, path) -> int:
        """Write every span as one JSON line. Full snapshot into a tmp
        file + atomic rename: repeated exports of a growing collector are
        deterministic (each export is complete or absent — a crashed
        export can never leave a truncated spans.jsonl behind)."""
        with self._lock:
            spans = list(self.spans)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            for s in spans:
                f.write(json.dumps(s) + "\n")
        os.replace(tmp, path)
        return len(spans)


_default = Collector()


def default_collector() -> Collector:
    return _default


def span(name: str, **attrs):
    return _default.span(name, **attrs)


# ---------------------------------------------------------------------------
# Trace-context event tags: a thread-local dict the kernel drivers merge
# into their per-chunk telemetry events (wgl_chunk / wgl_batch_chunk /
# wgl_sharded_chunk), linking device chunks to the span that dispatched
# them without threading new arguments through the kernel entry points.

_tags_local = threading.local()
_EMPTY_TAGS: dict = {}


@contextmanager
def span_tags(**tags: Any):
    """Attach trace-context tags to telemetry events emitted inside the
    body (nests: inner tags shadow outer keys, the outer dict is
    restored on exit). The online scheduler pushes
    ``trace_span=<oracle span id>`` around each engine decide call."""
    prev = getattr(_tags_local, "d", None)
    _tags_local.d = {**prev, **tags} if prev else dict(tags)
    try:
        yield
    finally:
        _tags_local.d = prev


def event_tags() -> dict:
    """The current thread's trace-context tags — ``{}`` (one shared
    instance, no allocation) when none are pushed."""
    return getattr(_tags_local, "d", None) or _EMPTY_TAGS


class TracingClient(jclient.Client):
    """Wraps a client so every lifecycle call records a span (the dgraph
    suite's with-trace around client bodies, trace.clj:32-74)."""

    def __init__(self, client: jclient.Client,
                 collector: Optional[Collector] = None):
        self.client = client
        self.collector = collector or _default

    def open(self, test, node):
        with self.collector.span("client.open", node=str(node)):
            return TracingClient(self.client.open(test, node),
                                 self.collector)

    def setup(self, test):
        with self.collector.span("client.setup"):
            self.client.setup(test)

    def invoke(self, test, op):
        with self.collector.span(
            "client.invoke", f=str(op.get("f")),
            process=str(op.get("process")),
        ) as rec:
            res = self.client.invoke(test, op)
            rec["type"] = res.get("type")
            return res

    def teardown(self, test):
        with self.collector.span("client.teardown"):
            self.client.teardown(test)

    def close(self, test):
        with self.collector.span("client.close"):
            self.client.close(test)


def tracing(client: jclient.Client,
            collector: Optional[Collector] = None) -> jclient.Client:
    out = TracingClient(client, collector)
    if isinstance(client, jclient.Reusable):
        class _R(TracingClient, jclient.Reusable):
            pass

        return _R(client, collector or _default)
    return out


def store_spans(test: dict, collector: Optional[Collector] = None) -> Optional[str]:
    """Write spans.jsonl into the test's store directory."""
    if not (test.get("name") and test.get("start-time")) or test.get(
        "no-store?"
    ):
        return None
    from . import store

    path = store.path_mk(test, "spans.jsonl")
    (collector or _default).export_jsonl(path)
    return str(path)
