"""Offline DAG executor: fan planned segments through the multi-stream
scheduler.

The driver walks a :class:`~jepsen_tpu.offline.planner.Plan` breadth-
first: every stream's segment chain is submitted in stream-local seq
order to ONE shared :class:`~jepsen_tpu.online.scheduler.
SegmentScheduler`, whose dispatch rounds co-batch ready (segment ×
carried-state) members from MANY streams into ONE
``check_encoded_batch`` device program. Verdicts fold per the monitor's
existing contract — a segment is valid iff ANY carried-state member
linearizes, invalid iff ALL are refuted, and an unknown poisons the
key's later segments one-sidedly — and the stream folds merge through
``checker.merge_valid`` into the plan-level verdict, so the offline
parallel path can only ever *degrade to unknown* relative to the
single-driver verdict, never flip it.

Engines: ``auto`` / ``device`` / ``host`` map straight onto the
scheduler's oracle dispatch; ``sharded`` is the device oracle with the
default :func:`~jepsen_tpu.parallel.make_mesh` attached, so one
co-batched round shards its members across the mesh's ``dp`` axis.
"""

from __future__ import annotations

import time as _time
from typing import Any, Optional

from ..checker import provenance as _prov
from ..models import Model
from ..online.scheduler import SegmentScheduler
from .planner import Plan

__all__ = ["drive", "ENGINES"]

ENGINES = ("auto", "device", "host", "sharded")


def _utilization_summary(metrics) -> Optional[dict]:
    """Per-device busy/idle attribution reconstructed from the
    registry's stamped chunk events (telemetry.utilization), None when
    the run produced no device timeline (pure host-engine rounds)."""
    if metrics is None:
        return None
    try:
        from ..telemetry.profile import _attribute_utilization

        u = _attribute_utilization(metrics)
        return u["summary"] if u else None
    except Exception:  # noqa: BLE001 - observability, not a dependency
        return None


def drive(p: Plan, model: Model, *, engine: str = "auto",
          metrics=None, max_configs: int = 500_000,
          batch_f: int = 256,
          max_ready_per_stream: Optional[int] = None,
          timeout: Optional[float] = 600.0) -> dict:
    """Decide a planned history; returns the offline result map::

        {"valid": True|False|"unknown", "n_ops": ..., "wall_s": ...,
         "engine": ..., "plan": p.stats(), "streams": {name: fold},
         "provenance": {...}?, "violation": {...}?,
         "utilization": {...}?}

    The verdict is the ``merge_valid`` fold of every stream's fold —
    identical in shape to what ``check_history`` returns for the same
    history on one driver, modulo one-sided unknown degradation with
    typed provenance causes.
    """
    if engine not in ENGINES:
        raise ValueError(f"unknown offline engine {engine!r}")
    t0 = _time.perf_counter()
    out: dict = {"n_ops": p.n_ops, "engine": engine, "plan": p.stats()}

    if p.mixed:
        # Same degradation (and same cause) as the monitor: a keyed/
        # keyless mix means independent.subhistory's keyless broadcast
        # cannot be reproduced by any split — planned or streamed.
        out["valid"] = "unknown"
        out["info"] = ("mixed keyed/keyless history: per-key split "
                       "cannot match independent.subhistory; verdict "
                       "degraded to unknown")
        out["provenance"] = _prov.block(
            _prov.add_counts({}, ["mixed_keys"]))
        out["wall_s"] = round(_time.perf_counter() - t0, 4)
        return out
    if not p.items:
        out["valid"] = True
        out["wall_s"] = round(_time.perf_counter() - t0, 4)
        return out

    mesh = None
    sched_engine = engine
    if engine == "sharded":
        from ..parallel import make_mesh

        mesh = make_mesh()
        sched_engine = "device"
    sched = SegmentScheduler(
        model, engine=sched_engine, metrics=metrics,
        max_configs=max_configs, batch_f=batch_f,
        max_ready_per_stream=max_ready_per_stream, mesh=mesh)
    try:
        for name in p.streams:
            sched.register_stream(name)
        # Breadth-first walk: submit every stream's chain in seq order;
        # the scheduler's ready-take interleaves across streams (the
        # fairness cap bounds any one stream's share of a round) and
        # carry edges hold back each key's next segment until its
        # predecessor decided.
        for name, items in p.streams.items():
            batch: list = []
            cur = None
            for it in items:
                if it.seq != cur and batch:
                    sched.submit(batch, stream=name)
                    batch = []
                cur = it.seq
                batch.append(it.segment)
            if batch:
                sched.submit(batch, stream=name)
    finally:
        sched.close(timeout)
    res = sched.result()
    out["valid"] = res["valid"]
    out["wall_s"] = round(_time.perf_counter() - t0, 4)
    streams: dict = {}
    decide_s = 0.0
    for name in p.streams:
        sr = sched.stream_result(name)
        busy = sum((row.get("wall_s") or 0.0)
                   for row in sr.get("segments", ()))
        decide_s += busy
        row = {k: v for k, v in sr.items() if k != "segments"}
        row["decide_s"] = round(busy, 4)
        streams[str(name)] = row
    out["streams"] = streams
    # Scheduler-side busy attribution: total decide wall across every
    # segment vs the drive's wall clock. On a host-engine run (no
    # device timeline, so no per-device attribution below) this is the
    # utilization number — how much of the run the decide pipeline was
    # actually deciding rather than planning/submitting/waiting.
    out["decide_s"] = round(decide_s, 4)
    if out["wall_s"] > 0:
        out["busy_pct"] = round(
            min(100.0, 100.0 * decide_s / out["wall_s"]), 1)
    out["segments_decided"] = res.get("segments_decided")
    if res.get("provenance") is not None:
        out["provenance"] = res["provenance"]
    if res.get("violation") is not None:
        out["violation"] = res["violation"]
    util = _utilization_summary(metrics)
    if util is not None:
        out["utilization"] = util
    return out
