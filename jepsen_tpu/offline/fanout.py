"""Fleet fanout: submit a planned history to your own serving stack.

The plan's streams partition the history's keys, so each stream's op
list is a self-contained sub-history — which makes it a perfectly
shaped *synthetic tenant* for the PR-8/PR-14 serving stack. Fanning the
streams across N backends turns the fleet into the third offline
parallelism axis (after the device batch and the sharded mesh): every
backend re-runs the SAME cut/carry rules server-side over its tenants'
ops, and the per-tenant verdicts fold through ``checker.merge_valid``
into the plan verdict, preserving the one-sided unknown contract
end to end — now across process boundaries.

Two transports, same shape:

- :func:`fanout_services` — N in-process :class:`~jepsen_tpu.service.
  Service` instances fed through ``InProcessServiceClient`` (tests,
  ``--simulate``-style runs; shares the GIL, so it proves the protocol,
  not the speedup).
- :func:`fanout_fleet` — N REAL backend processes behind the PR-14
  tenant :class:`~jepsen_tpu.service.router.Router`, fed as ndjson over
  HTTP through the resume-aware client. Separate processes mean the
  per-stream decision work runs on separate cores — this is where
  ``speedup_vs_serial`` comes from on a CPU box — and the router's
  federated scrape attributes per-backend utilization.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time as _time
from typing import Any, Optional

from ..checker import merge_valid
from ..checker import provenance as _prov
from .planner import Plan

__all__ = ["fanout_services", "fanout_fleet", "TENANT_PREFIX"]

TENANT_PREFIX = "offline-"


def _tenant_of(stream: str) -> str:
    return f"{TENANT_PREFIX}{stream}"


def _mixed_result(p: Plan) -> dict:
    return {
        "valid": "unknown", "n_ops": p.n_ops, "plan": p.stats(),
        "info": ("mixed keyed/keyless history: per-key split cannot "
                 "match independent.subhistory; verdict degraded to "
                 "unknown"),
        "provenance": _prov.block(_prov.add_counts({}, ["mixed_keys"])),
    }


def _fold_tenants(tenant_rows: dict, extra_causes=()) -> dict:
    """merge_valid over the synthetic tenants' verdicts + the union of
    their provenance causes (the one-sided degradation stays typed
    across the process boundary)."""
    valids = []
    counts: dict = {}
    for row in tenant_rows.values():
        v = (row or {}).get("valid")
        valids.append(v if v in (True, False, "unknown") else "unknown")
        causes = ((row or {}).get("provenance") or {}).get("causes")
        if causes:
            counts = _prov.merge_counts(counts, causes)
    counts = _prov.add_counts(counts, extra_causes)
    out: dict = {"valid": merge_valid(valids) if valids else True}
    prov = _prov.block(counts)
    if prov is not None:
        out["provenance"] = prov
    return out


def fanout_services(p: Plan, model, *, backends: int = 2,
                    engine: str = "host", metrics=None,
                    max_configs: int = 500_000,
                    chunk_ops: int = 512,
                    drain_timeout: float = 300.0) -> dict:
    """Decide a plan across N in-process Service backends (streams
    assigned round-robin as synthetic tenants)."""
    from ..service import Service
    from ..service.client import InProcessServiceClient

    if backends < 1:
        raise ValueError("backends must be >= 1")
    t0 = _time.perf_counter()
    if p.mixed:
        return _mixed_result(p)
    services = [Service(model, engine=engine, metrics=metrics,
                        max_configs=max_configs, register_live=False,
                        ledger=False, name=f"offline-backend-{i}")
                for i in range(backends)]
    try:
        assignment = {s: services[i % backends]
                      for i, s in enumerate(sorted(p.stream_ops))}
        reports: dict = {}

        def _feed(stream: str) -> None:
            client = InProcessServiceClient(
                assignment[stream], _tenant_of(stream),
                chunk_ops=chunk_ops)
            reports[stream] = client.feed(p.stream_ops[stream])

        feeders = [threading.Thread(target=_feed, args=(s,),
                                    daemon=True)
                   for s in p.stream_ops if p.stream_ops[s]]
        for th in feeders:
            th.start()
        for th in feeders:
            th.join()
        tenant_rows: dict = {}
        lost = []
        for svc in services:
            svc.flush(drain_timeout)
            fin = svc.drain(timeout=drain_timeout)
            tenant_rows.update(fin.get("tenants") or {})
        for s, rep in reports.items():
            if rep.get("error") or rep.get("sent") != rep.get("ops"):
                lost.append(s)
    finally:
        for svc in services:
            try:
                svc.drain(timeout=5)
            except Exception:  # noqa: BLE001 - already drained
                pass
    out = _fold_tenants(tenant_rows,
                        ["lost_segments"] if lost else [])
    if lost:
        # A feeder that could not deliver its whole stream leaves the
        # undelivered suffix undecided — unknown, never a silent True.
        out["valid"] = merge_valid([out["valid"], "unknown"])
        out["undelivered_streams"] = sorted(lost)
    out.update(n_ops=p.n_ops, backends=backends, plan=p.stats(),
               wall_s=round(_time.perf_counter() - t0, 4),
               feed_reports={s: r for s, r in reports.items()},
               tenants={t: {k: v for k, v in (row or {}).items()
                            if k != "segments"}
                        for t, row in tenant_rows.items()})
    return out


def fanout_fleet(p: Plan, *, backends: int = 2,
                 model: str = "cas-register", engine: str = "host",
                 max_configs: int = 500_000, chunk_ops: int = 1024,
                 drain_timeout: float = 600.0, metrics=None,
                 env: Optional[dict] = None,
                 journal_root: Optional[str] = None) -> dict:
    """Decide a plan across N REAL backend processes behind the tenant
    router ("submit the history to yourself"). Returns the folded
    verdict plus the router's fleet stats — including the federated
    per-backend utilization attribution."""
    from ..service import router as _router
    from ..service.client import HttpServiceClient
    from ..telemetry import Registry

    if backends < 1:
        raise ValueError("backends must be >= 1")
    t0 = _time.perf_counter()
    if p.mixed:
        return _mixed_result(p)
    reg = metrics if metrics is not None else Registry()
    tmpd = journal_root or tempfile.mkdtemp(prefix="jepsen-offline-")
    if env is None:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
    bks = _router.spawn_backends(
        backends, journal_root=tmpd, model=model, engine=engine,
        max_configs=max_configs, metrics=reg, env=env)
    router = _router.Router(bks, metrics=reg, name="offline-fanout",
                            register_live=False, rebalance=False)
    srv = _router.server(router, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        reports: dict = {}

        def _feed(stream: str) -> None:
            client = HttpServiceClient(url, _tenant_of(stream),
                                       chunk_ops=chunk_ops,
                                       max_retries=60,
                                       max_backoff_s=0.5)
            reports[stream] = client.feed(p.stream_ops[stream])

        feeders = [threading.Thread(target=_feed, args=(s,),
                                    daemon=True)
                   for s in p.stream_ops if p.stream_ops[s]]
        feed_t0 = _time.perf_counter()
        for th in feeders:
            th.start()
        for th in feeders:
            th.join()
        feed_s = _time.perf_counter() - feed_t0
        fin = router.drain(timeout=drain_timeout)
        stats = router.stats()
    finally:
        router.close()
        srv.shutdown()
        srv.server_close()
    lost = sorted(s for s, r in reports.items()
                  if r.get("error") or r.get("sent") != r.get("ops"))
    out = _fold_tenants(fin.get("tenants") or {},
                        ["lost_segments"] if lost else [])
    if lost:
        out["valid"] = merge_valid([out["valid"], "unknown"])
        out["undelivered_streams"] = lost
    out.update(
        n_ops=p.n_ops, backends=backends, plan=p.stats(),
        wall_s=round(_time.perf_counter() - t0, 4),
        feed_s=round(feed_s, 4),
        p99_decision_latency_s=fin.get("p99_decision_latency_s"),
        feed_reports=reports,
        tenants={t: {k: v for k, v in (row or {}).items()
                     if k != "segments"}
                 for t, row in (fin.get("tenants") or {}).items()},
        backend_loads=stats.get("backend_loads"),
        fleet=stats.get("fleet"))
    return out
