"""One-pass segment planner for fully recorded histories.

The online monitor (jepsen_tpu.online) discovers segments as the stream
arrives; offline we hold the WHOLE history, so the same cut rules —
quiescent cuts, per-key P-compositional splits, exact carried end-state
sets — can run as one up-front planning pass that emits a *static DAG*
of (stream × key × segment) work items. The DAG makes the available
parallelism explicit before any decision work starts:

- **Across keys** (P-compositionality): different keys' chains never
  depend on each other, so the planner partitions keys across N
  *streams* (greedy largest-first bin packing on op counts) and each
  stream decides independently — on one scheduler, or on one backend of
  the PR-14 fleet (jepsen_tpu.offline.fanout).
- **Across segments of one key** (decrease-and-conquer): segment k+1
  needs segment k's carried end states, so a key's chain is sequential
  — but MANY keys' ready segments co-batch into one device program
  (jepsen_tpu.offline.driver).
- **Across carried states**: each work item fans into one batch member
  per carried initial state at encode time (the scheduler's existing
  any-valid/all-refuted fold).

Planning reuses the online :class:`~jepsen_tpu.online.segmenter.
Segmenter` verbatim (in strict mode — offline ingestion REJECTS
non-monotone indexed input with
:class:`~jepsen_tpu.online.segmenter.NonMonotoneHistoryError` instead of
applying the live path's resume-protocol drop), so the offline cuts are
bit-identical to what the monitor would have produced for the same
stream: the differential contract (tests/test_offline.py) rides on the
two paths sharing one implementation.

Scheduler contract note: the multi-stream scheduler's per-stream
watermark/fold walks seq numbers contiguously from 0, but a stream that
owns a key subset only sees the cuts its keys appear in — so the planner
renumbers each stream's cut ordinals into a dense stream-local ``seq``
(order-preserving; ``PlanItem.global_seq`` keeps the original cut
ordinal for reporting).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field, replace
from typing import Any, Iterable, Optional

from .. import independent as ind
from ..history import History, Op
from ..online.segmenter import (SINGLE_KEY, KeySegment,
                                NonMonotoneHistoryError, Segmenter)

__all__ = ["Plan", "PlanItem", "plan", "NonMonotoneHistoryError"]


@dataclass(frozen=True)
class PlanItem:
    """One (stream × key × segment) node of the static decision DAG."""

    stream: str
    key: Any
    seq: int  # stream-local segment ordinal (dense from 0 per stream)
    global_seq: int  # the segmenter's cut ordinal over the whole history
    segment: KeySegment  # .seq already renumbered to the stream-local seq
    # Stream-local seq of this key's previous segment (the carry edge),
    # None for the key's first segment (carry = the model's init state).
    depends_on: Optional[int] = None

    @property
    def n_ops(self) -> int:
        return self.segment.n_ops


@dataclass
class Plan:
    """The planner's output: per-stream item chains plus the fan-out
    bookkeeping the driver, the fleet fanout and the bench/advisor
    read."""

    items: list[PlanItem] = field(default_factory=list)
    # stream name -> its items in stream-local seq order.
    streams: dict = field(default_factory=dict)
    # stream name -> the ORIGINAL client ops of its keys, index order,
    # [k v] values intact — what fanout feeds the fleet as synthetic
    # tenants (the backends re-run these exact cut rules server-side).
    stream_ops: dict = field(default_factory=dict)
    key_to_stream: dict = field(default_factory=dict)
    n_ops: int = 0  # client ops planned
    n_cuts: int = 0  # global quiescent segments
    n_keys: int = 0
    plan_seconds: float = 0.0
    mixed: bool = False  # keyed/keyless mix: no sound per-key split
    poisoned: bool = False  # an :info ended quiescence mid-history
    dropped_nemesis: int = 0  # non-client ops (no invoke/ok discipline)
    largest_item_ops: int = 0
    largest_item_key: Any = None

    @property
    def n_items(self) -> int:
        return len(self.items)

    @property
    def n_streams(self) -> int:
        return len(self.streams)

    def stats(self) -> dict:
        """The plan summary bench.py embeds and the advisor's
        ``segment_plan_skew`` rule reads."""
        per_stream = {s: sum(it.n_ops for it in items)
                      for s, items in self.streams.items()}
        mean_share = (self.n_ops / max(1, self.n_streams)
                      if self.n_ops else 0.0)
        return {
            "n_ops": self.n_ops,
            "n_cuts": self.n_cuts,
            "n_keys": self.n_keys,
            "n_items": self.n_items,
            "n_streams": self.n_streams,
            "plan_seconds": round(self.plan_seconds, 4),
            "mixed": self.mixed,
            "poisoned": self.poisoned,
            "dropped_nemesis": self.dropped_nemesis,
            "largest_item_ops": self.largest_item_ops,
            "largest_item_key": (repr(self.largest_item_key)
                                 if self.largest_item_key is not None
                                 else None),
            "mean_worker_share_ops": round(mean_share, 1),
            "stream_ops": {str(s): n for s, n in per_stream.items()},
        }


def _key_of(op: Op) -> Any:
    return op.value.key if ind.is_tuple(op.value) else SINGLE_KEY


def _as_ops(history: Any) -> Iterable:
    if isinstance(history, History):
        return list(history)
    return list(history)


def plan(history: Any, streams: int = 1) -> Plan:
    """Plan a fully recorded history into a static decision DAG.

    ``history`` is a :class:`~jepsen_tpu.history.History`, a list of
    :class:`~jepsen_tpu.history.Op`, or a list of plain scheduler op
    dicts (ndjson rows). Missing ``index`` fields are stamped
    monotonically; non-monotone pre-indexed input raises
    :class:`NonMonotoneHistoryError` (a recorded history promises every
    op exactly once, in order — see the exception's docstring).

    ``streams`` is the requested fan-out width; the effective width is
    clamped to the number of keys (an unkeyed history has exactly one
    carry chain, so it plans as one stream regardless).
    """
    if streams < 1:
        raise ValueError("streams must be >= 1")
    t0 = _time.perf_counter()
    seg = Segmenter(strict=True)
    raw_segments: list[KeySegment] = []
    kept_ops: list[Op] = []  # client ops, as (re)indexed by the segmenter
    dropped_nemesis = 0
    for op in _as_ops(history):
        raw_segments.extend(seg.offer(op))
        last = seg.last_op
        if last is None:
            continue
        if last.is_client:
            kept_ops.append(last)
        else:
            dropped_nemesis += 1
    raw_segments.extend(seg.finish())

    p = Plan(mixed=seg.mixed_keys, poisoned=seg.poisoned,
             dropped_nemesis=dropped_nemesis, n_ops=len(kept_ops),
             n_cuts=seg.segments_emitted)

    # Key universe + per-key op weights (the bin-packing load measure).
    key_ops: dict = {}
    for s in raw_segments:
        key_ops[s.key] = key_ops.get(s.key, 0) + s.n_ops
        if s.n_ops > p.largest_item_ops:
            p.largest_item_ops = s.n_ops
            p.largest_item_key = s.key
    p.n_keys = len(key_ops)

    # Greedy largest-first bin packing of keys onto streams. One carry
    # chain (unkeyed or mixed) cannot split.
    width = 1 if (p.mixed or p.n_keys <= 1) else min(streams, p.n_keys)
    names = [f"s{i}" for i in range(width)]
    loads = {n: 0 for n in names}
    for k, w in sorted(key_ops.items(), key=lambda kv: (-kv[1],
                                                        repr(kv[0]))):
        tgt = min(names, key=lambda n: (loads[n], n))
        p.key_to_stream[k] = tgt
        loads[tgt] += w
    p.streams = {n: [] for n in names}

    # Renumber each stream's cut ordinals densely (order-preserving):
    # the scheduler's per-stream watermark walks next_seq contiguously.
    next_seq = {n: 0 for n in names}
    seen_seq: dict = {}  # (stream, global_seq) -> stream-local seq
    last_seq_of_key: dict = {}
    for s in raw_segments:
        stream = p.key_to_stream[s.key]
        sk = (stream, s.seq)
        if sk not in seen_seq:
            seen_seq[sk] = next_seq[stream]
            next_seq[stream] += 1
        local = seen_seq[sk]
        item = PlanItem(stream=stream, key=s.key, seq=local,
                        global_seq=s.seq,
                        segment=replace(s, seq=local),
                        depends_on=last_seq_of_key.get((stream, s.key)))
        last_seq_of_key[(stream, s.key)] = local
        p.items.append(item)
        p.streams[stream].append(item)

    # Original-op retention for the fleet fanout: each stream's ops in
    # index order, [k v] intact — its keys' full subhistory.
    p.stream_ops = {n: [] for n in names}
    for op in kept_ops:
        stream = p.key_to_stream.get(_key_of(op))
        if stream is None:  # op of a key with no client completions
            stream = names[0]
        p.stream_ops[stream].append(op)

    p.plan_seconds = _time.perf_counter() - t0
    return p
