"""Offline decrease-and-conquer decision path for recorded histories.

``jepsen_tpu.online`` decides a history WHILE it streams; this package
decides a fully *recorded* history by planning the same quiescent-cut /
per-key / carried-state decomposition up front and fanning the
resulting static DAG across three axes at once:

1. the batched device pipeline (many segments → one
   ``check_encoded_batch`` program),
2. the sharded mesh (``--engine sharded``), and
3. the PR-14 backend fleet (streams as synthetic tenants).

Entry points: :func:`plan` + :func:`drive` (one process),
:func:`~jepsen_tpu.offline.fanout.fanout_fleet` (N backend processes),
``python -m jepsen_tpu.offline HISTORY.ndjson`` (CLI), and
``check_history(..., parallel="segmented")`` (the checker surface).
See docs/offline.md.
"""

from __future__ import annotations

from typing import Any, Optional

from .driver import ENGINES, drive
from .fanout import fanout_fleet, fanout_services
from .planner import NonMonotoneHistoryError, Plan, PlanItem, plan

__all__ = ["plan", "drive", "check_offline", "fanout_services",
           "fanout_fleet", "Plan", "PlanItem", "ENGINES",
           "NonMonotoneHistoryError"]


def check_offline(model, history: Any, *, streams: int = 0,
                  engine: str = "auto", backends: int = 0,
                  metrics=None, max_configs: int = 500_000,
                  **kw: Any) -> dict:
    """Plan + decide a recorded history in one call — the
    ``check_history(parallel="segmented")`` implementation.

    ``streams=0`` picks a width automatically (one per key, capped at
    8). ``backends=0`` decides in-process through the shared scheduler;
    ``backends>=1`` fans the streams across that many real backend
    processes via :func:`fanout_fleet`.
    """
    p = plan(history, streams=streams if streams >= 1 else 8)
    if backends >= 1:
        # Backend services speak auto/device/host; the mesh-sharded
        # oracle is a single-process engine, so it maps to device.
        svc_engine = "device" if engine == "sharded" else engine
        out = fanout_fleet(p, backends=backends, model=model.name,
                           engine=svc_engine,
                           max_configs=max_configs, metrics=metrics,
                           **kw)
    else:
        out = drive(p, model, engine=engine, metrics=metrics,
                    max_configs=max_configs, **kw)
    out["parallel"] = "segmented"
    return out
