"""CLI: decide a recorded ndjson history offline.

::

    python -m jepsen_tpu.offline HISTORY.ndjson --model cas-register \
        --engine auto --streams 8 --backends 0 [--keyed] [-o OUT.json]

Each input line is one scheduler-shaped op map (the same rows the
service ingestion endpoint parses); ``--keyed`` re-wraps two-element
list values as ``independent`` [k v] pairs (JSON cannot distinguish a
vector value from a key/value pair, so the caller must say which
recording convention the file uses). ``--backends N`` spawns N real
backend processes behind the tenant router and fans the plan's streams
across them; ``--backends 0`` (default) decides in-process through the
shared multi-stream scheduler.
"""

from __future__ import annotations

import argparse
import json
import sys

from .. import independent as ind
from ..models import known_models, model_by_name
from . import ENGINES, drive, fanout_fleet, plan


def _load_ndjson(path: str, keyed: bool) -> list:
    ops = []
    opener = (lambda: sys.stdin) if path == "-" else \
        (lambda: open(path))
    f = opener()
    try:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if keyed and isinstance(row.get("value"), list) \
                    and len(row["value"]) == 2:
                row = dict(row, value=ind.KV(*row["value"]))
            ops.append(row)
    finally:
        if path != "-":
            f.close()
    return ops


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.offline",
        description="Decide a recorded ndjson history with the "
                    "decrease-and-conquer segment planner.")
    ap.add_argument("history", help="ndjson history file, or - for stdin")
    ap.add_argument("--model", default="cas-register",
                    choices=sorted(known_models()))
    ap.add_argument("--engine", default="auto", choices=list(ENGINES))
    ap.add_argument("--streams", type=int, default=0,
                    help="fan-out width (0 = one per key, capped at 8)")
    ap.add_argument("--backends", type=int, default=0,
                    help="spawn N router backend processes and fan "
                         "the streams across them (0 = in-process)")
    ap.add_argument("--keyed", action="store_true",
                    help="treat 2-element list values as [k v] pairs")
    ap.add_argument("--max-configs", type=int, default=500_000)
    ap.add_argument("-o", "--out", default=None,
                    help="write the result JSON here (default stdout)")
    args = ap.parse_args(argv)

    model = model_by_name(args.model)
    ops = _load_ndjson(args.history, args.keyed)
    streams = args.streams if args.streams >= 1 else \
        max(args.backends, 8) if args.backends else 8
    p = plan(ops, streams=streams)
    from ..telemetry import Registry

    reg = Registry()
    if args.backends >= 1:
        engine = "device" if args.engine == "sharded" else args.engine
        res = fanout_fleet(p, backends=args.backends, model=args.model,
                           engine=engine, metrics=reg,
                           max_configs=args.max_configs)
    else:
        res = drive(p, model, engine=args.engine, metrics=reg,
                    max_configs=args.max_configs)
    res["parallel"] = "segmented"
    doc = json.dumps(res, indent=2, sort_keys=True, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    else:
        print(doc)
    v = res.get("valid")
    return 0 if v is True else 2 if v is False else 1


if __name__ == "__main__":
    sys.exit(main())
