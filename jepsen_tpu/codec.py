"""EDN ↔ bytes codec (jepsen/src/jepsen/codec.clj:9-29 equivalent).

The reference uses this for queue payloads and anywhere an object must
ride a byte channel: ``encode`` renders EDN text as UTF-8 bytes (nil →
empty), ``decode`` parses bytes back (nil/empty → None). Built on the
EDN reader/printer in :mod:`jepsen_tpu.edn`.
"""

from __future__ import annotations

from typing import Any, Optional

from . import edn


def encode(o: Any) -> bytes:
    """Serialize an object to bytes (codec.clj:9-15)."""
    if o is None:
        return b""
    return edn.write_string(o).encode("utf-8")


def decode(data: Optional[bytes]) -> Any:
    """Deserialize bytes to an object (codec.clj:17-29)."""
    if data is None or len(data) == 0:
        return None
    return edn.read_string(bytes(data).decode("utf-8"))
