"""Resume-aware ndjson client for the checking service and router.

Every feeder so far (bench.py's ``_drive`` threads, ``--simulate``'s
per-tenant loops, ad-hoc test helpers) re-implemented the same
half-protocol: submit ops in order, stop at the first typed rejection.
This module is the full client half of the ingestion contract the HTTP
layer already speaks:

- **Typed rejections carry a resume point** (``accepted``): the client
  advances its cursor by exactly what the server took and retries the
  rest — no op is ever skipped or double-counted by the transport.
- **Backoff honors the server's own estimate**: 429/503 responses
  carry ``Retry-After`` (the token bucket's refill estimate, the
  router's migration hint); the client sleeps that, falling back to
  bounded exponential backoff, and gives up after ``max_retries``
  consecutive zero-progress attempts.
- **Reconnects re-anchor on the journaled watermark** (the PR-10
  resume contract): after an unreachable backend or a migration 503,
  the acks the client holds may have come from a process that died
  with unjournaled state — so the client re-reads the tenant's
  watermark (``GET /tenants``) and rewinds to the watermark op
  *inclusive*. The one-op overlap is deliberate: the boundary op's
  delivery is ambiguous, and the server's drop floor
  (``Segmenter.resume``) makes overlap free — the tenant row's
  ``resubmitted_ops_dropped`` counter is the proof the floor engaged.

Two transports share one feed loop: :class:`HttpServiceClient` (the
router bench leg, real deployments) and :class:`InProcessServiceClient`
(``--simulate``, bench's in-process legs, tests) — the latter submits
through ``Service.submit`` directly so value tuples never round-trip
through JSON.
"""

from __future__ import annotations

import json
import logging
import time as _time
from typing import Any, Callable, Iterable, Optional
from urllib import error as _uerror
from urllib import request as _urequest
from urllib.parse import quote

from ..history import Op

LOG = logging.getLogger("jepsen.service")


def op_json(op: Any) -> dict:
    """One history op as the plain scheduler-dict shape the ingestion
    endpoint parses — INCLUDING the index when assigned (the resume
    protocol's drop floor is index-based; an unindexed resubmission
    cannot be deduplicated server-side). ``independent`` [k v] values
    are serialized as ``{"kv": [k, v]}`` — a plain JSON list would be
    indistinguishable from a vector value, and the server needs the
    key axis intact to run its P-compositional split (the ingestion
    seam rehydrates the marker; see ``service._decode_kv``)."""
    if isinstance(op, Op):
        from .. import independent as ind

        value = ({"kv": [op.value.key, op.value.value]}
                 if ind.is_tuple(op.value) else op.value)
        m: dict = {"type": op.type, "process": op.process, "f": op.f,
                   "value": value, "time": op.time}
        if op.index >= 0:
            m["index"] = op.index
        if op.error is not None:
            m["error"] = op.error
        return m
    return dict(op)


class ServiceClient:
    """Shared resume-aware feed loop; subclasses provide the transport
    (`_post(rows) -> response dict`) and the watermark lookup."""

    def __init__(self, tenant: str, *, chunk_ops: int = 256,
                 max_retries: int = 8, base_backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0,
                 sleep: Callable[[float], None] = _time.sleep,
                 trace_id: Optional[str] = None,
                 trace_span: Optional[str] = None) -> None:
        if chunk_ops < 1:
            raise ValueError("chunk_ops must be >= 1")
        self.tenant = tenant
        self.chunk_ops = chunk_ops
        self.max_retries = max_retries
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._sleep = sleep
        # Cross-process trace context: when set, every submit carries
        # the propagation headers (trace.TRACE_HEADER /
        # trace.PARENT_HEADER), so the router and every backend this
        # tenant touches — including post-migration — record their
        # spans under ONE trace id.
        self.trace_id = trace_id
        self.trace_span = trace_span

    # -- transport seam ------------------------------------------------------

    def _post(self, rows: list[dict]) -> dict:
        """Submit ``rows`` in order; NEVER raises. Returns a dict with
        ``status`` (int; 0 = transport unreachable), ``accepted``
        (resume point within this chunk), and optionally ``error`` /
        ``retryable`` / ``retry_after_s``."""
        raise NotImplementedError

    def _resume_watermark(self) -> Optional[int]:
        """The tenant's current journaled/decided watermark as the
        server reports it, or None when unavailable (mid-migration,
        transport down)."""
        return None

    # -- the feed loop -------------------------------------------------------

    def feed(self, ops: Iterable[Any]) -> dict:
        """Feed ``ops`` in order with retries, backoff and watermark
        re-anchoring. Returns a report::

            {"ops": N, "sent": n_accepted, "retries": r,
             "rewinds": w, "resubmitted_ops": k,
             "error": code | None, "gave_up": bool}

        ``error`` is set when a non-retryable rejection (tenant
        aborted, draining) stopped the feed or retries were exhausted;
        ``sent`` is then the exact resume cursor.
        """
        rows = [op_json(op) for op in ops]
        idx = [r["index"] if isinstance(r.get("index"), int) else -1
               for r in rows]
        report = {"ops": len(rows), "sent": 0, "retries": 0,
                  "rewinds": 0, "resubmitted_ops": 0, "error": None,
                  "gave_up": False}
        cursor = 0
        consec = 0  # consecutive zero-progress attempts
        while cursor < len(rows):
            chunk = rows[cursor:cursor + self.chunk_ops]
            r = self._post(chunk)
            accepted = r.get("accepted")
            accepted = accepted if isinstance(accepted, int) else 0
            accepted = max(0, min(accepted, len(chunk)))
            cursor += accepted
            if accepted == len(chunk):
                consec = 0
                continue
            if accepted > 0:
                consec = 0  # partial progress still resets the clock
            status = r.get("status")
            status = status if isinstance(status, int) else 0
            retryable = bool(r.get("retryable")) or status == 0
            if not retryable:
                report["error"] = r.get("error") or f"http_{status}"
                break
            consec += 1
            report["retries"] += 1
            if consec > self.max_retries:
                report["error"] = r.get("error") or "unreachable"
                report["gave_up"] = True
                break
            delay = r.get("retry_after_s")
            if isinstance(delay, (int, float)) and delay > 0:
                delay = min(float(delay), self.max_backoff_s)
            else:
                delay = min(self.base_backoff_s * (2 ** (consec - 1)),
                            self.max_backoff_s)
            self._sleep(delay)
            if status in (0, 503):
                # Reconnect episode (dead backend / migration in
                # flight): re-anchor on the server's watermark, from
                # the watermark op INCLUSIVE (see module docstring).
                wm = self._resume_watermark()
                if wm is not None and wm >= 0:
                    back = next((k for k, i in enumerate(idx)
                                 if i >= wm), None)
                    if back is not None and back < cursor:
                        report["resubmitted_ops"] += cursor - back
                        report["rewinds"] += 1
                        cursor = back
        report["sent"] = cursor
        return report


class HttpServiceClient(ServiceClient):
    """ndjson-over-HTTP transport — point ``base_url`` at a backend's
    or the router's ingestion port."""

    def __init__(self, base_url: str, tenant: str, *,
                 timeout_s: float = 10.0, resume: bool = True,
                 **kw: Any) -> None:
        super().__init__(tenant, **kw)
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.resume = resume

    def _post(self, rows: list[dict]) -> dict:
        body = ("\n".join(json.dumps(r, sort_keys=True, default=str)
                          for r in rows) + "\n").encode()
        url = (f"{self.base_url}/submit/"
               f"{quote(self.tenant, safe='')}")
        from .. import trace as _trace

        req = _urequest.Request(
            url, data=body, method="POST",
            headers=_trace.trace_headers(self.trace_id,
                                         self.trace_span))
        try:
            with _urequest.urlopen(req, timeout=self.timeout_s) as resp:
                doc = json.loads(resp.read().decode() or "{}")
                if not isinstance(doc, dict):
                    doc = {}
                doc.setdefault("status", resp.status)
                return doc
        except _uerror.HTTPError as e:
            try:
                doc = json.loads(e.read().decode() or "{}")
            except ValueError:
                doc = {}
            if not isinstance(doc, dict):
                doc = {}
            doc.setdefault("accepted", 0)
            doc["status"] = e.code
            ra = e.headers.get("Retry-After") if e.headers else None
            if ra and "retry_after_s" not in doc:
                try:
                    doc["retry_after_s"] = float(ra)
                except ValueError:
                    pass
            return doc
        except Exception as e:  # noqa: BLE001 - transport down
            return {"status": 0, "accepted": 0, "error": "unreachable",
                    "retryable": True, "detail": str(e)}

    def _resume_watermark(self) -> Optional[int]:
        if not self.resume:
            return None
        try:
            with _urequest.urlopen(f"{self.base_url}/tenants",
                                   timeout=self.timeout_s) as resp:
                doc = json.loads(resp.read().decode() or "{}")
            row = (doc.get("tenants") or {}).get(self.tenant) or {}
            wm = row.get("watermark")
            return wm if isinstance(wm, int) else None
        except Exception:  # noqa: BLE001 - resume point unavailable
            return None


class InProcessServiceClient(ServiceClient):
    """In-process transport over ``Service.submit`` — the seam
    ``--simulate``, bench's in-process legs and tests drive. Ops are
    handed to the service as-is (no JSON round-trip, so tuple values
    survive)."""

    def __init__(self, service, tenant: str, **kw: Any) -> None:
        super().__init__(tenant, **kw)
        self.service = service

    def _post(self, rows: list[dict]) -> dict:
        from .service import ServiceError

        trace = ((self.trace_id, self.trace_span)
                 if self.trace_id else None)
        accepted = 0
        for row in rows:
            try:
                self.service.submit(self.tenant, row, trace=trace)
            except ServiceError as e:
                return {"status": e.http_status, "accepted": accepted,
                        "error": e.code,
                        # Mirror the HTTP layer: an explicit
                        # e.retryable (the migration 503) overrides
                        # the status-derived default.
                        "retryable": (e.retryable
                                      if e.retryable is not None
                                      else e.http_status == 429),
                        "retry_after_s": e.retry_after_s}
            accepted += 1
        return {"status": 200, "accepted": accepted}

    def _resume_watermark(self) -> Optional[int]:
        try:
            snap = self.service.tenant_snapshot(self.tenant) or {}
            wm = snap.get("watermark")
            return wm if isinstance(wm, int) else None
        except Exception:  # noqa: BLE001
            return None
