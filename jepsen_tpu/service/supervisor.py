"""The fleet supervision layer: backend respawn and crash-safe router
state.

PR 14's router *survives* losing a backend (journal-backed migration
onto the survivors) but never *repairs* the loss: the dead process
stays dead and the fleet runs at N-1 forever, and the router's own
placement map is single-process state — a router crash loses every
placement, tombstone and orphan record at once. This module closes
both halves of that repair loop (ROADMAP item 3's named remainder):

- :class:`BackendSupervisor` — when a spawned backend child dies, the
  supervisor respawns it with **bounded exponential backoff** and a
  **flap-damping circuit** (``max_failures_in_window`` child deaths /
  failed respawns inside ``window_s`` ⇒ give up and stay on the
  survivors; a crash-looping binary must not eat the fleet's CPU
  forever). The replacement child re-binds the SAME ``--journal-dir``
  — the journals of any tenant the router could not migrate away are
  still there, so the respawned process restores them by ordinary
  PR-10 replay — and, once it passes ``/healthz``, the router
  re-adopts tenants toward it via the live ``/migrate`` machinery so
  capacity returns to N. ``JEPSEN_NO_RESPAWN=1`` is the operational
  kill-switch (checked per attempt, like every other kill-switch).
- :class:`ProcessRespawner` — the (re)spawn recipe for one real
  backend process. The child binds **port 0** and reports the bound
  port through an atomically-written ``--port-file``: the old
  probe-a-free-port-then-bind dance had a TOCTOU hole (another
  process could take the probed port between probe and bind) that
  would crash-loop a respawn on ``EADDRINUSE``.
- :class:`RouterState` / :func:`replay_state` — an append-only
  ``router_state.jsonl`` persisting the placement map, orphan
  records, backend lost/respawned events and a **monotone placement
  epoch**, under the same torn-final-line / replay discipline as the
  PR-10 tenant journal (binary read, stop at the first unparseable
  line, truncate the torn fragment on reopen). A restarted router
  replays it and then *reconciles* against live ``/healthz`` +
  journal-dir reality — a record is a hint, reality wins. The epoch
  (bumped past the replayed maximum on every router start) rides
  every ``/release``/``/adopt`` and fences a stale ex-router's
  in-flight migration with a typed 409 ``stale_epoch`` — the
  multi-router-HA primitive.

Telemetry: ``router_respawns_total{backend,outcome}`` (outcome ``ok``
/ ``failed`` / ``gave_up`` / ``disabled``), ``router_respawn_seconds``
(spawn → healthy), and the router's ``router_epoch`` gauge. A
backend whose supervisor gave up reports the typed
``respawn_gave_up`` health state on its fleet-table row (the
advisor's ``respawn_backend`` rule keys off it). See docs/service.md
"Supervision & rolling restart".
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import threading
import time as _time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional
from urllib import request as _urequest

LOG = logging.getLogger("jepsen.router")

STATE_FORMAT_VERSION = 1

RESPAWN_SECONDS_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                           30.0, 60.0, 120.0)


def respawn_disabled() -> bool:
    """``JEPSEN_NO_RESPAWN=1`` — checked per attempt, so flipping the
    env in a live router takes effect (the kill-switch contract)."""
    return os.environ.get("JEPSEN_NO_RESPAWN", "") == "1"


# ---------------------------------------------------------------------------
# Respawning a real backend process (the --port-file protocol).


class ProcessRespawner:
    """(Re)spawn one backend service process: the same command line,
    the same ``--journal-dir``, a FRESH child that binds port 0 and
    reports its bound port through ``port_file`` (written atomically
    by the child after bind — no probe-then-bind TOCTOU, so a respawn
    can never crash-loop on ``EADDRINUSE``). Calling the instance
    replaces ``backend.proc``/``backend.url`` in place; it raises when
    the child exits before becoming healthy or the deadline passes."""

    def __init__(self, cmd: list, *, port_file: str,
                 env: Optional[dict] = None,
                 wait_ready_s: float = 120.0) -> None:
        self.cmd = list(cmd)
        self.port_file = port_file
        self.env = env
        self.wait_ready_s = wait_ready_s

    def spawn(self, backend) -> None:
        """Start the child (any previous incarnation is killed first —
        two children must never share a journal dir)."""
        p = backend.proc
        if p is not None and p.poll() is None:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 - already gone
                pass
        try:
            os.remove(self.port_file)  # a stale port is a wrong port
        except OSError:
            pass
        backend.proc = subprocess.Popen(
            self.cmd, env=self.env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

    def await_ready(self, backend,
                    deadline: Optional[float] = None) -> None:
        """Wait for the bound-port report, then for ``/healthz``."""
        if deadline is None:
            deadline = _time.monotonic() + self.wait_ready_s
        port = None
        while port is None:
            try:
                with open(self.port_file, encoding="utf-8") as f:
                    txt = f.read().strip()
                if txt:
                    port = int(txt)
                    break
            except (OSError, ValueError):
                pass
            if backend.proc.poll() is not None:
                raise RuntimeError(
                    f"backend {backend.name} exited "
                    f"rc={backend.proc.poll()} before reporting its "
                    "bound port")
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"backend {backend.name} did not report a bound "
                    f"port within {self.wait_ready_s}s")
            _time.sleep(0.05)
        url = f"http://127.0.0.1:{port}"
        while True:
            try:
                with _urequest.urlopen(url + "/healthz",
                                       timeout=2) as r:
                    if r.status == 200:
                        break
            except Exception:  # noqa: BLE001 - not up yet
                pass
            if backend.proc.poll() is not None:
                raise RuntimeError(
                    f"backend {backend.name} exited "
                    f"rc={backend.proc.poll()} before becoming "
                    "healthy")
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"backend {backend.name} not healthy after "
                    f"{self.wait_ready_s}s")
            _time.sleep(0.05)
        backend.url = url

    def __call__(self, backend) -> None:
        self.spawn(backend)
        self.await_ready(backend)


# ---------------------------------------------------------------------------
# The per-backend respawn supervisor.


@dataclass(frozen=True)
class RespawnPolicy:
    """Backoff + flap-damping knobs for one backend's supervisor."""

    base_backoff_s: float = 0.25
    max_backoff_s: float = 15.0
    # The flap circuit: this many failures (child deaths + failed
    # respawn attempts) inside the sliding window give up for good.
    window_s: float = 60.0
    max_failures_in_window: int = 5


class BackendSupervisor:
    """Respawn lifecycle for ONE backend: the router's supervision
    tick calls :meth:`note_exit` + :meth:`kick` when it detects the
    child's death; a worker thread then backs off, respawns through
    the injected ``respawner`` and hands the healthy backend to
    ``on_ready`` (the router marks it up and re-adopts tenants).
    Failures accumulate in the flap window; crossing
    ``max_failures_in_window`` flips the terminal ``gave_up`` state —
    the fleet stays on the survivors and the backend row reports
    ``respawn_gave_up`` until an operator intervenes (or the router
    restarts)."""

    def __init__(self, backend, respawner: Callable, policy:
                 Optional[RespawnPolicy] = None, *, metrics=None,
                 on_ready: Optional[Callable] = None,
                 on_give_up: Optional[Callable] = None) -> None:
        self.backend = backend
        self.respawner = respawner
        self.policy = policy or RespawnPolicy()
        self.metrics = metrics
        self.on_ready = on_ready
        self.on_give_up = on_give_up
        self.respawns = 0          # successful respawns, lifetime
        self.last_respawn_s: Optional[float] = None
        self.gave_up = False
        self._attempt = 0          # consecutive failed respawns
        self._failures: "deque[float]" = deque()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- observation ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            self._trim_locked()
            return {
                "respawns": self.respawns,
                "gave_up": self.gave_up,
                "window_failures": len(self._failures),
                "last_respawn_s": self.last_respawn_s,
            }

    # -- the protocol --------------------------------------------------------

    def note_exit(self) -> None:
        """Record one observed child death (a flap-window failure)."""
        with self._lock:
            self._failures.append(_time.monotonic())
            self._trim_locked()

    def kick(self) -> None:
        """Start the respawn worker unless one is already running, the
        circuit gave up, or the supervisor was closed."""
        with self._lock:
            if self.gave_up or self._stop.is_set():
                return
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"jepsen-respawn-{self.backend.name}")
            self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    # -- the worker ----------------------------------------------------------

    def _trim_locked(self) -> None:
        now = _time.monotonic()
        while self._failures and \
                now - self._failures[0] > self.policy.window_s:
            self._failures.popleft()

    def _run(self) -> None:
        b = self.backend
        pol = self.policy
        disabled_seen = False
        while not self._stop.is_set():
            if respawn_disabled():
                # "Checked per attempt" means un-setting the env must
                # take effect on a backend that is ALREADY dead (no
                # further death will ever re-kick it): keep the worker
                # parked on a slow poll instead of exiting, and resume
                # the normal backoff/flap protocol the moment the
                # switch clears. Counted/logged once per kick.
                if not disabled_seen:
                    disabled_seen = True
                    self._count("disabled")
                    LOG.warning("backend %s dead and "
                                "JEPSEN_NO_RESPAWN=1; respawn parked "
                                "until the switch clears", b.name)
                if self._stop.wait(max(pol.max_backoff_s, 1.0)):
                    return
                continue
            with self._lock:
                self._trim_locked()
                if len(self._failures) >= pol.max_failures_in_window:
                    self.gave_up = True
            if self.gave_up:
                self._count("gave_up")
                LOG.error(
                    "backend %s FLAPPING (%d failures within %.0fs); "
                    "giving up on respawn — fleet stays on the "
                    "survivors (respawn_gave_up)", b.name,
                    pol.max_failures_in_window, pol.window_s)
                if self.on_give_up is not None:
                    try:
                        self.on_give_up(b)
                    except Exception:  # noqa: BLE001
                        LOG.warning("on_give_up hook failed",
                                    exc_info=True)
                return
            delay = min(pol.base_backoff_s * (2 ** self._attempt),
                        pol.max_backoff_s)
            if self._stop.wait(delay):
                return
            t0 = _time.monotonic()
            try:
                self.respawner(b)
            except Exception as e:  # noqa: BLE001 - a failed respawn
                self._attempt += 1
                with self._lock:
                    self._failures.append(_time.monotonic())
                self._count("failed")
                LOG.warning("respawn of backend %s failed (%s: %s); "
                            "attempt %d", b.name, type(e).__name__, e,
                            self._attempt)
                continue
            if self._stop.is_set():
                # Closed mid-respawn (drain / teardown): don't
                # resurrect a child nobody will supervise or reap.
                p = getattr(b, "proc", None)
                if p is not None and p.poll() is None:
                    try:
                        p.kill()
                        p.wait(timeout=5)
                    except Exception:  # noqa: BLE001
                        pass
                return
            seconds = _time.monotonic() - t0
            ready = True
            if self.on_ready is not None:
                # The bring-up hook may REFUSE the healthy child (the
                # router could not apply its epoch fence): that is a
                # failed attempt — count it in the flap window, back
                # off, respawn fresh (the next spawn reaps this one).
                try:
                    ready = self.on_ready(b) is not False
                except Exception:  # noqa: BLE001
                    ready = False
                    LOG.warning("on_ready hook for backend %s raised",
                                b.name, exc_info=True)
            if not ready:
                self._attempt += 1
                with self._lock:
                    self._failures.append(_time.monotonic())
                self._count("failed")
                LOG.warning("backend %s respawned but was refused at "
                            "bring-up; retrying", b.name)
                continue
            with self._lock:
                self._attempt = 0
                self.respawns += 1
                self.last_respawn_s = round(seconds, 4)
            self._count("ok")
            self._observe(seconds)
            LOG.info("backend %s respawned in %.2fs (%s)", b.name,
                     seconds, b.url)
            return

    # -- metrics -------------------------------------------------------------

    def _count(self, outcome: str) -> None:
        if self.metrics is not None:
            try:
                self.metrics.counter(
                    "router_respawns_total",
                    "Backend respawn attempts by the supervision "
                    "layer, by backend and outcome (ok / failed / "
                    "gave_up / disabled)",
                    labelnames=("backend", "outcome")).labels(
                        backend=self.backend.name,
                        outcome=outcome).inc()
            except Exception:  # noqa: BLE001 - observability only
                pass

    def _observe(self, seconds: float) -> None:
        if self.metrics is not None:
            try:
                self.metrics.histogram(
                    "router_respawn_seconds",
                    "Wall seconds from respawn start to the "
                    "replacement child passing /healthz",
                    buckets=RESPAWN_SECONDS_BUCKETS).observe(seconds)
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# Crash-safe router state: append-only router_state.jsonl.
#
# Record kinds:
#   header       {"kind": "header", "v": 1, "epoch": N}   (per open)
#   place        {"kind": "place", "tenant", "backend", ["from"]}
#   orphan       {"kind": "orphan", "tenant", "from", "causes"}
#   orphan_clear {"kind": "orphan_clear", "tenant"}
#   lost         {"kind": "lost", "backend"}               (audit)
#   respawned    {"kind": "respawned", "backend", "url"}   (audit)
#
# The "from" field on a place record is the durable tombstone of the
# previous placement (on the backend side the renamed `.migrated`
# journal is the enforcing tombstone; this record lets a restarted
# router know the move happened even when that backend is dead).


def replay_state(path: str) -> dict:
    """Reconstruct the router's durable state from its journal: the
    newest placement per tenant, the open orphan records, and the
    highest epoch any header recorded. The torn-final-line discipline
    is the tenant journal's own reader (``journal.ConsistentLines`` —
    ONE copy of the rule; a missing trailing newline would otherwise
    let the reopen garble the next header, regressing the epoch and
    unfencing a stale router). Every record is a HINT: the restarted
    router reconciles the replayed state against live ``/healthz`` +
    journal-dir reality before serving."""
    from . import journal as _journal

    out: dict = {"epoch": 0, "placement": {}, "orphans": {},
                 "records": 0, "torn_tail": False,
                 "consistent_bytes": 0}
    lines = _journal.ConsistentLines(path)
    try:
        for rec in lines:
            out["records"] += 1
            kind = rec.get("kind")
            if kind == "header":
                ep = rec.get("epoch")
                if isinstance(ep, int):
                    out["epoch"] = max(out["epoch"], ep)
            elif kind == "place":
                t, b = rec.get("tenant"), rec.get("backend")
                if isinstance(t, str) and isinstance(b, str):
                    out["placement"][t] = b
                    # A completed migration supersedes the orphan
                    # record ("orphaned until a later migration
                    # succeeds").
                    out["orphans"].pop(t, None)
            elif kind == "orphan":
                t = rec.get("tenant")
                if isinstance(t, str):
                    out["orphans"][t] = {
                        "from": rec.get("from"),
                        "causes": dict(rec.get("causes") or {}),
                        **({"note": rec["note"]} if rec.get("note")
                           else {}),
                    }
            elif kind == "orphan_clear":
                out["orphans"].pop(rec.get("tenant"), None)
            # "lost"/"respawned" are audit-only: liveness is decided
            # by reconciliation against reality, never by a record.
    except FileNotFoundError:
        return out
    out["torn_tail"] = lines.torn
    out["consistent_bytes"] = lines.consistent_bytes
    return out


class RouterState:
    """The append side of ``router_state.jsonl``: one line-buffered
    writer, append never raises into routing (failures are counted —
    losing durability must not lose a migration)."""

    def __init__(self, path: str, epoch: int,
                 truncate_to: Optional[int] = None) -> None:
        self.path = path
        self.append_failures = 0
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if truncate_to is not None:
            # A torn final line has no trailing newline: appending
            # straight after it would garble the next record (the
            # PR-10 lesson); cut back to the consistent prefix first.
            try:
                with open(path, "r+b") as tf:
                    tf.truncate(truncate_to)
            except FileNotFoundError:
                pass
        self._f = open(path, "a", buffering=1, encoding="utf-8")
        self.append({"kind": "header", "v": STATE_FORMAT_VERSION,
                     "epoch": int(epoch)})

    def append(self, rec: dict) -> bool:
        # Every record carries its wall-clock write time: the /fleet
        # timeline joins these events with per-backend busy spans, and
        # replay tolerates (ignores) unknown keys by construction.
        rec = {**rec, "t": round(_time.time(), 3)}
        try:
            with self._lock:
                self._f.write(json.dumps(rec, sort_keys=True) + "\n")
            return True
        except Exception:  # noqa: BLE001 - durability only
            self.append_failures += 1
            LOG.warning("router state append failed (%d so far)",
                        self.append_failures, exc_info=True)
            return False

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # noqa: BLE001
            pass
