"""ndjson-over-HTTP ingestion for the multi-tenant checking service.

Reuses the ``web.py`` server machinery (``ThreadingHTTPServer`` + a
handler factory closed over the state it serves) for the WRITE side the
results browser never needed:

- ``POST /submit/<tenant>`` — body is ndjson, one history op per line
  (the interpreter's scheduler-dict shape: ``{"type": "invoke",
  "process": 0, "f": "write", "value": 1, "time": ...}``). Ops are fed
  in order through ``Service.submit``; the response reports how many
  lines were accepted. A typed rejection maps to its HTTP status
  (quota/queue-full → 429, draining → 503, aborted tenant → 409) with
  ``{"error": <code>, "accepted": <n>}`` so the client knows exactly
  where to resume.
- ``GET /`` / ``GET /tenants`` — the service's live snapshot (per-tenant
  watermark, backlog, verdict, decision-latency quantiles) as JSON.
- ``GET /healthz`` — liveness.
- ``POST /drain`` — graceful shutdown: folds every tenant's partial
  verdict and returns the per-tenant results document.

The service also registers itself on the results browser's ``/live``
feed (``ServiceConfig.register_live``), so the ingestion port carries
only the ingest API while dashboards keep polling the web server.
"""

from __future__ import annotations

import json
import logging
import math
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote

from .service import Service, ServiceError

LOG = logging.getLogger("jepsen.service")

# Largest POST body accepted (bytes). The per-tenant queue bounds are
# useless if one request can buffer an arbitrary body in RAM first —
# a bigger stream is just more requests (the response's `accepted`
# count is the client's resume cursor anyway).
MAX_BODY_BYTES = 8 << 20


def make_handler(service: Service, max_body: int = MAX_BODY_BYTES):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            LOG.debug(fmt, *args)

        def _json(self, code: int, doc: dict,
                  retry_after_s=None) -> None:
            body = json.dumps(doc, sort_keys=True,
                              default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after_s is not None:
                # The standard backoff hint, integral seconds, never
                # zero (clients treat 0 as "immediately", defeating
                # the point): quota refill estimate on 429s, the fixed
                # drain hint on 503s.
                self.send_header("Retry-After",
                                 str(max(1, math.ceil(retry_after_s))))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = unquote(self.path)
            try:
                if path in ("/", "/tenants", "/tenants/"):
                    self._json(200, service.live_snapshot())
                elif path == "/healthz":
                    self._json(200, {"ok": True,
                                     "service": service.name})
                else:
                    self._json(404, {"error": "not_found"})
            except Exception as e:  # noqa: BLE001 - never 500 silently
                LOG.warning("error serving %s", path, exc_info=True)
                self._json(500, {"error": "internal",
                                 "detail": f"{type(e).__name__}: {e}"})

        def do_POST(self):
            path = unquote(self.path)
            try:
                if path.startswith("/submit/"):
                    tenant = path[len("/submit/"):].strip("/")
                    self._submit(tenant)
                elif path in ("/drain", "/drain/"):
                    self._json(200, service.drain())
                else:
                    self._json(404, {"error": "not_found"})
            except Exception as e:  # noqa: BLE001
                LOG.warning("error serving %s", path, exc_info=True)
                self._json(500, {"error": "internal",
                                 "detail": f"{type(e).__name__}: {e}"})

        def _submit(self, tenant: str) -> None:
            length = int(self.headers.get("Content-Length") or 0)
            if length > max_body:
                self._json(413, {
                    "error": "body_too_large", "tenant": tenant,
                    "accepted": 0, "max_bytes": max_body,
                    "detail": "split the stream into smaller POSTs; "
                              "`accepted` is the resume cursor"})
                return
            body = self.rfile.read(length)
            accepted = 0
            for line in body.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    op = json.loads(line)
                except ValueError:
                    self._json(400, {
                        "error": "bad_json", "tenant": tenant,
                        "accepted": accepted,
                        "detail": "unparseable ndjson line"})
                    return
                try:
                    service.submit(tenant, op)
                except ServiceError as e:
                    # Typed rejection: the client resumes after
                    # `accepted` lines (quota/backpressure are
                    # retryable 429s; aborted/draining are not). 429s
                    # and 503s additionally carry Retry-After — the
                    # token bucket's refill estimate / the drain hint
                    # — so a well-behaved client backs off by exactly
                    # the server's own estimate.
                    doc = {
                        "error": e.code, "tenant": tenant,
                        "accepted": accepted, "detail": str(e),
                        "retryable": e.http_status == 429}
                    ra = (e.retry_after_s
                          if e.http_status in (429, 503) else None)
                    if ra is not None:
                        doc["retry_after_s"] = ra
                    self._json(e.http_status, doc, retry_after_s=ra)
                    return
                accepted += 1
            self._json(200, {"tenant": tenant, "accepted": accepted})

    return Handler


def server(service: Service, port: int = 0) -> ThreadingHTTPServer:
    """Build (without starting) the ingestion server — tests drive
    this; port 0 binds an ephemeral port."""
    return ThreadingHTTPServer(("", port), make_handler(service))


def serve(service: Service, port: int = 8089) -> None:
    """Serve forever (the ``jepsen_tpu.service`` CLI's daemon mode)."""
    srv = server(service, port)
    LOG.info("Service %s ingesting on http://0.0.0.0:%d",
             service.name, srv.server_address[1])
    print(f"Service {service.name} ingesting on "
          f"http://0.0.0.0:{srv.server_address[1]} "
          "(POST /submit/<tenant>, POST /drain, GET /tenants)")
    srv.serve_forever()
