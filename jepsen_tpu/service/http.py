"""ndjson-over-HTTP ingestion for the multi-tenant checking service.

Reuses the ``web.py`` server machinery (``ThreadingHTTPServer`` + a
handler factory closed over the state it serves) for the WRITE side the
results browser never needed:

- ``POST /submit/<tenant>`` — body is ndjson, one history op per line
  (the interpreter's scheduler-dict shape: ``{"type": "invoke",
  "process": 0, "f": "write", "value": 1, "time": ...}``). Ops are fed
  in order through ``Service.submit``; the response reports how many
  lines were accepted. A typed rejection maps to its HTTP status
  (quota/queue-full → 429, draining → 503, aborted tenant → 409) with
  ``{"error": <code>, "accepted": <n>}`` so the client knows exactly
  where to resume.
- ``GET /`` / ``GET /tenants`` — the service's live snapshot (per-tenant
  watermark, backlog, verdict, decision-latency quantiles) as JSON.
- ``GET /healthz`` — liveness.
- ``POST /drain`` — graceful shutdown: folds every tenant's partial
  verdict and returns the per-tenant results document.

The service also registers itself on the results browser's ``/live``
feed (``ServiceConfig.register_live``), so the ingestion port carries
only the ingest API while dashboards keep polling the web server.
"""

from __future__ import annotations

import json
import logging
import math
import os
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote, urlsplit

from .journal import JournalError, JournalModelMismatchError
from .service import Service, ServiceError

LOG = logging.getLogger("jepsen.service")

# Largest POST body accepted (bytes). The per-tenant queue bounds are
# useless if one request can buffer an arbitrary body in RAM first —
# a bigger stream is just more requests (the response's `accepted`
# count is the client's resume cursor anyway).
MAX_BODY_BYTES = 8 << 20
# Adopt bodies are WHOLE journals and have no chunked resume protocol
# (the replay needs the complete file) — a long-lived tenant's journal
# easily exceeds the submit cap, and refusing it would permanently
# orphan exactly the tenants with the most decided state to protect.
# Still bounded: one adopt buffers at most this much.
MAX_ADOPT_BODY_BYTES = 256 << 20


def make_handler(service: Service, max_body: int = MAX_BODY_BYTES):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            LOG.debug(fmt, *args)

        def _json(self, code: int, doc: dict,
                  retry_after_s=None) -> None:
            body = json.dumps(doc, sort_keys=True,
                              default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after_s is not None:
                # The standard backoff hint, integral seconds, never
                # zero (clients treat 0 as "immediately", defeating
                # the point): quota refill estimate on 429s, the fixed
                # drain hint on 503s.
                self.send_header("Retry-After",
                                 str(max(1, math.ceil(retry_after_s))))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = unquote(self.path)
            try:
                if path in ("/", "/tenants", "/tenants/",
                            "/live", "/live/"):
                    # /live is the alias the fleet page's per-backend
                    # links target — same row the web dashboard polls.
                    self._json(200, service.live_snapshot())
                elif path == "/healthz":
                    # Liveness PLUS the per-tenant overload signals
                    # (backlog, journal_lag_ops, degraded) the router /
                    # an external LB makes placement decisions from —
                    # no /metrics scrape needed.
                    self._json(200, service.health_snapshot())
                elif path in ("/metrics", "/metrics/"):
                    # The LIVE registry as Prometheus text (before,
                    # prom export only landed in store files at drain —
                    # nothing was scrape-able mid-run).
                    self._metrics_text()
                elif path in ("/metrics.json", "/metrics.json/"):
                    # The federation scrape the router consumes:
                    # samples + helps + the event-ring tail (see
                    # telemetry.fleet.scrape_payload).
                    self._metrics_json()
                elif path in ("/trace", "/trace/"):
                    # The service's span sink (when tracing is on) —
                    # how a cross-process trace is observed without a
                    # span-shipping sidecar: the test/operator scrapes
                    # each backend's spans and joins on trace id.
                    col = getattr(service, "collector", None)
                    if col is None:
                        self._json(404, {"error": "no_collector"})
                    else:
                        with col._lock:
                            spans = list(col.spans)
                        self._json(200, {"service": service.name,
                                         "spans": spans})
                elif path in ("/alerts", "/alerts/"):
                    # The alert plane's lifecycle view: firing set,
                    # rule catalogue, recent transitions
                    # ({"enabled": false} without an alert config).
                    self._json(200, service.alerts_snapshot())
                else:
                    self._json(404, {"error": "not_found"})
            except Exception as e:  # noqa: BLE001 - never 500 silently
                LOG.warning("error serving %s", path, exc_info=True)
                self._json(500, {"error": "internal",
                                 "detail": f"{type(e).__name__}: {e}"})

        def do_POST(self):
            parts = urlsplit(self.path)
            path = unquote(parts.path)
            query = parse_qs(parts.query)
            try:
                if path.startswith("/submit/"):
                    tenant = path[len("/submit/"):].strip("/")
                    self._submit(tenant, query)
                elif path.startswith("/adopt/"):
                    self._adopt(path[len("/adopt/"):].strip("/"),
                                query)
                elif path.startswith("/release/"):
                    self._release(path[len("/release/"):].strip("/"),
                                  query)
                elif path in ("/fence", "/fence/"):
                    self._fence(query)
                elif path in ("/drain", "/drain/"):
                    self._json(200, service.drain())
                else:
                    self._json(404, {"error": "not_found"})
            except Exception as e:  # noqa: BLE001
                LOG.warning("error serving %s", path, exc_info=True)
                self._json(500, {"error": "internal",
                                 "detail": f"{type(e).__name__}: {e}"})

        def _metrics_text(self) -> None:
            reg = service.metrics
            if reg is None:
                self._json(404, {"error": "no_registry"})
                return
            from ..telemetry import export as _export

            body = _export.prometheus_text(reg).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _metrics_json(self) -> None:
            reg = service.metrics
            if reg is None:
                self._json(404, {"error": "no_registry"})
                return
            from ..telemetry import fleet as _fleet

            self._json(200, _fleet.scrape_payload(
                reg, service=service.name))

        def _trace_ctx(self):
            """The propagated cross-process trace context of this
            request, or None (see trace.TRACE_HEADER)."""
            from .. import trace as _trace

            tid = self.headers.get(_trace.TRACE_HEADER)
            if not tid:
                return None
            return (tid, self.headers.get(_trace.PARENT_HEADER))

        def _read_body(self, tenant: str, limit: Optional[int] = None):
            """Bounded body read shared by submit and adopt; None when
            the 413 was already sent."""
            cap = limit if limit is not None else max_body
            length = int(self.headers.get("Content-Length") or 0)
            if length > cap:
                self._json(413, {
                    "error": "body_too_large", "tenant": tenant,
                    "accepted": 0, "max_bytes": cap,
                    "detail": "split the stream into smaller POSTs; "
                              "`accepted` is the resume cursor"})
                return None
            return self.rfile.read(length)

        def _epoch_of(self, query: dict):
            """Parse the optional fencing epoch; returns (ok, epoch)
            — a non-integer epoch is a 400, not a silent unfenced
            call (the fence would never learn the caller's
            generation)."""
            raw = (query.get("epoch") or [None])[0]
            if raw is None:
                return True, None
            try:
                return True, int(raw)
            except ValueError:
                self._json(400, {"error": "bad_epoch",
                                 "detail": f"epoch {raw!r} is not an "
                                           "integer"})
                return False, None

        def _fence(self, query: dict) -> None:
            ok, epoch = self._epoch_of(query)
            if not ok:
                return
            if epoch is None:
                self._json(400, {"error": "bad_epoch",
                                 "detail": "POST /fence?epoch=N"})
                return
            try:
                self._json(200, service.fence(epoch))
            except ServiceError as e:
                self._json(e.http_status,
                           {"error": e.code, "detail": str(e)})

        def _adopt(self, tenant: str, query: dict) -> None:
            # The migration seam: body = the tenant's journal (the
            # router's handover), ?cause= the typed migration reason
            # (backend_lost), ?epoch= the caller's placement epoch
            # (a stale ex-router is refused 409 `stale_epoch`). Typed
            # refusals map like /submit's; a journal written for
            # another model family is the 409 the PR-10 replay already
            # types. The cap is the ADOPT cap — journals have no
            # chunked resume protocol, and the submit-sized bound
            # would orphan big tenants forever.
            ok, epoch = self._epoch_of(query)
            if not ok:
                return
            body = self._read_body(tenant, limit=MAX_ADOPT_BODY_BYTES)
            if body is None:
                return
            cause = (query.get("cause") or [None])[0]
            try:
                doc = service.adopt(tenant, body, cause=cause,
                                    epoch=epoch,
                                    trace=self._trace_ctx())
            except ServiceError as e:
                self._json(e.http_status,
                           {"error": e.code, "tenant": tenant,
                            "detail": str(e)},
                           retry_after_s=(e.retry_after_s
                                          if e.http_status in (429, 503)
                                          else None))
                return
            except JournalModelMismatchError as e:
                self._json(409, {"error": "journal_model_mismatch",
                                 "tenant": tenant, "detail": str(e)})
                return
            except JournalError as e:
                self._json(409, {"error": "journal_error",
                                 "tenant": tenant, "detail": str(e)})
                return
            except ValueError as e:  # unknown provenance cause code
                self._json(400, {"error": "bad_cause",
                                 "tenant": tenant, "detail": str(e)})
                return
            self._json(200, doc)

        def _release(self, tenant: str, query: dict) -> None:
            ok, epoch = self._epoch_of(query)
            if not ok:
                return
            try:
                doc = service.release(tenant, epoch=epoch)
            except ServiceError as e:
                self._json(e.http_status,
                           {"error": e.code, "tenant": tenant,
                            "detail": str(e)},
                           retry_after_s=(e.retry_after_s
                                          if e.http_status in (429, 503)
                                          else None))
                return
            self._json(200, doc)

        def _submit(self, tenant: str, query: Optional[dict] = None
                    ) -> None:
            body = self._read_body(tenant)
            if body is None:
                return
            trace = self._trace_ctx()
            adapter = ((query or {}).get("adapter") or [None])[0]
            if adapter is not None:
                # Content negotiation: the body is a RAW TRACE in the
                # named adapter's dialect, not ndjson ops — the ingest
                # front door (docs/ingest.md).
                self._submit_trace(tenant, adapter, body, query or {},
                                   trace)
                return
            accepted = 0
            for line in body.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    op = json.loads(line)
                except ValueError:
                    self._json(400, {
                        "error": "bad_json", "tenant": tenant,
                        "accepted": accepted,
                        "detail": "unparseable ndjson line"})
                    return
                try:
                    service.submit(tenant, op, trace=trace)
                except ServiceError as e:
                    # Typed rejection: the client resumes after
                    # `accepted` lines (quota/backpressure are
                    # retryable 429s; aborted/draining are not). 429s
                    # and 503s additionally carry Retry-After — the
                    # token bucket's refill estimate / the drain hint
                    # — so a well-behaved client backs off by exactly
                    # the server's own estimate.
                    doc = {
                        "error": e.code, "tenant": tenant,
                        "accepted": accepted, "detail": str(e),
                        # Migration 503s override the status-derived
                        # default: the tenant comes back (elsewhere),
                        # so the client retries through the router.
                        "retryable": (e.retryable
                                      if e.retryable is not None
                                      else e.http_status == 429)}
                    ra = (e.retry_after_s
                          if e.http_status in (429, 503) else None)
                    if ra is not None:
                        doc["retry_after_s"] = ra
                    self._json(e.http_status, doc, retry_after_s=ra)
                    return
                accepted += 1
            self._json(200, {"tenant": tenant, "accepted": accepted})

        def _submit_trace(self, tenant: str, adapter: str,
                          body: bytes, query: dict, trace) -> None:
            """``POST /submit/<tenant>?adapter=<name>``: parse a raw
            recording through the named ingest adapter, submit the
            recovered history ops, and TAINT the tenant for every
            line no rule explained — its drain verdict folds
            one-sidedly to unknown (``ingest_unmapped_op``)."""
            from .. import ingest as _ingest
            from ..online.segmenter import NonMonotoneHistoryError

            try:
                a = _ingest.by_name(adapter)
            except KeyError:
                self._json(400, {
                    "error": "unknown_adapter", "tenant": tenant,
                    "accepted": 0, "adapter": adapter,
                    "known": sorted(_ingest.ADAPTERS)})
                return
            window = (query.get("reorder_window_ns") or [None])[0]
            try:
                window = (int(window) if window is not None
                          else _ingest.DEFAULT_REORDER_WINDOW_NS)
            except ValueError:
                self._json(400, {"error": "bad_reorder_window",
                                 "tenant": tenant, "accepted": 0})
                return
            try:
                parsed = _ingest.parse_trace(
                    body.decode("utf-8", errors="replace").splitlines(),
                    a, reorder_window_ns=window,
                    metrics=service.metrics)
            except NonMonotoneHistoryError as e:
                # Corrupt recording (out of order beyond the repair
                # window): typed refusal, nothing submitted.
                self._json(400, {"error": "non_monotone_trace",
                                 "tenant": tenant, "accepted": 0,
                                 "detail": str(e)})
                return
            # Taint FIRST: the degradation must be durable even if a
            # rejection truncates the submit loop below.
            if parsed["unmapped"]:
                service.taint(tenant, "ingest_unmapped_op",
                              parsed["unmapped"])
            accepted = 0
            for op in parsed["ops"]:
                # The service stamps its own indexes (the tenant may
                # already hold ops from earlier POSTs).
                op = {k: v for k, v in op.items() if k != "index"}
                try:
                    service.submit(tenant, op, trace=trace)
                except ServiceError as e:
                    doc = {
                        "error": e.code, "tenant": tenant,
                        "accepted": accepted, "detail": str(e),
                        "adapter": adapter,
                        "unmapped": parsed["unmapped"],
                        "retryable": (e.retryable
                                      if e.retryable is not None
                                      else e.http_status == 429)}
                    ra = (e.retry_after_s
                          if e.http_status in (429, 503) else None)
                    if ra is not None:
                        doc["retry_after_s"] = ra
                    self._json(e.http_status, doc, retry_after_s=ra)
                    return
                accepted += 1
            self._json(200, {
                "tenant": tenant, "accepted": accepted,
                "adapter": adapter, "unmapped": parsed["unmapped"],
                "hint": parsed["hint"], "stats": parsed["stats"]})

    return Handler


def server(service: Service, port: int = 0) -> ThreadingHTTPServer:
    """Build (without starting) the ingestion server — tests drive
    this; port 0 binds an ephemeral port."""
    return ThreadingHTTPServer(("", port), make_handler(service))


def serve(service: Service, port: int = 8089,
          port_file: Optional[str] = None) -> None:
    """Serve forever (the ``jepsen_tpu.service`` CLI's daemon mode).
    ``port_file`` is the spawned-backend readiness protocol: the
    BOUND port (``--port 0`` = ephemeral) is written atomically after
    bind, so a supervisor never has to probe-then-bind a port it
    could lose to another process (the TOCTOU the old
    ``_free_port`` dance had)."""
    srv = server(service, port)
    if port_file:
        tmp = f"{port_file}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(srv.server_address[1]))
        os.replace(tmp, port_file)
    LOG.info("Service %s ingesting on http://0.0.0.0:%d",
             service.name, srv.server_address[1])
    print(f"Service {service.name} ingesting on "
          f"http://0.0.0.0:{srv.server_address[1]} "
          "(POST /submit/<tenant>, POST /drain, GET /tenants)")
    srv.serve_forever()
