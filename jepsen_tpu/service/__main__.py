"""``python -m jepsen_tpu.service`` — run the multi-tenant checking
service.

Two modes:

- **Daemon** (default): start the ndjson-over-HTTP ingestion server and
  run until interrupted; Ctrl-C drains gracefully and prints the
  per-tenant results document. ``--live-port`` additionally serves the
  results browser in-process so ``/live.html`` shows the per-tenant
  rows while the service runs. ``--journal-dir`` makes verdicts
  crash-safe: every decided segment is journaled, and a restarted
  daemon pointed at the same directory replays it — reconnecting
  tenants resume from their journaled watermark (reported under
  ``resumed_from_journal`` on ``GET /tenants``) instead of
  resubmitting history.
- **Simulation** (``--simulate N``): drive N synthetic tenant streams
  through the in-process ``Service.submit`` seam (the same seam the
  tests and bench use), drain, and print per-tenant results. Exit code
  follows the CLI convention: 0 all valid, 1 any invalid, 2 any
  unknown.

    python -m jepsen_tpu.service --port 8089 --model cas-register \\
        --max-tenants 16 --quota-ops 2000 --backpressure reject
    python -m jepsen_tpu.service --simulate 4 --sim-ops 2000 \\
        --abort-on-violation
"""

from __future__ import annotations

import argparse
import json
import logging
import random
import sys
import threading
from typing import Optional

from ..models import known_models, model_by_name
from ..telemetry import Registry
from . import Service, ServiceConfig
from . import http as shttp
from .client import InProcessServiceClient

LOG = logging.getLogger("jepsen.service")


def build_service(ns: argparse.Namespace,
                  metrics: Optional[Registry] = None) -> Service:
    model_args = json.loads(ns.model_args) if ns.model_args else {}
    if ns.model in ("register", "cas-register"):
        model_args.setdefault("init", 0)
    model = model_by_name(ns.model, **model_args)
    cfg = ServiceConfig(
        engine=ns.engine,
        max_tenants=ns.max_tenants,
        quota_ops_per_s=ns.quota_ops,
        queue_limit=ns.queue_limit,
        backpressure=ns.backpressure,
        block_timeout_s=ns.block_timeout,
        abort_on_violation=ns.abort_on_violation,
        max_configs=ns.max_configs,
        store_root=ns.store_root,
        journal_dir=ns.journal_dir,
        journal_fsync=ns.journal_fsync,
        alerts=ns.alerts,
        alerts_path=ns.alerts_path,
        alerts_sink=ns.alerts_sink,
    )
    # Every daemon carries a span collector: the fleet's cross-process
    # traces are observed by scraping each backend's GET /trace — a
    # backend without a collector would be a hole in every trace that
    # crosses it.
    from .. import trace as _trace

    return Service(model, cfg, metrics=metrics,
                   collector=_trace.Collector(), name=ns.name)


def simulate(service: Service, n_tenants: int, n_ops: int,
             seed: int = 0, invalid_tenants: int = 0) -> dict:
    """Drive N synthetic tenant streams concurrently through the
    in-process submit seam (one thread per tenant — the simulated
    generator), then drain. ``invalid_tenants`` streams are seeded
    with a violation (demonstrating per-tenant abort isolation when
    abort_on_violation is armed)."""
    from ..testing import chunked_register_history, perturb_history

    def run_one(i: int):
        rng = random.Random(seed + i)
        h = chunked_register_history(rng, n_ops=n_ops, n_procs=4,
                                     chunk_ops=60)
        if i < invalid_tenants:
            h = perturb_history(random.Random(seed + 1000 + i), h,
                                within=0.5)
        name = f"tenant-{i}"
        # The resume-aware client replaces the old ad-hoc loop: typed
        # 429s are retried with the server's own Retry-After estimate,
        # terminal rejections (aborted tenant) stop the feed cleanly.
        rep = InProcessServiceClient(service, name).feed(h)
        if rep["error"]:
            LOG.info("tenant %s: stopped at op %d (%s)", name,
                     rep["sent"], rep["error"])

    threads = [threading.Thread(target=run_one, args=(i,))
               for i in range(n_tenants)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return service.drain()


def _run_roll(router_url: str) -> int:
    """``--roll ROUTER_URL``: ask a running router for a rolling
    restart and report the per-backend outcome."""
    from urllib import error as _uerror
    from urllib import request as _urequest

    req = _urequest.Request(router_url.rstrip("/") + "/roll",
                            data=b"", method="POST")
    try:
        with _urequest.urlopen(req, timeout=600) as r:
            doc = json.loads(r.read().decode() or "{}")
    except _uerror.HTTPError as e:
        # A partial roll answers 409 WITH the structured per-backend
        # report (which backend failed to drain, which rolled) — the
        # operator needs that body, not just the status line.
        try:
            doc = json.loads(e.read().decode() or "{}")
        except ValueError:
            doc = {"ok": False, "error": f"http_{e.code}"}
    except Exception as e:  # noqa: BLE001 - router down / refused
        print(f"roll failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=1, sort_keys=True, default=str))
    return 0 if doc.get("ok") else 1


def _run_router(ns: argparse.Namespace, metrics: Registry) -> int:
    """``--router``: front a fleet of backend service processes."""
    from . import router as jrouter

    if ns.backend_urls:
        backends = []
        for i, spec in enumerate(ns.backend_urls.split(",")):
            url, _, jdir = spec.strip().partition("=")
            backends.append(jrouter.Backend(
                f"backend-{i}", url, journal_dir=jdir or None,
                metrics=metrics,
                failure_threshold=ns.failure_threshold))
    else:
        if not ns.journal_dir:
            print("--router needs --journal-dir (per-backend journal "
                  "roots) or --backend-urls", file=sys.stderr)
            return 2
        backends = jrouter.spawn_backends(
            ns.router_backends, journal_root=ns.journal_dir,
            model=ns.model, engine=ns.engine,
            max_configs=ns.max_configs, metrics=metrics,
            failure_threshold=ns.failure_threshold,
            extra_args=(("--abort-on-violation",)
                        if ns.abort_on_violation else ()))
    router = jrouter.Router(
        backends, metrics=metrics, name=ns.name,
        probe_interval_s=ns.probe_interval,
        failure_threshold=ns.failure_threshold,
        state_path=ns.state_path,
        respawn=not ns.no_respawn,
        alerts=ns.alerts,
        alerts_path=ns.alerts_path,
        alerts_sink=ns.alerts_sink)
    web_srv = None
    if ns.live_port is not None:
        from .. import web

        web_srv = web.server(root=ns.store_root, port=ns.live_port)
        threading.Thread(target=web_srv.serve_forever,
                         name="jepsen-live-web", daemon=True).start()
        print(f"live dashboard on http://0.0.0.0:"
              f"{web_srv.server_address[1]}/live.html")
    try:
        try:
            jrouter.serve(router, port=ns.port)
            fin = router.drain()
        except KeyboardInterrupt:
            print("draining backends…", file=sys.stderr)
            fin = router.drain()
    finally:
        router.close()
        if web_srv is not None:
            web_srv.shutdown()
            web_srv.server_close()
    print(json.dumps(fin, indent=1, sort_keys=True, default=str))
    valid = fin.get("valid")
    if valid is False:
        return 1
    if valid is not True:
        return 2
    return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.service",
        description="Always-on multi-tenant checking service: ndjson "
                    "ingestion, per-tenant online verdicts, cross-"
                    "tenant device co-batching.")
    p.add_argument("--port", type=int, default=8089,
                   help="ingestion port (POST /submit/<tenant>); 0 "
                        "binds an ephemeral port (see --port-file)")
    p.add_argument("--port-file", default=None,
                   help="write the BOUND ingestion port here "
                        "(atomically, after bind) — the spawned-"
                        "backend readiness protocol the router's "
                        "respawn supervisor reads, immune to the "
                        "probe-then-bind port race")
    p.add_argument("--model", choices=known_models(),
                   default="cas-register")
    p.add_argument("--model-args", default=None,
                   help='JSON kwargs for the model, e.g. \'{"init": 0}\'')
    p.add_argument("--engine", choices=["auto", "device", "host"],
                   default="auto")
    p.add_argument("--name", default="service")
    p.add_argument("--max-tenants", type=int, default=64)
    p.add_argument("--quota-ops", type=float, default=None,
                   help="per-tenant ops/s admission quota "
                        "(default: unlimited)")
    p.add_argument("--queue-limit", type=int, default=4096,
                   help="bounded per-tenant ingest queue size")
    p.add_argument("--backpressure", choices=["reject", "block"],
                   default="reject",
                   help="full-queue policy: 429-style reject or "
                        "blocking submit")
    p.add_argument("--block-timeout", type=float, default=30.0)
    p.add_argument("--abort-on-violation", action="store_true",
                   help="abort (only) the violating tenant's stream at "
                        "its first invalid segment")
    p.add_argument("--max-configs", type=int, default=500_000)
    p.add_argument("--store-root", default=None)
    p.add_argument("--journal-dir", default=None,
                   help="crash-safe per-tenant verdict journal "
                        "directory; a restart replays it and "
                        "reconnecting tenants resume from their "
                        "journaled watermark (GET /tenants reports "
                        "resumed_from_journal)")
    p.add_argument("--journal-fsync", action="store_true",
                   help="fsync every journal record (kill-safe, "
                        "slower)")
    p.add_argument("--live-port", type=int, default=None,
                   help="also serve the results browser (incl. the "
                        "/live per-tenant dashboard) on this port")
    p.add_argument("--router", action="store_true",
                   help="run as the scale-out FRONT-END instead of a "
                        "backend: place tenants across N backend "
                        "service processes, health-check them, and "
                        "live-migrate tenants via their verdict "
                        "journals (docs/service.md "
                        "'Scale-out & migration')")
    p.add_argument("--router-backends", type=int, default=2,
                   metavar="N",
                   help="spawn N backend processes (each gets its own "
                        "port and <journal-dir>/backend-i; requires "
                        "--journal-dir)")
    p.add_argument("--backend-urls", default=None,
                   help="attach to EXISTING backends instead of "
                        "spawning: comma-separated url[=journal_dir] "
                        "pairs")
    p.add_argument("--probe-interval", type=float, default=1.0,
                   help="router health-probe period (seconds)")
    p.add_argument("--failure-threshold", type=int, default=3,
                   help="consecutive failed probes before a backend "
                        "is declared lost and its tenants migrate")
    p.add_argument("--state-path", default=None,
                   help="router crash safety: append placement / "
                        "orphan records / the placement epoch to this "
                        "jsonl; a restarted router replays it and "
                        "reconciles against live backend reality "
                        "(docs/service.md 'Supervision & rolling "
                        "restart')")
    p.add_argument("--no-respawn", action="store_true",
                   help="disable the respawn supervisor (equivalent "
                        "to JEPSEN_NO_RESPAWN=1): dead spawned "
                        "backends stay dead, the fleet runs on the "
                        "survivors")
    p.add_argument("--alerts", action="store_true",
                   help="evaluate the built-in alert rule catalogue "
                        "on the existing pump/probe cadence and serve "
                        "GET /alerts (docs/alerts.md)")
    p.add_argument("--alerts-path", default=None,
                   help="durable alerts.jsonl (implies --alerts); a "
                        "restart replays it to the same firing set. "
                        "Routers default to an alerts.jsonl next to "
                        "--state-path when alerting is on")
    p.add_argument("--alerts-sink", default=None,
                   help="fan alert transitions out to an http(s):// "
                        "webhook (JSON POST per transition, bounded "
                        "retry) or an ndjson file (implies --alerts)")
    p.add_argument("--roll", metavar="ROUTER_URL", default=None,
                   help="POST /roll to a RUNNING router (rolling "
                        "restart: drain-migrate, respawn and re-adopt "
                        "one backend at a time) and print the "
                        "result; exits 0 when every backend rolled")
    p.add_argument("--simulate", type=int, default=None, metavar="N",
                   help="run N synthetic tenant streams through the "
                        "in-process seam instead of serving HTTP")
    p.add_argument("--sim-ops", type=int, default=1000)
    p.add_argument("--sim-invalid", type=int, default=0,
                   help="seed this many simulated tenants with a "
                        "violation")
    p.add_argument("--seed", type=int, default=0)
    ns = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s{%(threadName)s} %(levelname)s %(name)s - "
               "%(message)s")
    if ns.roll:
        return _run_roll(ns.roll)
    metrics = Registry()
    if ns.router:
        return _run_router(ns, metrics)
    service = build_service(ns, metrics=metrics)

    web_srv = None
    if ns.live_port is not None:
        from .. import web

        web_srv = web.server(root=ns.store_root, port=ns.live_port)
        threading.Thread(target=web_srv.serve_forever,
                         name="jepsen-live-web", daemon=True).start()
        print(f"live dashboard on http://0.0.0.0:"
              f"{web_srv.server_address[1]}/live.html")

    try:
        if ns.simulate is not None:
            fin = simulate(service, ns.simulate, ns.sim_ops,
                           seed=ns.seed,
                           invalid_tenants=ns.sim_invalid)
        else:
            try:
                shttp.serve(service, port=ns.port,
                            port_file=ns.port_file)
                fin = service.drain()  # serve_forever returned
            except KeyboardInterrupt:
                print("draining…", file=sys.stderr)
                fin = service.drain()
    finally:
        if web_srv is not None:
            web_srv.shutdown()
            web_srv.server_close()
    print(json.dumps(fin, indent=1, sort_keys=True, default=str))
    valid = fin.get("valid")
    if valid is False:
        return 1
    if valid is not True:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
