"""The always-on multi-tenant checking service.

One resident :class:`Service` accepts many concurrent tenant streams
(ndjson-over-HTTP via ``jepsen_tpu.service.http``, or the in-process
:meth:`Service.submit` seam tests and the simulated generator use),
runs one ``online`` segmenter per tenant, and feeds ONE shared
:class:`~jepsen_tpu.online.scheduler.SegmentScheduler` whose dispatch
loop co-batches ready (segment × carried-state) members *across
tenants* into the PR-2 batched device pipeline — the "distinct keys
pipeline" generalized to distinct tenants, so device batches fill from
whoever has work while each tenant keeps its own in-order fold,
watermark, and verdict (the co-batching contract: sharing a batch
never changes a verdict; tests/test_service.py pins it differentially
against offline ``check_history`` per tenant).

Production controls:

- **Admission**: at most ``max_tenants`` concurrent streams
  (:class:`TenantLimitError`), a per-tenant ops/s token bucket
  (:class:`QuotaExceededError`) — both typed, both HTTP-429-mappable.
- **Backpressure**: every tenant's ingest queue is BOUNDED
  (``queue_limit``); when the pump falls behind, ``backpressure=
  "reject"`` raises :class:`IngestQueueFullError` (the 429 path) and
  ``"block"`` makes :meth:`submit` wait up to ``block_timeout_s`` —
  memory never grows unboundedly. The pump additionally stops draining
  a tenant whose undecided scheduler backlog passed
  ``max_inflight_segments``, so pressure propagates ingest-ward
  instead of piling segments behind the device.
- **Fairness**: per-(tenant, key) in-order dispatch guarantees every
  tenant with ready work lands in every round; ``max_ready_per_tenant``
  caps a flooding tenant's share of any single round.
- **Isolation on violation**: with ``abort_on_violation`` a tenant
  whose stream folds invalid is ABORTED — further submits raise
  :class:`TenantAbortedError`, ``ops_to_detection`` /
  ``seconds_to_detection`` are recorded — while every other tenant's
  stream keeps deciding undisturbed (``--online-abort`` semantics,
  scoped to one tenant).
- **Graceful drain**: :meth:`drain` stops admission, flushes the
  queues, folds each tenant's terminal segment, and returns per-tenant
  partial results (verdict, watermark, decision-latency summary,
  violation witness), appending one ledger record per tenant stream.

Telemetry rides the existing stack: ``online_scheduler_backlog`` /
``online_decided_watermark`` grow ``{tenant}`` children next to their
unlabeled totals, ``decision_latency_seconds`` is registered with a
``{tenant}`` label family plus the aggregate, ``online_round`` events
carry the per-round stream mix (the co-batching assertion), and
``live_snapshot()`` feeds the web ``/live`` page one row per tenant.
See docs/service.md.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time as _time
from collections import deque
from dataclasses import dataclass, replace
from typing import Any, Optional

from ..checker import provenance as _prov
from ..online.scheduler import SegmentScheduler
from ..online.segmenter import Segmenter
from ..telemetry import flight as _flight
from ..telemetry.registry import DECISION_LATENCY_BUCKETS, Histogram
from ..testing import chaos as _chaos

LOG = logging.getLogger("jepsen.service")


def _decode_kv(op: Any) -> Any:
    """Rehydrate the wire encoding of ``independent`` [k v] values.

    JSON cannot distinguish a plain vector value from a key/value pair,
    so ``client.op_json`` serializes KV values as ``{"kv": [k, v]}`` —
    this (the one ingestion seam both transports share) turns the
    marker back into the live ``independent.KV``, which the tenant's
    segmenter needs to run the P-compositional key split server-side
    (the offline fleet fanout's whole parallelism axis)."""
    v = op.get("value") if isinstance(op, dict) else None
    if (isinstance(v, dict) and len(v) == 1
            and isinstance(v.get("kv"), (list, tuple))
            and len(v["kv"]) == 2):
        from .. import independent as ind

        return dict(op, value=ind.KV(*v["kv"]))
    return op


# ---------------------------------------------------------------------------
# Typed rejections (the ingestion layer maps these to HTTP statuses).


class ServiceError(Exception):
    """Base class of every typed service rejection.

    ``retry_after_s`` (instance attribute, set at raise time where the
    raiser can estimate it) rides to the HTTP layer as a standard
    ``Retry-After`` header next to the ``retryable`` flag: a quota
    rejection carries the token bucket's refill estimate, a full-queue
    rejection a short drain hint, a draining 503 the fixed restart
    hint."""

    http_status = 400
    code = "service_error"
    retry_after_s: Optional[float] = None
    # None = derive from status (429 retryable, everything else not);
    # a migration 503 overrides to True — the tenant comes back on
    # another backend, and the client must keep retrying through it.
    retryable: Optional[bool] = None


# Fixed Retry-After hints where no live estimate exists: a full ingest
# queue usually drains within a pump sweep or two; a draining service
# needs a deploy-scale pause before the replacement listens.
QUEUE_RETRY_AFTER_S = 1.0
DRAIN_RETRY_AFTER_S = 30.0


class ServiceClosedError(ServiceError):
    """The service is draining or closed — no new work is admitted."""

    http_status = 503
    code = "draining"
    retry_after_s = DRAIN_RETRY_AFTER_S


class AdmissionError(ServiceError):
    """Admission control rejected the submit (the 429 family)."""

    http_status = 429
    code = "admission"


class TenantLimitError(AdmissionError):
    code = "tenant_limit"
    retry_after_s = 30.0  # capacity frees on another tenant's drain


class QuotaExceededError(AdmissionError):
    code = "quota_exceeded"


class IngestQueueFullError(AdmissionError):
    code = "ingest_queue_full"


class TenantAbortedError(ServiceError):
    """The tenant's stream folded invalid with abort armed."""

    http_status = 409
    code = "tenant_aborted"


class UnknownTenantError(ServiceError):
    """The named tenant does not live on this backend."""

    http_status = 404
    code = "unknown_tenant"


class TenantMigratingError(ServiceError):
    """The tenant is mid-migration (released, or a second concurrent
    release): the client should back off briefly and resume against
    the router, which will hold the new placement."""

    http_status = 503
    code = "migrating"
    retry_after_s = 1.0
    retryable = True


class TenantAdoptConflictError(ServiceError):
    """Double-adopt refusal: the tenant (or its journal) already lives
    on this backend — adopting it again would fork the fold."""

    http_status = 409
    code = "already_adopted"


class TenantMigratedError(ServiceError):
    """The tenant was released to another backend: this backend must
    never silently re-admit it as a fresh stream (the fork would check
    its tail from the model's init state — a potential flip). Clients
    go through the router, which holds the new placement; only an
    explicit ``adopt`` (journal in hand) may re-own the name here."""

    http_status = 410
    code = "migrated"
    retryable = False


class AdoptUnsupportedError(ServiceError):
    """Adopt/release need a journal: without ``journal_dir`` this
    backend has no checkpoint to restore from or hand over."""

    http_status = 400
    code = "no_journal"


class StaleEpochError(ServiceError):
    """The caller's placement epoch is below this backend's fence: a
    newer router generation has taken ownership of the fleet, and
    honoring a stale ex-router's in-flight ``/release``/``/adopt``
    would split tenant ownership (two routers flipping placement
    independently — the fork the fence exists to prevent)."""

    http_status = 409
    code = "stale_epoch"
    retryable = False


# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ServiceConfig:
    """Service-wide policy knobs (every tenant shares them)."""

    engine: str = "auto"
    max_tenants: int = 64
    # ops/s admitted per tenant; None = unlimited. The bucket's burst
    # defaults to two seconds' worth of quota.
    quota_ops_per_s: Optional[float] = None
    quota_burst: Optional[float] = None
    queue_limit: int = 4096
    backpressure: str = "reject"  # "reject" (429) | "block"
    block_timeout_s: float = 30.0
    abort_on_violation: bool = False
    max_configs: int = 500_000
    batch_f: int = 256
    # Fairness: max segments one tenant contributes to a single
    # scheduler round (see SegmentScheduler.max_ready_per_stream).
    max_ready_per_tenant: int = 64
    # Flow control: the pump stops draining a tenant whose undecided
    # scheduler backlog passed this high-water mark, so the bounded
    # ingest queue (not the scheduler) absorbs the flood.
    max_inflight_segments: int = 512
    register_live: bool = True  # expose live_snapshot on web /live
    ledger: bool = True  # append one record per tenant stream on drain
    store_root: Optional[str] = None
    # Crash safety: when set, every decided segment appends one record
    # to <journal_dir>/<tenant>.jsonl under the fold lock, and a
    # restarted service REPLAYS the directory — reconnecting clients
    # resume from their journaled watermark instead of resubmitting
    # history (docs/service.md "Crash-safe verdict journal").
    journal_dir: Optional[str] = None
    journal_fsync: bool = False  # fsync every record (slow, kill-safe)
    # Alerting plane (docs/alerts.md): evaluate the built-in rule
    # catalogue over this service's own registry/health on the pump
    # cadence (throttled to ALERT_EVAL_INTERVAL_S — no new thread) and
    # serve GET /alerts. Off by default; enabling any of the three
    # lazily imports telemetry/alerts.py. alerts_path makes the
    # lifecycle durable (alerts.jsonl, ConsistentLines discipline);
    # alerts_sink fans transitions to a webhook/ndjson target.
    alerts: bool = False
    alerts_path: Optional[str] = None
    alerts_sink: Optional[str] = None

    def __post_init__(self):
        if self.backpressure not in ("reject", "block"):
            raise ValueError(
                f"backpressure must be 'reject' or 'block', "
                f"got {self.backpressure!r}")
        if self.max_tenants < 1:
            raise ValueError("max_tenants must be >= 1")


class _Tenant:
    """One tenant stream's service-side state."""

    def __init__(self, name: str, cfg: ServiceConfig):
        self.name = name
        self.queue: "queue.Queue" = queue.Queue(maxsize=cfg.queue_limit)
        self.segmenter = Segmenter()
        self.aborted = threading.Event()
        self.lock = threading.Lock()       # counters + token bucket
        self.lat_lock = threading.Lock()   # leaf: pending-latency deque
        self.lat_pending: "deque[tuple[int, int]]" = deque()
        self.ops_ingested = 0   # accepted into the queue
        self.ops_observed = 0   # fed through the segmenter
        # Segments the closed scheduler refused (a drain-deadline race):
        # the ops are observed but their verdict contribution is lost,
        # so a definite True can no longer cover the stream.
        self.lost_segments = False
        # Ingest-side taints: {taxonomy code: count} of trace lines /
        # ops the ?adapter= front door could not explain — the checked
        # history is incomplete, so the drain fold degrades ANY
        # definite verdict (True or False) to unknown, one-sidedly.
        self.taints: dict = {}
        self.rejected = {"quota": 0, "queue": 0, "aborted": 0}
        self.detection: Optional[dict] = None
        self.journal = None           # TenantJournal when journaling
        self.resumed: Optional[dict] = None  # journal replay summary
        # Highest watermark a SUCCESSFUL journal append has recorded —
        # the resume point a release/crash hands over (a swallowed
        # append must not advance it; the /healthz lag reads it).
        self.journaled_watermark = -1
        # release() flips this: the tenant is mid-migration, submits
        # 503 with a short Retry-After while the router flips placement.
        self.released = threading.Event()
        self.t0 = _time.monotonic()
        self.registered_at = _time.time()
        # Propagated cross-process trace context: (trace_id, parent
        # span id) from the newest submit that carried the headers,
        # and the span id of the service.ingest span recorded for it
        # (decide spans parent to it). Guarded by self.lock.
        self.trace: Optional[tuple] = None
        self.trace_span: Optional[str] = None
        # Whether an ingest span was recorded under self.trace ON THIS
        # backend: an adopt joins the context (so decide spans parent
        # right) without consuming the resumed feed's ingest span.
        self.trace_ingested = False
        # Token bucket (guarded by self.lock).
        self.allowance = float(cfg.quota_burst
                               if cfg.quota_burst is not None
                               else (cfg.quota_ops_per_s or 0) * 2.0)
        self.last_refill = _time.monotonic()


class Service:
    """The resident daemon: ``submit(tenant, op)`` in, per-tenant
    verdicts out, one shared device pipeline underneath."""

    def __init__(self, model, config: Optional[ServiceConfig] = None,
                 *, metrics=None, collector=None, flight=None,
                 name: str = "service", **overrides) -> None:
        cfg = config or ServiceConfig()
        if overrides:
            cfg = replace(cfg, **overrides)
        self.model = model
        self.config = cfg
        self.metrics = metrics
        # The span sink shared with the scheduler below: propagated
        # trace context (client → router → here) is recorded against
        # it, so cross-process spans land next to the in-process
        # op/segment/member/oracle chain and join on stream + index.
        self.collector = collector
        self.name = name
        self._tenants: dict[str, _Tenant] = {}
        # Tombstones of tenants released to another backend: _admit
        # refuses them (TenantMigratedError) so a stray direct-to-
        # backend retry can't fork the stream as a fresh tenant; an
        # explicit adopt (journal in hand) clears the tombstone.
        self._released_tenants: set[str] = set()
        self._tlock = threading.Lock()
        # Epoch fence (multi-router HA): the highest placement epoch
        # any /fence, /release or /adopt has presented; calls carrying
        # a LOWER epoch are refused with the typed 409 StaleEpochError
        # (guarded by _tlock).
        self._fence_epoch = -1
        self._draining = False
        self._drain_lock = threading.Lock()
        self._finished: Optional[dict] = None
        self._t0 = _time.monotonic()
        self.scheduler = SegmentScheduler(
            model, engine=cfg.engine, metrics=metrics,
            max_configs=cfg.max_configs, batch_f=cfg.batch_f,
            collector=collector, flight=flight,
            max_ready_per_stream=cfg.max_ready_per_tenant)
        # ONE decision-latency histogram family: the aggregate child is
        # the service-wide summary, {tenant} children the per-tenant
        # p99s the bench leg and /live rows report.
        _help = ("Per-op lag from observed invocation to decided-"
                 "watermark coverage, by tenant (unlabeled = all "
                 "tenants)")
        self._lat = (
            metrics.histogram("decision_latency_seconds", _help,
                              labelnames=("tenant",),
                              buckets=DECISION_LATENCY_BUCKETS,
                              aggregate=True)
            if metrics is not None else
            Histogram("decision_latency_seconds", _help,
                      labelnames=("tenant",),
                      buckets=DECISION_LATENCY_BUCKETS, aggregate=True))
        self.flight = flight
        # Journal replay runs BEFORE the pump thread exists: a raising
        # replay (model mismatch, unreadable dir) fails the ctor
        # without leaking a thread — including the scheduler's worker,
        # which already started above and must be closed on the way
        # out — and no submit can race the restore (restore_stream
        # requires a work-free stream).
        if cfg.journal_dir:
            try:
                with _flight.phase(flight, "service.replay"):
                    self._replay_journals(cfg.journal_dir)
            except BaseException:
                self.scheduler.close(timeout=10.0)
                raise
        # Alerting plane: built ONLY when configured (the off-path pin
        # — telemetry/alerts.py is never imported otherwise), and
        # evaluated from the pump thread on a throttle, never a new
        # thread.
        self.alert_engine = None
        self._sentinel = None
        self._alerts_mod = None
        self._next_alert_eval = 0.0
        self._alert_prev_ops: Optional[tuple] = None
        if cfg.alerts or cfg.alerts_path or cfg.alerts_sink:
            from ..telemetry import alerts as _alerts

            self._alerts_mod = _alerts
            sink = (_alerts.AlertSink(cfg.alerts_sink)
                    if cfg.alerts_sink else None)
            self._sentinel = _alerts.RegressionSentinel()
            self.alert_engine = _alerts.AlertEngine(
                metrics=metrics, path=cfg.alerts_path, sink=sink,
                source=self.name)
        self._wake = threading.Event()
        self._pump_stop = threading.Event()
        self._pump_thread = threading.Thread(
            target=self._pump, name="jepsen-service-pump", daemon=True)
        self._pump_thread.start()
        if cfg.register_live:
            try:
                from .. import web

                web.register_live_source(self.name, self.live_snapshot)
            except Exception:  # noqa: BLE001 - observability only
                LOG.warning("could not register live source",
                            exc_info=True)

    # -- the crash-safe verdict journal ---------------------------------------

    def _replay_journals(self, journal_dir: str) -> None:
        """Service restart: rebuild every journaled tenant's fold
        state (watermark, verdict counters, per-key carries, violation
        witness) and reopen its journal for appends. Raises the typed
        :class:`journal.JournalModelMismatchError` when a journal was
        written for a different model family — carried states must
        never cross folds."""
        from . import journal as _journal

        from urllib.parse import unquote as _unquote

        # Tombstones survive restarts: a `.jsonl.migrated` file marks
        # a tenant released to another backend — re-admitting it fresh
        # here would fork its history (the TenantMigratedError class
        # docstring's flip). An adopt (journal in hand) still clears
        # the tombstone.
        try:
            for name in os.listdir(journal_dir):
                if name.endswith(".jsonl.migrated"):
                    self._released_tenants.add(
                        _unquote(name[:-len(".jsonl.migrated")]))
        except FileNotFoundError:
            pass
        for tenant, path in _journal.scan(journal_dir).items():
            rep = _journal.replay(path, self.model)
            with self._tlock:
                if len(self._tenants) >= self.config.max_tenants:
                    raise TenantLimitError(
                        f"journal dir holds more tenants than "
                        f"max_tenants={self.config.max_tenants}")
                t = self._tenants[tenant] = _Tenant(tenant, self.config)
            self._restore_tenant(t, path, rep)
        if self.metrics is not None and self._tenants:
            self.metrics.gauge(
                "service_tenants",
                "Tenant streams currently admitted").set(
                    len(self._tenants))

    def _restore_tenant(self, t: _Tenant, path: str, rep: dict,
                        adopt_cause: Optional[str] = None) -> None:
        """Restore ONE tenant's fold state from a replayed journal —
        the one seam the ctor replay AND the router's ``adopt`` share
        (the two registration paths must not drift). The caller has
        already inserted ``t`` into ``_tenants``; ``adopt_cause`` is
        the migration reason the router passes (``backend_lost``)."""
        from . import journal as _journal

        tenant = t.name
        if rep.get("fresh"):
            # Empty journal / torn header (a crash inside the very
            # first write): nothing to restore — admit the tenant
            # fresh and REWRITE the header so the reopened file is
            # replayable next time. An ADOPT that lands here is
            # different: the router migrated a tenant it knows existed
            # on a lost backend, so the stream has a decided past no
            # carry enumerates — checking anything from the model's
            # init state could wrongly refute. Pin the stream unknown
            # with the migration cause (poisoned carries): strictly
            # one-sided, never a flip.
            if adopt_cause is None:
                self.scheduler.register_stream(
                    tenant, **self._stream_hooks(t))
            else:
                cc = _prov.add_counts({}, [adopt_cause])
                self.scheduler.restore_stream(
                    tenant, watermark=-1, next_seq=0, carry={},
                    carry_poisoned=True, n_decided=1, n_unknown=1,
                    cause_counts=cc, **self._stream_hooks(t))
                _prov.count_metric(self.metrics,
                                   [_prov.cause(adopt_cause)],
                                   tenant=tenant)
                t.resumed = {"records": 0, "watermark": -1,
                             "torn_tail": bool(rep.get("torn_tail")),
                             "degraded": True, "cause": adopt_cause}
            t.journal = _journal.TenantJournal(
                path, tenant, self.model,
                fsync=self.config.journal_fsync, fresh_header=True,
                truncate=True)
            LOG.warning("tenant %s: journal was empty/torn; "
                        "admitted %s", tenant,
                        "fresh" if adopt_cause is None
                        else f"pinned unknown ({adopt_cause})")
            return
        t.resumed = {
            "records": rep["records"],
            "watermark": rep["watermark"],
            "torn_tail": rep["torn_tail"],
        }
        if adopt_cause is not None:
            t.resumed["cause"] = adopt_cause
        if rep.get("degraded"):
            # Swallowed-append gap: the restored fold is pinned
            # unknown and carries are poisoned (journal.replay);
            # surface it on the tenant row too.
            t.resumed["degraded"] = True
        t.segmenter.resume(rep["watermark"] + 1, rep["next_seq"])
        if rep["violation"] is not None:
            t.detection = {}  # detection clock predates this run
            if self.config.abort_on_violation:
                t.aborted.set()
        self.scheduler.restore_stream(
            tenant,
            watermark=rep["watermark"],
            next_seq=rep["next_seq"],
            carry=rep["carry"],
            carry_poisoned=rep["carry_poisoned"],
            n_decided=rep["n_decided"],
            n_invalid=rep["n_invalid"],
            n_unknown=rep["n_unknown"],
            violation=rep["violation"],
            segments=rep["segments"],
            cause_counts=rep.get("cause_counts"),
            **self._stream_hooks(t))
        t.journal = _journal.TenantJournal(
            path, tenant, self.model,
            fsync=self.config.journal_fsync, fresh_header=False,
            truncate_to=(rep["consistent_bytes"]
                         if rep["torn_tail"] else None))
        t.journaled_watermark = rep["watermark"]
        self._set_journal_lag(t, rep["watermark"])
        LOG.info("tenant %s resumed from journal: watermark %d, "
                 "%d records%s", tenant, rep["watermark"],
                 rep["records"],
                 " (torn tail)" if rep["torn_tail"] else "")

    def _stream_hooks(self, t: _Tenant) -> dict:
        """The one hook triple every stream registration path
        (fresh admit, journal restore, empty-journal re-admit) wires —
        kept in one place so the paths cannot drift."""
        return {
            "on_watermark": lambda w, _t=t: self._on_watermark(_t, w),
            "on_violation": lambda v, _t=t: self._on_violation(_t, v),
            "on_segment": (lambda row, key, carry, w, _t=t:
                           self._on_segment(_t, row, key, carry, w)),
        }

    def _on_segment(self, t: _Tenant, row: dict, key: Any, carry: Any,
                    watermark: int) -> None:
        # Scheduler worker thread, fold lock held: the journal record
        # lands before any reader can observe the new fold state, so a
        # journaled watermark never runs ahead of it. Append failures
        # are swallowed inside append_segment (durability lost, verdict
        # unaffected).
        if t.journal is not None:
            if t.journal.append_segment(row, key, carry, watermark):
                # Only a SUCCESSFUL append advances the durable resume
                # point — a swallowed append's watermark was never
                # written, and handing it over would promise coverage
                # the file cannot deliver.
                t.journaled_watermark = watermark
        self._set_journal_lag(t, watermark)

    def _set_journal_lag(self, t: _Tenant, watermark: int) -> None:
        """``journal_lag_ops{tenant}``: ops this tenant has observed
        (by index) that a journaled watermark does not yet cover —
        what a crash right now would force the client to resubmit.
        Only meaningful WITH a journal: without one the gauge would
        imply a bounded loss that does not exist."""
        if self.metrics is None or t.journal is None:
            return
        lag = max(t.segmenter.next_index - (watermark + 1), 0)
        self.metrics.gauge(
            "journal_lag_ops",
            "Observed ops not yet covered by the journaled watermark, "
            "by tenant (what a crash would lose)",
            labelnames=("tenant",), aggregate=True).labels(
                tenant=t.name).set(lag)

    # -- admission -----------------------------------------------------------

    def register(self, tenant: str) -> None:
        """Admit a tenant explicitly (submit() auto-admits). Raises
        :class:`ServiceClosedError` / :class:`TenantLimitError`."""
        self._admit(tenant)

    def _admit(self, tenant: str) -> _Tenant:
        if not isinstance(tenant, str) or not tenant:
            raise ServiceError(f"invalid tenant name {tenant!r}")
        with self._tlock:
            if self._draining:
                raise ServiceClosedError("service is draining")
            t = self._tenants.get(tenant)
            if t is not None:
                return t
            if tenant in self._released_tenants:
                raise TenantMigratedError(
                    f"tenant {tenant!r} was migrated off this "
                    "backend; submit through the router")
            if len(self._tenants) >= self.config.max_tenants:
                raise TenantLimitError(
                    f"max_tenants={self.config.max_tenants} reached; "
                    f"tenant {tenant!r} rejected")
            t = self._tenants[tenant] = _Tenant(tenant, self.config)
            self.scheduler.register_stream(
                tenant, **self._stream_hooks(t))
            if self.config.journal_dir:
                from . import journal as _journal

                try:
                    t.journal = _journal.TenantJournal(
                        _journal.tenant_path(self.config.journal_dir,
                                             tenant),
                        tenant, self.model,
                        fsync=self.config.journal_fsync)
                except Exception:  # noqa: BLE001 - durability only
                    LOG.warning("could not open journal for tenant %s",
                                tenant, exc_info=True)
            if self.metrics is not None:
                self.metrics.gauge(
                    "service_tenants",
                    "Tenant streams currently admitted").set(
                        len(self._tenants))
            return t

    def _take_token(self, t: _Tenant) -> None:
        rate = self.config.quota_ops_per_s
        if rate is None:
            return
        with t.lock:
            now = _time.monotonic()
            burst = (self.config.quota_burst
                     if self.config.quota_burst is not None
                     else rate * 2.0)
            t.allowance = min(burst,
                              t.allowance + (now - t.last_refill) * rate)
            t.last_refill = now
            if t.allowance < 1.0:
                t.rejected["quota"] += 1
                self._count_reject(t, "quota")
                err = QuotaExceededError(
                    f"tenant {t.name!r} over its {rate} ops/s quota")
                # Refill estimate: seconds until the bucket holds one
                # whole token again — the HTTP Retry-After value.
                err.retry_after_s = round((1.0 - t.allowance) / rate, 3)
                raise err
            t.allowance -= 1.0

    def _count_reject(self, t: _Tenant, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "service_rejects_total",
                "Submits rejected by admission control / backpressure",
                labelnames=("tenant", "reason")).labels(
                    tenant=t.name, reason=reason).inc()

    # -- live migration (the router's adopt/release seams) -------------------

    def _check_epoch(self, epoch: Optional[int]) -> None:
        """The fencing primitive: a ``/release``/``/adopt`` carrying a
        placement epoch BELOW the fence is a stale ex-router's
        in-flight migration — refuse it (typed 409) before it can
        split ownership; an equal-or-higher epoch ratchets the fence
        up. Epoch-less calls (direct operator curl, pre-epoch tests)
        pass: fencing is opt-in per caller, the ratchet only ever
        rises."""
        if epoch is None:
            return
        if not isinstance(epoch, int):
            raise ServiceError(f"invalid epoch {epoch!r}")
        with self._tlock:
            if epoch < self._fence_epoch:
                raise StaleEpochError(
                    f"epoch {epoch} is stale: this backend is fenced "
                    f"at epoch {self._fence_epoch} (a newer router "
                    "generation owns the fleet)")
            self._fence_epoch = epoch

    def fence(self, epoch: int) -> dict:
        """Raise the epoch fence explicitly (``POST /fence`` — a
        restarted router fences every live backend at its new epoch
        during reconciliation, so a stale ex-router is refused even on
        backends its own migrations never touched)."""
        if not isinstance(epoch, int):
            raise ServiceError(f"invalid epoch {epoch!r}")
        self._check_epoch(epoch)
        return {"ok": True, "epoch": epoch, "service": self.name}

    def adopt(self, tenant: str, journal_text: Any,
              cause: Optional[str] = None,
              epoch: Optional[int] = None,
              trace: Optional[tuple] = None) -> dict:
        """Adopt one migrated tenant: write its journal (handed over
        by the router — the tenant's complete checkpoint) under this
        backend's ``journal_dir`` and replay it behind ADMISSION —
        draining, double-adopt (typed 409) and ``max_tenants`` all
        refuse before a byte of fold state lands. On success the
        tenant is live here exactly as after a PR-10 restart: the
        reconnecting client resumes from the returned watermark, and
        resubmitted covered ops are dropped server-side. ``cause``
        (``backend_lost``) pins a journal that restores NOTHING to an
        unknown fold — the tenant demonstrably had a past this backend
        cannot check from. A failed adopt removes the written file so
        the NEXT restart's ctor replay cannot trip over it."""
        from . import journal as _journal

        self._check_epoch(epoch)  # fencing outranks every other check
        if not self.config.journal_dir:
            raise AdoptUnsupportedError(
                "this backend runs without --journal-dir; it cannot "
                "adopt a migrated tenant")
        if not isinstance(tenant, str) or not tenant:
            raise ServiceError(f"invalid tenant name {tenant!r}")
        if cause is not None:
            _prov.cause(cause)  # closed-taxonomy validation, up front
        data = (journal_text.encode("utf-8")
                if isinstance(journal_text, str) else bytes(journal_text))
        path = _journal.tenant_path(self.config.journal_dir, tenant)
        # Phase 1, under _tlock: admission checks + a GATED
        # placeholder (released ⇒ submits 503 with Retry-After while
        # the restore runs). The expensive replay happens OUTSIDE the
        # lock — _admit shares _tlock, and holding it through a
        # 100k-record replay would freeze every OTHER tenant's
        # ingestion on this backend.
        with self._tlock:
            if self._draining:
                raise ServiceClosedError("service is draining")
            if tenant in self._tenants:
                raise TenantAdoptConflictError(
                    f"tenant {tenant!r} already lives on this backend")
            if len(self._tenants) >= self.config.max_tenants:
                raise TenantLimitError(
                    f"max_tenants={self.config.max_tenants} reached; "
                    f"cannot adopt tenant {tenant!r}")
            if os.path.exists(path):
                raise TenantAdoptConflictError(
                    f"a journal for tenant {tenant!r} already exists "
                    "on this backend")
            t = self._tenants[tenant] = _Tenant(tenant, self.config)
            t.released.set()  # gate: not ready until phase 3
            # An adopt legitimately re-owns a name this backend once
            # released (a rebalance round-trip): clear the tombstone —
            # but remember it, so a FAILED adopt restores it (dropping
            # it would re-open the fresh-stream fork the tombstone
            # exists to prevent, until the next restart re-scans the
            # .migrated file).
            was_tombstoned = tenant in self._released_tenants
            self._released_tenants.discard(tenant)

        def _undo():
            with self._tlock:
                self._tenants.pop(tenant, None)
                if was_tombstoned:
                    self._released_tenants.add(tenant)
            for p in (tmp, path):
                try:
                    os.remove(p)
                except OSError:
                    pass

        # Phase 2, no lock: write the journal and replay it.
        tmp = f"{path}.{os.getpid()}.adopt"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
            rep = _journal.replay(path, self.model)
        except BaseException:
            _undo()
            raise
        # Phase 3, under _tlock: wire the restored fold in and open
        # the gate. restore_stream requires a work-free stream — the
        # gate guaranteed no submit touched the placeholder.
        with self._tlock:
            try:
                if self._draining:
                    raise ServiceClosedError("service is draining")
                self._restore_tenant(t, path, rep, adopt_cause=cause)
            except BaseException:
                self._tenants.pop(tenant, None)
                if was_tombstoned:
                    self._released_tenants.add(tenant)
                try:
                    os.remove(path)
                except OSError:
                    pass
                raise
            t.released.clear()
            # A re-adopt back onto a backend that once released this
            # tenant: the old `.migrated` artifact is now stale — the
            # fresh journal is authoritative — and leaving it would
            # let a FUTURE migration's rescue path hand out an ancient
            # checkpoint.
            try:
                os.remove(path + ".migrated")
            except OSError:
                pass
            if self.metrics is not None:
                self.metrics.gauge(
                    "service_tenants",
                    "Tenant streams currently admitted").set(
                        len(self._tenants))
        LOG.info("adopted tenant %s (watermark %d, %d records%s)",
                 tenant, rep.get("watermark", -1),
                 rep.get("records", 0),
                 f", cause={cause}" if cause else "")
        # The resume end of a migration handover: recorded against the
        # router-propagated trace context so the tenant's life on THIS
        # backend joins the same trace that covered its life on the
        # source backend and the router's migration span between them.
        self._record_trace(t, trace, "service.adopt",
                           watermark=rep.get("watermark", -1),
                           cause=cause, epoch=epoch)
        return {
            "tenant": tenant,
            "watermark": rep.get("watermark", -1),
            "records": rep.get("records", 0),
            "fresh": bool(rep.get("fresh")),
            "torn_tail": bool(rep.get("torn_tail")),
            "resumed": dict(t.resumed) if t.resumed is not None else None,
        }

    def release(self, tenant: str,
                timeout: Optional[float] = 30.0,
                epoch: Optional[int] = None) -> dict:
        """Live-migration handover of one tenant: stop admitting its
        ops (submits 503 with ``Retry-After`` — the router holds the
        client off while placement flips), QUIESCE it (queue drained,
        every accepted op fed, no undecided segments — so the journal
        is a complete checkpoint through the fold watermark), then
        close the journal, hand its content back, rename the file
        (``.migrated`` — a restart of THIS backend must not re-replay
        a tenant that now lives elsewhere) and forget the tenant. A
        quiesce that outlives ``timeout`` still hands over the journal
        — the un-fed tail sits above the journaled watermark, so the
        client's resume re-submits it on the target: coverage lost,
        never a verdict flipped."""
        from . import journal as _journal

        self._check_epoch(epoch)  # fencing outranks every other check
        with self._tlock:
            if self._draining:
                raise ServiceClosedError("service is draining")
            t = self._tenants.get(tenant)
            if t is None:
                raise UnknownTenantError(
                    f"tenant {tenant!r} does not live on this backend")
            if t.journal is None:
                raise AdoptUnsupportedError(
                    f"tenant {tenant!r} has no journal; there is no "
                    "checkpoint to hand over")
            if t.released.is_set():
                raise TenantMigratingError(
                    f"tenant {tenant!r} is already being released")
            t.released.set()
        deadline = ((_time.monotonic() + timeout)
                    if timeout is not None else None)
        quiesced = False
        while True:
            with t.lock:
                fed = t.ops_observed == t.ops_ingested
            if (fed and t.queue.qsize() == 0
                    and self.scheduler.stream_backlog(tenant) == 0):
                quiesced = True
                break
            self._wake.set()
            if deadline is not None and _time.monotonic() > deadline:
                break
            _time.sleep(0.002)
        # After quiesce no appender is left (on_segment fires under the
        # fold lock BEFORE the backlog reaches 0), so the file content
        # IS the checkpoint. Close first: a post-handover append must
        # fail (counted, swallowed), never extend a handed-over file.
        t.journal.close()
        path = _journal.tenant_path(self.config.journal_dir, tenant)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            # The handover failed BEFORE anything moved: un-release so
            # the tenant is not wedged behind a permanent 503 — reopen
            # the journal for appends where the file still exists
            # (where it vanished, appends stay swallowed-and-counted:
            # rewriting a fresh header over a stream with a decided
            # past would make the NEXT restart check it from init).
            if os.path.exists(path):
                try:
                    t.journal = _journal.TenantJournal(
                        path, tenant, self.model,
                        fsync=self.config.journal_fsync,
                        fresh_header=False)
                except Exception:  # noqa: BLE001 - durability only
                    LOG.warning("could not reopen journal for tenant "
                                "%s after a failed release", tenant,
                                exc_info=True)
            t.released.clear()
            raise ServiceError(
                f"journal for tenant {tenant!r} unreadable: {e}")
        try:
            os.replace(path, path + ".migrated")
        except OSError:
            pass
        with self._tlock:
            self._tenants.pop(tenant, None)
            # Tombstone: a stray direct-to-backend retry must get a
            # typed 410, never a silent fresh stream (fork).
            self._released_tenants.add(tenant)
            if self.metrics is not None:
                self.metrics.gauge(
                    "service_tenants",
                    "Tenant streams currently admitted").set(
                        len(self._tenants))
        removed = self.scheduler.remove_stream(tenant)
        LOG.info("released tenant %s (journaled watermark %d, "
                 "quiesced=%s)", tenant, t.journaled_watermark,
                 quiesced)
        return {
            "tenant": tenant,
            "watermark": t.journaled_watermark,
            "quiesced": quiesced,
            "stream_removed": removed,
            "journal": data.decode("utf-8", "replace"),
        }

    def health_snapshot(self) -> dict:
        """The enriched ``GET /healthz`` document: liveness plus the
        per-tenant overload signals (undecided-segment backlog,
        ``journal_lag_ops``, ``degraded``) the router — or any
        external load balancer — needs for placement and rebalancing
        decisions without scraping ``/metrics``."""
        with self._tlock:
            items = list(self._tenants.items())
            draining = self._draining
        tenants: dict[str, dict] = {}
        for name, t in items:
            ss = self.scheduler.stream_stats(name)
            row: dict = {
                "backlog": ss.get("backlog", 0) or 0,
                "queue_depth": t.queue.qsize(),
                "watermark": ss.get("decided_through_index"),
                "degraded": bool(
                    t.lost_segments or ss.get("segments_unknown")
                    or (t.journal is not None
                        and t.journal.append_failures)),
            }
            if t.journal is not None:
                row["journal_lag_ops"] = max(
                    t.segmenter.next_index
                    - (t.journaled_watermark + 1), 0)
                if t.journal.append_failures:
                    # Durability compromised (the journal_errors alert
                    # predicate reads this; degraded above already
                    # folded it in).
                    row["journal_append_failures"] = \
                        t.journal.append_failures
            tenants[name] = row
        return {
            "ok": True,
            "service": self.name,
            "draining": draining,
            "fence_epoch": self._fence_epoch,
            "tenant_count": len(items),
            "scheduler_backlog": self.scheduler.backlog,
            "tenants": tenants,
        }

    # -- ingestion -----------------------------------------------------------

    def _record_trace(self, t: _Tenant, trace: Optional[tuple],
                      name: str, **attrs) -> None:
        """Record one point-span against the propagated trace context
        (no-op without a collector or context). The first span a new
        context mints (``service.ingest``) is remembered as the parent
        for this tenant's later ``service.decide`` spans — the
        cross-process hop stays one tree per backend visit."""
        if self.collector is None:
            return
        ctx = trace
        with t.lock:
            if ctx is None:
                ctx = t.trace
            elif ctx != t.trace:
                t.trace = ctx
                t.trace_span = None  # new context: new subtree root
                t.trace_ingested = False
            parent_span = t.trace_span
        if ctx is None:
            return
        now = _time.monotonic_ns()
        rec = self.collector.record(
            name, start_ns=now, end_ns=now, trace_id=ctx[0],
            parent_id=parent_span if parent_span is not None else ctx[1],
            stage="service", tenant=t.name, service=self.name, **attrs)
        if parent_span is None:
            with t.lock:
                t.trace_span = rec["span_id"]

    def submit(self, tenant: str, op: Any,
               trace: Optional[tuple] = None) -> None:
        """Accept one history op for ``tenant`` (auto-admitting it).
        Raises the typed rejections documented on the class; an
        accepted op WILL be fed through the tenant's segmenter (unless
        drain's deadline truncates the stream — reported per tenant as
        ``undelivered_ops``). ``trace`` is the propagated cross-process
        trace context ``(trace_id, parent_span_id)`` — recorded once
        per context as a ``service.ingest`` span, not per op."""
        t = self._admit(tenant)
        if trace is not None and self.collector is not None:
            with t.lock:
                is_new = trace != t.trace or not t.trace_ingested
            if is_new:
                self._record_trace(
                    t, trace, "service.ingest",
                    next_index=t.segmenter.next_index)
                with t.lock:
                    t.trace_ingested = True
        if t.released.is_set():
            raise TenantMigratingError(
                f"tenant {t.name!r} is being migrated to another "
                "backend; retry against the router")
        if t.aborted.is_set():
            t.rejected["aborted"] += 1
            self._count_reject(t, "aborted")
            raise TenantAbortedError(
                f"tenant {t.name!r} aborted on a linearizability "
                "violation")
        self._take_token(t)
        # The ingest timestamp rides the queue with the op: decision
        # latency must include queue wait (a flow-controlled tenant's
        # ops CAN sit here for seconds — a p99 stamped at pump-feed
        # time would hide exactly the regression the benchcmp gate
        # watches).
        item = (_decode_kv(op), _time.monotonic_ns())
        try:
            if self.config.backpressure == "block":
                t.queue.put(item, timeout=self.config.block_timeout_s)
            else:
                t.queue.put_nowait(item)
        except queue.Full:
            t.rejected["queue"] += 1
            self._count_reject(t, "queue")
            err = IngestQueueFullError(
                f"tenant {t.name!r} ingest queue full "
                f"({self.config.queue_limit} ops)")
            err.retry_after_s = QUEUE_RETRY_AFTER_S
            raise err from None
        with t.lock:
            t.ops_ingested += 1
        self._wake.set()

    def taint(self, tenant: str, code: str, count: int = 1) -> None:
        """Record ``count`` occurrences of a typed degradation the
        caller observed while producing this tenant's ops (the ingest
        front door's unmapped trace lines: ``ingest_unmapped_op``).
        A tainted tenant's drain verdict folds one-sidedly to unknown
        — the checked history is known to be incomplete, so neither a
        definite True nor a definite False may stand. ``code`` must be
        in the closed provenance taxonomy."""
        _prov.cause(code)  # closed-taxonomy validation
        if count < 1:
            return
        t = self._admit(tenant)
        with t.lock:
            t.taints[code] = t.taints.get(code, 0) + int(count)

    # -- the pump ------------------------------------------------------------

    # Ops drained per tenant per sweep: small enough that a flooding
    # tenant cannot monopolize the pump between a trickle tenant's
    # visits, large enough to amortize the sweep.
    PUMP_BATCH = 256

    def _pump(self) -> None:
        # Single consumer for every tenant queue: offers ops to each
        # tenant's segmenter IN ORDER and submits closed segments to
        # the shared scheduler. Exception-guarded — a pump death stops
        # consumption, which the bounded queues turn into backpressure
        # rather than silent loss.
        try:
            while not self._pump_stop.is_set():
                if not self._pump_once():
                    self._wake.wait(0.05)
                    self._wake.clear()
                # Alerting rides the existing sweep cadence (throttled
                # inside; no-op without an alert config).
                self._maybe_evaluate_alerts()
        except Exception:  # noqa: BLE001
            LOG.error("service pump died; ingest queues will fill",
                      exc_info=True)

    def _pump_once(self) -> bool:
        """One round-robin sweep over the tenants; returns whether any
        op moved."""
        # Chaos seam, BEFORE any op is popped: an injected raise kills
        # the pump with every accepted op still queued — the bounded
        # queues turn the death into backpressure, and drain's
        # synchronous flush feeds everything in order, so the fault
        # costs latency, never a verdict (tests/test_chaos.py).
        _chaos.fire("service.pump")
        with self._tlock:
            tenants = list(self._tenants.values())
        moved = False
        for t in tenants:
            # Flow control: a tenant whose undecided segments passed
            # the high-water mark stops being drained — its bounded
            # queue fills and submit() pushes back — EXCEPT while
            # draining, when the goal is to finish what was accepted.
            if (not self._draining
                    and self.scheduler.stream_backlog(t.name)
                    >= self.config.max_inflight_segments):
                continue
            for _ in range(self.PUMP_BATCH):
                try:
                    item = t.queue.get_nowait()
                except queue.Empty:
                    break
                moved = True
                self._feed(t, item)
        return moved

    def _feed(self, t: _Tenant, item: tuple) -> None:
        op, t_ns = item
        try:
            segs = t.segmenter.offer(op)
        except Exception:  # noqa: BLE001 - one tenant's malformed op
            # must never kill the shared pump (ingest is an external
            # surface); the op is counted and dropped, the stream's
            # already-accepted prefix keeps deciding.
            LOG.warning("tenant %s: dropping malformed op", t.name,
                        exc_info=True)
            with t.lock:
                t.ops_observed += 1
                t.rejected["malformed"] = (
                    t.rejected.get("malformed", 0) + 1)
            self._count_reject(t, "malformed")
            return
        last = t.segmenter.last_op
        if last is not None and last.is_client and last.is_invoke:
            # The pump is the single feeder, so appends land in index
            # order — the watermark pop loop's invariant. Stamped with
            # the INGEST time carried through the queue, and appended
            # BEFORE the scheduler submit so a fast decide can't fire
            # the watermark past an invocation not yet pending.
            with t.lat_lock:
                t.lat_pending.append((last.index, t_ns))
        if segs:
            try:
                self.scheduler.submit(segs, stream=t.name)
            except RuntimeError:
                # Scheduler closed (worker died / drain raced): these
                # segments are LOST — mark the stream so drain degrades
                # a would-be definite True to unknown (it no longer
                # covers the whole stream); the pump must survive.
                t.lost_segments = True
                LOG.warning("scheduler rejected segments of tenant %s",
                            t.name)
        # Counted observed only AFTER any segments were submitted:
        # flush()'s "everything accepted is decided" reads
        # ops_observed == ops_ingested, then waits for scheduler
        # idleness — counting earlier would let flush return between
        # the count and the submit.
        with t.lock:
            t.ops_observed += 1

    # -- the alert plane (docs/alerts.md) ------------------------------------

    def _maybe_evaluate_alerts(self, now: Optional[float] = None
                               ) -> list:
        """One throttled alert pass (the pump-loop hook): samples from
        this service's registry, the /healthz document, and the
        change-point sentinel fed the live sustained-ops/s and p99
        windows. Fully guarded — alerting must never kill the pump."""
        eng = self.alert_engine
        if eng is None:
            return []
        now = _time.monotonic() if now is None else now
        if now < self._next_alert_eval:
            return []
        self._next_alert_eval = (
            now + self._alerts_mod.ALERT_EVAL_INTERVAL_S)
        try:
            sentinel: list = []
            if self._sentinel is not None:
                with self._tlock:
                    tenants = list(self._tenants.values())
                total = 0
                for t in tenants:
                    with t.lock:
                        total += t.ops_observed
                if self._alert_prev_ops is not None:
                    t_prev, n_prev = self._alert_prev_ops
                    dt = now - t_prev
                    if dt > 0:
                        self._sentinel.observe(
                            f"{self.name}:ops_per_s",
                            (total - n_prev) / dt,
                            lower_is_better=False)
                self._alert_prev_ops = (now, total)
                p99 = self._lat.quantile(0.99)
                if p99 is not None:
                    self._sentinel.observe(
                        f"{self.name}:p99_decision_latency_s", p99,
                        lower_is_better=True)
                sentinel = self._sentinel.active()
            return eng.evaluate({
                "samples": (self.metrics.collect()
                            if self.metrics is not None else []),
                "health": self.health_snapshot(),
                "sentinel": sentinel,
            })
        except Exception:  # noqa: BLE001 - observability only
            LOG.warning("alert evaluation failed", exc_info=True)
            return []

    def alerts_snapshot(self) -> dict:
        """The service ``GET /alerts`` document ({"enabled": False}
        without an alert config)."""
        if self.alert_engine is None:
            return {"enabled": False, "service": self.name}
        return {"service": self.name, **self.alert_engine.snapshot()}

    # -- scheduler hooks (worker thread, scheduler lock held) ----------------

    def _on_watermark(self, t: _Tenant, w: int) -> None:
        now_ns = _time.monotonic_ns()
        popped = 0
        with t.lat_lock:
            while t.lat_pending and t.lat_pending[0][0] <= w:
                _idx, t_ns = t.lat_pending.popleft()
                lat = max(now_ns - t_ns, 0) / 1e9
                self._lat.observe(lat)  # aggregate (all tenants)
                self._lat.labels(tenant=t.name).observe(lat)
                popped += 1
        if popped and self.collector is not None:
            # One decide span per watermark advance (never per op):
            # the propagated trace's proof that ops SUBMITTED under it
            # were DECIDED here — the "…→ resume → decide" tail of the
            # cross-process chain.
            self._record_trace(t, None, "service.decide",
                               watermark=w, ops_covered=popped)

    def _on_violation(self, t: _Tenant, violation: dict) -> None:
        with t.lock:
            if t.detection is None:
                t.detection = {
                    "ops_to_detection": t.ops_observed,
                    "seconds_to_detection": round(
                        _time.monotonic() - t.t0, 4),
                }
        if self.config.abort_on_violation:
            LOG.warning(
                "service tenant %s hit a linearizability violation "
                "(segment seq %s); aborting that tenant",
                t.name, violation.get("segment", {}).get("seq"))
            t.aborted.set()

    # -- observation ---------------------------------------------------------

    def tenants(self) -> list[str]:
        with self._tlock:
            return sorted(self._tenants)

    def tenant_snapshot(self, tenant: str) -> Optional[dict]:
        with self._tlock:
            t = self._tenants.get(tenant)
        if t is None:
            return None
        ss = self.scheduler.stream_stats(t.name)
        with t.lat_lock:
            undecided = len(t.lat_pending)
        with t.lock:
            snap = {
                "ops_ingested": t.ops_ingested,
                "ops_observed": t.ops_observed,
                "rejected": dict(t.rejected),
            }
        snap.update({
            "queue_depth": t.queue.qsize(),
            "watermark": ss.get("decided_through_index"),
            "backlog": ss.get("backlog"),
            "segments_decided": ss.get("segments_decided"),
            "verdict": str(ss.get("verdict")),
            "undecided_ops": undecided,
            "aborted": t.aborted.is_set(),
            # Degraded = this tenant's definite-True coverage is
            # already compromised (lost segments at a closed
            # scheduler, unknown-folded segments from a crashed round
            # / failover that couldn't decide) — the /live row flag.
            "degraded": bool(t.lost_segments or t.taints
                             or ss.get("segments_unknown")),
            "decision_latency": self._lat.stats(
                labels={"tenant": t.name}),
        })
        # Why-unknown provenance: the scheduler's per-stream cause
        # union plus the service-layer degradations this tenant hit.
        prov_counts = dict(
            (ss.get("provenance") or {}).get("causes") or {})
        if t.lost_segments:
            _prov.add_counts(prov_counts, ["lost_segments"])
        if t.taints:
            with t.lock:
                prov_counts = _prov.merge_counts(
                    prov_counts,
                    {code: int(n) for code, n in t.taints.items()})
        if prov_counts:
            snap["provenance"] = _prov.block(prov_counts)
            # The /live row's one-glance answer to "why unknown".
            snap["dominant_unknown_cause"] = _prov.dominant(prov_counts)
        if t.resumed is not None:
            snap["resumed_from_journal"] = dict(t.resumed)
        if t.segmenter.dropped_covered:
            # Resubmitted ops at/below the stream's high-water mark
            # the server dropped — the restored-journal floor, or a
            # LIVE stream's lost-response/rewind overlap (re-checking
            # either from the current carries could flip a verdict:
            # the resume protocol is enforced, not trusted).
            snap["resubmitted_ops_dropped"] = \
                t.segmenter.dropped_covered
        if t.journal is not None and t.journal.append_failures:
            # Durability (not verdict) is compromised: a crash now
            # would lose more than the journaled watermark admits.
            snap["journal_append_failures"] = t.journal.append_failures
            snap["degraded"] = True
        if t.detection is not None:
            snap.update(t.detection)
        return snap

    def live_snapshot(self) -> dict:
        """One point-in-time operational view — the web ``/live``
        line: service totals plus one row per tenant (watermark,
        queue/backlog depths, verdict, per-tenant decision latency).
        Tenants are listed in REGISTRATION order (stable across
        polls)."""
        with self._tlock:
            items = sorted(self._tenants.items(),
                           key=lambda kv: kv[1].registered_at)
        rows = {name: self.tenant_snapshot(name) for name, _t in items}
        totals_obs = sum((r or {}).get("ops_observed") or 0
                         for r in rows.values())
        doc = {
            "run": self.name,
            "service": True,
            "t": round(_time.time(), 3),
            "draining": self._draining,
            "tenant_count": len(rows),
            "ops_observed": totals_obs,
            "scheduler_backlog": self.scheduler.backlog,
            "queue_depths": self.scheduler.queue_depths(),
            "decision_latency": self._lat.stats(),
            "tenants": rows,
        }
        if self.alert_engine is not None:
            # The /live badge row: which rules are firing right now.
            doc["alerts"] = sorted(self.alert_engine.firing())
        return doc

    # -- drain / shutdown ----------------------------------------------------

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted op has been fed through its
        segmenter AND the scheduler has decided everything submitted —
        the tests'/bench's sync point (drain() is the terminal one)."""
        deadline = ((_time.monotonic() + timeout)
                    if timeout is not None else None)
        while True:
            with self._tlock:
                tenants = list(self._tenants.values())
            settled = all(t.queue.qsize() == 0 for t in tenants)
            if settled:
                for t in tenants:
                    with t.lock:
                        if t.ops_observed != t.ops_ingested:
                            settled = False
                            break
            if settled and self.scheduler.wait_idle(0.05):
                return True
            self._wake.set()
            if deadline is not None and _time.monotonic() > deadline:
                return False
            _time.sleep(0.002)

    def drain(self, timeout: Optional[float] = 120.0) -> dict:
        """Graceful shutdown: stop admitting, flush every tenant's
        queue through its segmenter, fold the terminal segments, close
        the shared scheduler, and return per-tenant partial results.
        Idempotent — a second (or concurrent: the CLI's Ctrl-C racing
        an HTTP POST /drain) call returns the first result."""
        with self._drain_lock:
            return self._drain_locked(timeout)

    def _drain_locked(self, timeout: Optional[float]) -> dict:
        if self._finished is not None:
            return self._finished
        deadline = ((_time.monotonic() + timeout)
                    if timeout is not None else None)
        with self._tlock:
            self._draining = True
            tenants = list(self._tenants.values())
        # Stop the pump and flush the accepted backlog synchronously:
        # deterministic in-order feeding per tenant, immune to a
        # stalled/dead pump, and the scheduler keeps deciding
        # concurrently underneath. The pump MUST actually be gone
        # before drain touches the segmenters — two concurrent feeders
        # would corrupt them — so if it outlives the deadline (a
        # pathologically slow sweep), the sync flush and the terminal
        # fold are SKIPPED; the unfed ops surface as undelivered_ops.
        self._pump_stop.set()
        self._wake.set()
        while self._pump_thread.is_alive():
            self._pump_thread.join(0.1)
            if deadline is not None and _time.monotonic() > deadline:
                break
        pump_gone = not self._pump_thread.is_alive()
        if not pump_gone:
            LOG.warning("service pump still running at the drain "
                        "deadline; skipping the synchronous flush")
        for t in (tenants if pump_gone else ()):
            # Anything still queued past the deadline is reported,
            # never silently dropped.
            while True:
                if deadline is not None and _time.monotonic() > deadline:
                    break
                try:
                    item = t.queue.get_nowait()
                except queue.Empty:
                    break
                self._feed(t, item)
            tail = t.segmenter.finish()
            if tail:
                try:
                    self.scheduler.submit(tail, stream=t.name)
                except RuntimeError:
                    LOG.warning("scheduler closed before tenant %s's "
                                "terminal segment", t.name)
        left = (max(deadline - _time.monotonic(), 1.0)
                if deadline is not None else None)
        self.scheduler.close(timeout=left)
        wall = _time.monotonic() - self._t0
        results: dict[str, dict] = {}
        for t in tenants:
            res = self.scheduler.stream_result(t.name)
            lat = self._lat.stats(labels={"tenant": t.name})
            with t.lat_lock:
                lat["undecided_ops"] = len(t.lat_pending)
            with t.lock:
                out = {
                    "valid": res["valid"],
                    "ops_ingested": t.ops_ingested,
                    "ops_observed": t.ops_observed,
                    "rejected": dict(t.rejected),
                }
                # Count-based, not a queue-size snapshot: an op whose
                # blocked put() raced past the flush (or one stranded
                # by a skipped flush) is ACCEPTED-but-unfed and must
                # surface here, not vanish.
                undelivered = t.ops_ingested - t.ops_observed
            out.update({
                "decided_through_index": res["decided_through_index"],
                "segments_decided": res["segments_decided"],
                "aborted": t.aborted.is_set(),
                "decision_latency": lat,
                "segments": res["segments"],
            })
            svc_causes: list = []
            if undelivered > 0:
                out["undelivered_ops"] = undelivered
                # A queue truncated by the drain deadline means the
                # verdict covers only the observed prefix.
                out["info"] = ("drain deadline truncated the stream; "
                               "verdict covers the observed prefix")
                svc_causes.append(_prov.cause("undelivered_ops",
                                              count=undelivered))
            if t.lost_segments and out["valid"] is True:
                # Segments were dropped at a closed scheduler: a
                # definite True must cover the whole stream, and this
                # one cannot. (An invalid verdict stands — the
                # refutation evidence is real regardless.)
                out["valid"] = "unknown"
                out["info"] = ("segments lost after scheduler close; "
                               "verdict degraded to unknown")
            if t.lost_segments:
                svc_causes.append(_prov.cause("lost_segments"))
            # Per-tenant provenance: the scheduler's per-stream cause
            # union plus the service-layer degradations above.
            prov_counts = _prov.add_counts(dict(
                (res.get("provenance") or {}).get("causes") or {}),
                svc_causes)
            with t.lock:
                taints = dict(t.taints)
            if taints:
                # Ingest taints (unexplained trace lines behind the
                # ?adapter= front door): the checked history is
                # incomplete, so BOTH a definite True (a dropped write
                # could be the anomaly) and a definite False (a
                # dropped write could explain the impossible read)
                # fold to unknown. One-sided — never a flip.
                out["tainted_ops"] = int(sum(taints.values()))
                if out["valid"] != "unknown":
                    out["valid"] = "unknown"
                    out["info"] = ("ingest taints (unexplained trace "
                                   "lines); verdict degraded to "
                                   "unknown")
                svc_causes.extend(
                    _prov.cause(code, count=int(n))
                    for code, n in sorted(taints.items()))
                prov_counts = _prov.merge_counts(
                    prov_counts,
                    {code: int(n) for code, n in taints.items()})
            if out["valid"] not in (True, False) and not prov_counts:
                # The one unknown no segment record explains: work
                # still in flight when the drain deadline closed the
                # scheduler (undecided ≠ degraded, but the tenant's
                # answer is still unknown and must say why).
                dl = _prov.cause("deadline")
                svc_causes.append(dl)
                _prov.add_counts(prov_counts, [dl])
            if svc_causes:
                _prov.count_metric(self.metrics, svc_causes,
                                   tenant=t.name)
            if prov_counts:
                out["provenance"] = _prov.block(prov_counts)
            if t.resumed is not None:
                out["resumed_from_journal"] = dict(t.resumed)
            if t.segmenter.dropped_covered:
                out["resubmitted_ops_dropped"] = \
                    t.segmenter.dropped_covered
            if t.journal is not None:
                if t.journal.append_failures:
                    out["journal_append_failures"] = \
                        t.journal.append_failures
                t.journal.close()
            if t.detection is not None:
                out.update(t.detection)
            if res.get("violation") is not None:
                out["violation"] = res["violation"]
            results[t.name] = out
        if self.config.register_live:
            try:
                from .. import web

                web.unregister_live_source(self.name)
            except Exception:  # noqa: BLE001
                pass
        if self.alert_engine is not None:
            # One final pass (the pump is gone) so a condition that
            # only materialized during drain still transitions, then
            # seal the journal.
            self._next_alert_eval = 0.0
            self._maybe_evaluate_alerts()
            self.alert_engine.close()
        fin = {
            "service": self.name,
            "tenants": results,
            "tenant_count": len(results),
            "wall_s": round(wall, 3),
            "valid": self._merge(results),
            # Service-wide latency (the aggregate child): the bench
            # leg's p99 — per-tenant p99s don't compose into it.
            "decision_latency": self._lat.stats(),
        }
        run_prov = _prov.block(_prov.merge_counts(
            *((r.get("provenance") or {}).get("causes")
              for r in results.values())))
        if run_prov is not None:
            fin["provenance"] = run_prov
        self._finished = fin
        if self.config.ledger:
            self._append_ledger(results, wall)
        return fin

    def _merge(self, results: dict) -> Any:
        # The one safety-critical fold, shared with every other path
        # (checker.clj:33-47 priority: False > unknown > True).
        from ..checker import merge_valid

        return merge_valid(r.get("valid") for r in results.values())

    def _append_ledger(self, results: dict, wall: float) -> None:
        """One ledger record per tenant stream (kind="service") — the
        cross-run trend the /runs page and `ledger --check` gate."""
        try:
            from ..telemetry import ledger as jledger

            path = jledger.default_path(self.config.store_root)
            engine = self.config.engine
            for tenant, r in results.items():
                rec = {
                    "kind": "service",
                    "run": f"{self.name}/{tenant}",
                    "workload": "service_stream",
                    "engine": engine,
                    "ops": r.get("ops_observed"),
                    "verdict": str(r.get("valid")),
                }
                if wall > 0 and r.get("ops_observed"):
                    rec["ops_per_s"] = round(
                        r["ops_observed"] / wall, 1)
                p99 = (r.get("decision_latency") or {}).get("p99_s")
                if p99 is not None:
                    rec["p99_decision_latency_s"] = p99
                prov = r.get("provenance")
                if prov:
                    # The cross-run trend's why-unknown column: the
                    # advisor joins this with the perf metrics.
                    rec["dominant_cause"] = prov.get("dominant")
                    rec["causes"] = prov.get("causes")
                jledger.append(rec, path=path)
        except Exception:  # noqa: BLE001 - the ledger never sinks drain
            LOG.warning("service ledger append failed", exc_info=True)
