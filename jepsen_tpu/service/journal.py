"""Crash-safe per-tenant verdict journal.

A service crash used to lose every per-tenant verdict: reconnecting
clients had to resubmit their whole history. The journal is the
durability layer underneath the fold — one append-only JSONL file per
tenant under ``journal_dir``, one record per decided segment, written
from the scheduler's ``on_segment`` hook *inside the fold lock* (so a
journaled watermark can never run ahead of the in-memory fold state).
On restart, :func:`replay` reconstructs each tenant's watermark,
verdict counters, violation witness and per-key carried end-state
sets; the service seeds its segmenter and the scheduler's stream state
from them (``SegmentScheduler.restore_stream``), and a reconnecting
client reads its watermark from ``GET /tenants``
(``resumed_from_journal``) and resumes submitting from there instead
of resubmitting history.

File format (``<journal_dir>/<quoted tenant>.jsonl``):

- line 1 — ``{"kind": "header", "v": 1, "tenant": …, "model": {…}}``.
  The model identity is the kernel-cache identity
  (``Model.cache_key()`` + ``cache_args()``); replaying a journal
  against a different model family raises the TYPED
  :class:`JournalModelMismatchError` — a cas-register journal must
  never silently seed a queue fold.
- one ``{"kind": "segment", …}`` line per decided segment: the
  display row (seq, key repr, verdict, index range, terminal) plus
  the stream watermark AFTER this segment and the key's new carry —
  the decoded (table-independent) end-state set, ``"unknown"`` where
  the carry was lost, or absent for terminal segments. Keys and
  states are JSON-round-tripped (tuples survive via a freeze/thaw
  codec); a key or state the codec cannot round-trip journals
  ``carry_ok: false`` and replays as a LOST carry — the one-sided
  degradation again, never a wrong state.

Torn final lines — the signature of a kill-9 mid-append — are
expected: replay stops at the first unparseable line and keeps the
prefix (every complete record was written under the fold lock, so any
prefix is a consistent fold state). Append failures (disk full, the
``journal.fsync`` chaos seam) are counted and swallowed: the journal
loses durability, never a verdict.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Optional
from urllib.parse import quote, unquote

from ..checker import provenance as _prov
from ..models import Model
from ..online.segmenter import SINGLE_KEY
from ..testing import chaos as _chaos

LOG = logging.getLogger("jepsen.service")

FORMAT_VERSION = 1

# Display rows kept by replay (the fold counters stay exact): matches
# SegmentScheduler.max_segment_rows' default bounded table.
MAX_REPLAY_ROWS = 2000


class JournalError(RuntimeError):
    """Base class of journal read/replay failures."""


class JournalModelMismatchError(JournalError):
    """The journal was written for a different model family — its
    carried states are meaningless under this fold's model."""


# ---------------------------------------------------------------------------
# JSON round-trip codec: the decoded (semantic) states and keys are
# tuples-of-hashables; JSON has no tuples, so freeze→lists on write and
# thaw→tuples on read. Anything the codec can't round-trip EXACTLY
# (sets, exotic objects) degrades to a lost carry, never a wrong one.


def _jsonable(v: Any) -> Any:
    """Tuples→lists, recursively; raises TypeError on the
    un-round-trippable (actual lists would thaw into tuples and change
    identity, so they are refused too — decoded states never contain
    them)."""
    if isinstance(v, tuple):
        return [_jsonable(x) for x in v]
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    raise TypeError(f"not journal-round-trippable: {type(v).__name__}")


def _thaw(v: Any) -> Any:
    if isinstance(v, list):
        return tuple(_thaw(x) for x in v)
    return v


def model_identity(model: Model) -> dict:
    """The journal header's model identity — the same identity the
    device kernel cache keys on, so "same family" here means "same
    fold behavior"."""
    return {
        "name": model.name,
        "key": _jsonable(tuple(model.cache_key())),
        "args": _jsonable(tuple(model.cache_args())),
    }


def tenant_path(journal_dir: str, tenant: str) -> str:
    """Filesystem-safe per-tenant journal path (tenant names are an
    external input; percent-quote everything non-alphanumeric)."""
    return os.path.join(journal_dir, quote(tenant, safe="") + ".jsonl")


def scan(journal_dir: str) -> dict[str, str]:
    """tenant -> journal path, for every journal file present."""
    out: dict[str, str] = {}
    try:
        names = sorted(os.listdir(journal_dir))
    except FileNotFoundError:
        return out
    for name in names:
        if name.endswith(".jsonl"):
            out[unquote(name[:-len(".jsonl")])] = os.path.join(
                journal_dir, name)
    return out


class ConsistentLines:
    """The ONE torn-final-line reader every append-only jsonl replay
    in the service shares (the tenant journal here, the router's
    ``router_state.jsonl`` in service/supervisor.py — a rule patched
    in one copy must not silently leave the other wrong). Iterates
    the parseable JSON-dict records of the file's consistent prefix;
    after iteration ``.torn`` says whether a torn tail was dropped
    and ``.consistent_bytes`` is the exact byte length of that prefix
    (the reopening writer's truncation offset).

    Torn = the kill-9 signature: a final line missing its newline
    (even when its bytes happen to parse — appending after it would
    garble the next record, and the garbled line would make the NEXT
    replay silently drop every later record), an undecodable or
    unparseable line, or a non-dict record. Replay stops there; an
    append-only writer cannot have put reachable records after it."""

    def __init__(self, path: str):
        self.path = path
        self.torn = False
        self.consistent_bytes = 0

    def __iter__(self):
        with open(self.path, "rb") as f:
            for raw in f:
                if not raw.endswith(b"\n"):
                    self.torn = True
                    LOG.warning("%s: final line lacks its newline; "
                                "dropping the torn tail", self.path)
                    return
                try:
                    line = raw.decode("utf-8").strip()
                except UnicodeDecodeError:
                    self.torn = True
                    return
                if not line:
                    self.consistent_bytes += len(raw)
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    self.torn = True
                    LOG.warning("%s: torn line; replaying the "
                                "consistent prefix", self.path)
                    return
                if not isinstance(rec, dict):
                    self.torn = True
                    return
                self.consistent_bytes += len(raw)
                yield rec


class TenantJournal:
    """The append side: one open file, one record per decided segment.
    ``append_segment`` is called from the scheduler worker under the
    fold lock; it must be cheap (one line-buffered write) and must
    NEVER raise into the fold (failures are counted on the instance
    and logged)."""

    def __init__(self, path: str, tenant: str, model: Model,
                 fsync: bool = False, fresh_header: bool = True,
                 truncate: bool = False,
                 truncate_to: Optional[int] = None):
        self.path = path
        self.tenant = tenant
        self.fsync = fsync
        self.append_failures = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # A torn FINAL line has no trailing newline: appending straight
        # after it would garble the next record onto the fragment, and
        # the garbled line would stop the NEXT replay early (silently
        # dropping every later record). ``truncate_to`` cuts the file
        # back to replay's consistent prefix first; ``truncate``
        # discards it entirely (reopening over a torn-HEADER file
        # replay deemed empty).
        if truncate_to is not None and not truncate:
            try:
                with open(path, "r+b") as tf:
                    tf.truncate(truncate_to)
            except FileNotFoundError:
                pass
        # Line-buffered append: a complete record is flushed to the OS
        # per call (fsync additionally forces it to disk); a kill-9
        # mid-write leaves at most one torn FINAL line, which replay
        # tolerates (and the next reopen trims).
        self._f = open(path, "w" if truncate else "a", buffering=1,
                       encoding="utf-8")
        if fresh_header:
            self._write({"kind": "header", "v": FORMAT_VERSION,
                         "tenant": tenant,
                         "model": model_identity(model)})

    def _write(self, rec: dict) -> None:
        _chaos.fire("journal.fsync")
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")
        if self.fsync:
            os.fsync(self._f.fileno())

    def append_segment(self, row: dict, key: Any, carry: Any,
                       watermark: int) -> bool:
        """One decided-segment record; returns False on a swallowed
        append failure (durability lost, verdict unaffected)."""
        rec = {
            "kind": "segment",
            "seq": row.get("seq"),
            "key": row.get("key"),  # repr'd display key
            "ops": row.get("ops"),
            "start_index": row.get("start_index"),
            "end_index": row.get("end_index"),
            "terminal": bool(row.get("terminal")),
            "valid": row.get("valid"),
            "watermark": int(watermark),
        }
        if row.get("info"):
            rec["info"] = row["info"]
        if row.get("causes"):
            # The structured why-unknown provenance rides the journal,
            # so a restart restores the cause Pareto (cause params are
            # JSON scalars by construction). `cause_counts` carries
            # the EXACT counts when the display list was truncated.
            rec["causes"] = row["causes"]
            if row.get("cause_counts"):
                rec["cause_counts"] = row["cause_counts"]
        if self.append_failures:
            # A prior append was swallowed: every later record admits
            # it, so replay can tell a mid-stream GAP (stale carries,
            # possibly a lost invalid verdict) from a clean journal —
            # a gap must degrade the restored fold, never restore a
            # definite True over records that never landed.
            rec["after_append_failure"] = True
        # Every record carries its exact key (terminal ones too: a
        # replayed terminal segment must INVALIDATE the key's earlier
        # carry — its effects are not enumerable, so ops submitted
        # after a post-drain restart would otherwise be checked from a
        # state missing them). An un-round-trippable KEY journals a
        # repr only (replay cannot address it and poisons the stream's
        # carries).
        try:
            key_enc = ({"single": True} if key == SINGLE_KEY
                       else {"k": _jsonable(key)})
        except TypeError:
            key_enc = {"repr": str(row.get("key"))}
        rec["key_enc"] = key_enc
        if not row.get("terminal"):
            # The key's carry AFTER this segment, round-tripped for
            # replay; un-round-trippable STATES under a good key lose
            # only THAT key's carry ("unknown").
            rec["carry_ok"] = "repr" not in key_enc
            if rec["carry_ok"]:
                try:
                    rec["carry"] = (
                        "unknown" if carry == "unknown"
                        else None if carry is None
                        else [_jsonable(s) for s in carry])
                except TypeError:
                    rec["carry"] = "unknown"
        try:
            self._write(rec)
            return True
        except Exception:  # noqa: BLE001 - durability only, never fold
            self.append_failures += 1
            LOG.warning("journal append failed for tenant %s (%d so "
                        "far); verdicts unaffected", self.tenant,
                        self.append_failures, exc_info=True)
            return False

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # noqa: BLE001
            pass


def replay(path: str, model: Model) -> dict:
    """Reconstruct one tenant's fold state from its journal.

    Returns the kwargs shape ``SegmentScheduler.restore_stream``
    takes, plus ``tenant``/``records``/``torn_tail``/``degraded``/
    ``consistent_bytes``/``fresh``. Raises
    :class:`JournalModelMismatchError` when the header names a
    different model family, :class:`JournalError` when the file has no
    parseable header at all (a parseable non-header first record — a
    foreign file).

    Soundness of the restore:

    - Only records COVERED by the final journaled watermark (their
      ``end_index`` <= it) restore carries, seq numbering and fold
      counters: a record beyond the watermark belongs to a cut that
      was still partially decided at the crash — restoring its carry
      would hand the resubmitted ops their OWN post-states to check
      from (a verdict flip), and counting its valid verdict would let
      the fold claim definite True over the undecided sibling
      segments. Uncovered valid/unknown records are dropped (their
      ops sit above the watermark, so the resume protocol re-checks
      them from the committed carries); an uncovered INVALID record
      keeps its verdict and witness — refutation evidence is real
      regardless of coverage.
    - ``degraded`` (swallowed append failures admitted by later
      records, or a committed-seq gap) poisons carries and pins the
      restored fold off definite-True with one phantom unknown.
    - A torn FINAL line (kill-9 mid-append) is tolerated — replay
      keeps the consistent prefix, reports ``torn_tail: True`` and
      ``consistent_bytes`` (the byte length of that prefix) so the
      reopening writer can TRUNCATE the torn fragment instead of
      concatenating the next record onto it.
    """
    want = model_identity(model)
    header: Optional[dict] = None
    n_records = 0
    torn = False
    consistent_bytes = 0
    watermark = -1
    next_seq = 0
    carry: dict[Any, Any] = {}
    carry_poisoned = False
    cause_counts: dict[str, int] = {}
    degraded = False  # swallowed append failures / seq gaps
    seen_seqs: set = set()
    n_decided = n_invalid = n_unknown = 0
    violation: Optional[dict] = None
    segments: list[dict] = []
    # Records parsed but not yet covered by the watermark (segments of
    # cuts that were still in flight); folded in file order the moment
    # a later record's watermark covers them, dropped at EOF if never.
    pending: list[dict] = []

    def _fold(rec: dict) -> None:
        nonlocal next_seq, carry_poisoned, violation
        nonlocal n_decided, n_invalid, n_unknown
        n_decided += 1
        v = rec.get("valid")
        if v is False:
            n_invalid += 1
        elif v is not True:
            n_unknown += 1
        seq = rec.get("seq")
        if isinstance(seq, int):
            seen_seqs.add(seq)
            next_seq = max(next_seq, seq + 1)
        row = {k: rec.get(k) for k in
               ("seq", "key", "ops", "start_index", "end_index",
                "terminal", "valid")}
        row.update(engine="journal", members=0, wall_s=0.0,
                   info="replayed from journal")
        if rec.get("causes"):
            row["causes"] = rec["causes"]
            if rec.get("cause_counts"):
                # Exact counts outrank the bounded display list (a
                # many-member segment journals both).
                for code, cnt in rec["cause_counts"].items():
                    if isinstance(cnt, (int, float)):
                        cause_counts[code] = (cause_counts.get(code, 0)
                                              + int(cnt))
            else:
                _prov.add_counts(cause_counts, rec["causes"])
        elif v is not True and v is not False:
            # A pre-provenance journal (or a record written by a
            # taxonomy hole): the restored Pareto still accounts for
            # the unknown.
            _prov.add_counts(cause_counts, ["unattributed"])
        if len(segments) < MAX_REPLAY_ROWS:
            segments.append(row)
        if v is False and violation is None:
            violation = {"segment": dict(row), "refutation": None,
                         "replayed": True}
        ke = rec.get("key_enc") or {}
        if ke.get("single"):
            k = SINGLE_KEY
        elif "k" in ke:
            k = _thaw(ke["k"])
        else:
            k = None  # un-round-trippable (or pre-key_enc) key
        if rec.get("terminal"):
            # The terminal segment consumed ops whose effects no carry
            # enumerates: a later restart continuing this stream must
            # NOT check from the key's pre-terminal carry (stale — a
            # wrong-state refutation). Invalidate it; an unaddressable
            # key poisons the stream's carries wholesale.
            if k is None:
                carry_poisoned = True
            else:
                carry[k] = "unknown"
        else:
            c = rec.get("carry")
            if k is None or not rec.get("carry_ok"):
                # The key is known only by repr — it cannot be
                # addressed in the restored carry map, and a future
                # segment of it would otherwise check from the
                # model's INIT state, which could wrongly REFUTE.
                # Poison the whole restored stream's carries instead
                # (every future segment folds unknown): strictly
                # one-sided.
                carry_poisoned = True
            elif c == "unknown" or c is None:
                # Lost carry, or a segment journaled with no carry
                # recorded: unknown forward.
                carry[k] = "unknown"
            else:
                carry[k] = [_thaw(s) for s in c]

    # One streaming pass, bounded memory (the pending buffer holds at
    # most the in-flight cuts at the crash): the restore keeps the
    # fold COUNTERS exact for the committed prefix but only the first
    # MAX_REPLAY_ROWS display rows (mirroring the scheduler's own
    # bounded segment table). The shared torn-final-line reader
    # (ConsistentLines) decides what counts as the consistent prefix
    # — a dropped torn record's ops sit above the reported watermark,
    # so the resume protocol re-checks them: one-sided, never a flip.
    lines = ConsistentLines(path)
    for rec in lines:
        if header is None:
            if rec.get("kind") != "header":
                # A parseable first record that is NOT a header
                # means this is some other file (e.g.
                # --journal-dir pointed at a directory holding
                # ledger.jsonl): a misconfiguration the operator
                # must see, not silently replay.
                raise JournalError(
                    f"journal {path}: missing header record")
            if rec.get("v") != FORMAT_VERSION:
                raise JournalError(
                    f"journal {path}: unsupported format version "
                    f"{rec.get('v')!r}")
            if rec.get("model") != want:
                raise JournalModelMismatchError(
                    f"journal {path} was written for model "
                    f"{(rec.get('model') or {}).get('name')!r} "
                    f"{rec.get('model')!r}; this service folds "
                    f"{want!r} — refusing to seed carried states "
                    "across model families")
            header = rec
            continue
        n_records += 1
        if rec.get("kind") != "segment":
            continue
        if rec.get("after_append_failure"):
            degraded = True
        pending.append(rec)
        new_wm = int(rec.get("watermark", -1))
        if new_wm > watermark:
            watermark = new_wm
            still = []
            cover: dict = {}  # (seq, key) -> newest covered record
            for p in pending:  # file order preserved
                if int(p.get("end_index", -1)) <= watermark:
                    # Last-wins per (seq, key): after a crash, a
                    # resubmission re-decides an UNCOVERED cut
                    # under the same seq, and the next restart
                    # sees both the stale record and the fresh
                    # one — only the newest may fold (the stale
                    # one would double-count and, folded last,
                    # resurrect a stale carry).
                    cover[(p.get("seq"), p.get("key"))] = p
                else:
                    still.append(p)
            pending = still
            for p in cover.values():
                _fold(p)
    torn = lines.torn
    consistent_bytes = lines.consistent_bytes
    if header is None:
        # Empty file, or the HEADER line itself was torn (the process
        # died inside the very first write — an append-only writer
        # cannot have put records after it). This journal holds
        # nothing: replay as a FRESH tenant instead of bricking every
        # restart behind a file an operator must hand-delete.
        LOG.warning("journal %s: no usable records (empty or torn "
                    "header); treating as fresh", path)
        return {
            "tenant": "", "watermark": -1, "next_seq": 0, "carry": {},
            "carry_poisoned": False, "n_decided": 0, "n_invalid": 0,
            "n_unknown": 0, "violation": None, "segments": [],
            "cause_counts": {},
            "records": 0, "torn_tail": torn, "degraded": False,
            "consistent_bytes": 0, "fresh": True,
        }
    # Records never covered by the watermark: cuts in flight at the
    # crash. Their ops sit ABOVE the watermark, so the resume protocol
    # re-checks them from the committed carries — dropping the
    # valid/unknown ones loses nothing and keeps the restored fold
    # honest (a kept valid verdict would claim definite True over the
    # undecided sibling segments of the same cut). An INVALID one
    # keeps its verdict and witness: refutation evidence is real
    # whether or not the cut completed.
    for p in pending:
        if p.get("valid") is False:
            # Verdict + witness only: its seq must NOT extend the
            # restored numbering (the cut never completed — counting
            # it would fake a committed-prefix gap), and its carry is
            # irrelevant to an invalid stream.
            n_decided += 1
            n_invalid += 1
            row = {k: p.get(k) for k in
                   ("seq", "key", "ops", "start_index", "end_index",
                    "terminal", "valid")}
            row.update(engine="journal", members=0, wall_s=0.0,
                       info="replayed from journal (uncovered cut)")
            if len(segments) < MAX_REPLAY_ROWS:
                segments.append(row)
            if violation is None:
                violation = {"segment": dict(row), "refutation": None,
                             "replayed": True}
        else:
            LOG.info("journal %s: dropping uncovered record "
                     "(seq %s, key %s) — its cut was still in flight",
                     path, p.get("seq"), p.get("key"))
    if seen_seqs and seen_seqs != set(range(next_seq)):
        # A mid-stream seq GAP in the COMMITTED prefix can only come
        # from a swallowed append failure (the file is append-only; a
        # kill-9 truncates the tail, it cannot punch holes). The
        # missing cut may have moved a carry — or held the stream's
        # only invalid verdict.
        degraded = True
    if degraded:
        # One-sided restore: carries may be stale (poison them all)
        # and a lost record could have been invalid, so the restored
        # fold must never report a definite True — one phantom
        # unknown pins it (provenance: journal_gap). Journaled invalid
        # verdicts still stand (their refutation evidence is real
        # regardless).
        carry_poisoned = True
        n_unknown += 1
        n_decided += 1
        _prov.add_counts(cause_counts, [_prov.cause("journal_gap")])
        LOG.warning("journal %s: append-failure gap detected; "
                    "restoring with poisoned carries and an unknown "
                    "fold", path)
    return {
        "tenant": header.get("tenant") or "",
        "watermark": watermark,
        "next_seq": next_seq,
        "carry": carry,
        "carry_poisoned": carry_poisoned,
        "n_decided": n_decided,
        "n_invalid": n_invalid,
        "n_unknown": n_unknown,
        "violation": violation,
        "segments": segments,
        "cause_counts": cause_counts,
        "records": n_records,
        "torn_tail": torn,
        "degraded": degraded,
        "consistent_bytes": consistent_bytes,
    }
