"""Always-on multi-tenant checking service (ROADMAP item 3).

A resident :class:`Service` ingests many concurrent tenant streams
(ndjson-over-HTTP via :mod:`jepsen_tpu.service.http`, or the in-process
``Service.submit(tenant, op)`` seam), segments each live with one
``online`` segmenter per tenant, and co-batches ready segments ACROSS
tenants onto the shared PR-2 batched device pipeline through one
:class:`~jepsen_tpu.online.scheduler.SegmentScheduler` — P-composition
makes keys independent, and tenants are one more independence axis, so
the batch fills from whoever has work while per-tenant verdict, carry,
and watermark isolation hold (the co-batching contract, pinned
differentially in tests/test_service.py).

Production controls: admission (``max_tenants``, per-tenant ops/s
quota), bounded per-tenant ingest queues with blocking or 429-style
reject backpressure, per-tenant round fairness, per-tenant
abort-on-violation isolation, and a graceful ``drain`` returning
per-tenant partial verdicts. CLI: ``python -m jepsen_tpu.service``.
See docs/service.md.
"""

from __future__ import annotations

from .journal import (  # noqa: F401
    JournalError,
    JournalModelMismatchError,
)
from .service import (  # noqa: F401
    AdmissionError,
    AdoptUnsupportedError,
    IngestQueueFullError,
    QuotaExceededError,
    Service,
    ServiceClosedError,
    ServiceConfig,
    ServiceError,
    StaleEpochError,
    TenantAbortedError,
    TenantAdoptConflictError,
    TenantLimitError,
    TenantMigratedError,
    TenantMigratingError,
    UnknownTenantError,
)

__all__ = [
    "AdmissionError",
    "AdoptUnsupportedError",
    "IngestQueueFullError",
    "JournalError",
    "JournalModelMismatchError",
    "QuotaExceededError",
    "Service",
    "ServiceClosedError",
    "ServiceConfig",
    "ServiceError",
    "StaleEpochError",
    "TenantAbortedError",
    "TenantAdoptConflictError",
    "TenantLimitError",
    "TenantMigratedError",
    "TenantMigratingError",
    "UnknownTenantError",
]
