"""Tenant router: horizontal scale-out for the checking service.

One :class:`~jepsen_tpu.service.service.Service` process lives or dies
as a unit — PR 10 made a *restart* of that unit lossless (the
per-tenant verdict journal is the tenant's complete checkpoint), and
this module cashes that enabler in for *horizontal* resilience
(ROADMAP item 3): a front-end that places tenants across N backend
service processes (each with its own scheduler/mesh slice and its own
``--journal-dir``) and survives losing an ENTIRE backend the same way
the single process survives a restart — by journal replay, one-sided,
never a flipped verdict.

The pieces:

- **Sticky placement** — a tenant's first submit places it on the
  least-loaded live backend; every later submit proxies to the same
  backend (the fold is stateful; bouncing a tenant would fork it).
- **Health checking** — a probe loop GETs each backend's ``/healthz``
  (now carrying per-tenant backlog / ``journal_lag_ops`` / degraded
  flags) under a deadline, feeding a per-backend
  :class:`~jepsen_tpu.parallel.resilience.CircuitBreaker`:
  ``failure_threshold`` consecutive failures open the circuit and the
  backend is declared LOST (a spawned child's exit is detected
  directly).
- **Journal-backed migration** — losing a backend (or an overload
  rebalance) moves each of its tenants: quiesce + ``POST
  /release/<tenant>`` on a live source (the journal handover), or —
  when the backend is dead — read the journal straight from its
  ``--journal-dir`` (the journal IS the checkpoint; there is nothing
  else to save), then ``POST /adopt/<tenant>`` on the target (replay
  behind admission) and atomically flip placement. Clients mid-stream
  get 503 + ``Retry-After`` and resume from the journaled watermark
  exactly as after a PR-10 restart; resubmitted covered ops are
  dropped server-side. Soundness is the PR-5/PR-10 quiescent-cut
  argument: every journal record ends at a cut carrying the exact
  feasible end-state set, so the target re-decides nothing that was
  covered and checks everything above the watermark from the carried
  states.
- **Load-adaptive rebalancing** — :func:`plan_rebalance` is a pure
  function over the ``/healthz`` overload signals (scheduler backlog,
  queue depths, ``journal_lag_ops``); when one backend's load exceeds
  the least-loaded's by ``rebalance_ratio`` (and an absolute floor),
  the heaviest tenant is live-migrated off it.
- **Failure attribution** — a tenant that cannot be migrated (no
  target, no checkpoint, adopt refused, ``JEPSEN_NO_MIGRATION=1``) is
  ORPHANED: its router-level row folds ``unknown`` with the typed
  ``backend_lost`` / ``migration_interrupted`` causes
  (checker/provenance.py) — degraded one-sidedly, never flipped.
- **Chaos seams** — ``router.probe`` (an injected raise counts as a
  failed health probe: the false-positive path) and
  ``backend.process`` (the router SIGKILLs one of its own spawned
  backend children: a real kill-9 of a real process).

``JEPSEN_NO_MIGRATION=1`` is the operational kill-switch: no
migrations, no rebalancing — dead backends simply orphan their
tenants (checked per attempt, like every other kill-switch).

Telemetry: ``router_placements_total{backend}``,
``router_migrations_total{reason}``,
``router_failed_probes_total{backend}``, ``router_orphaned_tenants``,
``router_migration_seconds``. The router registers on the web
``/live`` feed and aggregates ``/tenants`` across backends. See
docs/service.md "Scale-out & migration".
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time as _time
from dataclasses import dataclass, replace
from typing import Any, Optional
from urllib import error as _uerror
from urllib import request as _urequest
from urllib.parse import parse_qs, quote, unquote, urlsplit

from ..checker import provenance as _prov
from ..parallel import resilience as _resilience
from ..testing import chaos as _chaos
from . import journal as _journal

LOG = logging.getLogger("jepsen.router")

MIGRATION_SECONDS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                             10.0, 30.0, 60.0)


def migration_disabled() -> bool:
    """``JEPSEN_NO_MIGRATION=1`` — checked per attempt, so flipping the
    env in a live router takes effect (the kill-switch contract)."""
    return os.environ.get("JEPSEN_NO_MIGRATION", "") == "1"


class NoBackendError(RuntimeError):
    """No live backend is available to place a tenant on."""


@dataclass(frozen=True)
class RouterConfig:
    """Router policy knobs."""

    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    # Consecutive probe failures before a backend's circuit opens and
    # it is declared lost (resilience.CircuitBreaker semantics; the
    # cooldown paces half-open re-probes of a backend that may heal).
    failure_threshold: int = 3
    probe_cooldown_s: float = 30.0
    http_timeout_s: float = 10.0
    release_timeout_s: float = 30.0
    drain_timeout_s: float = 120.0
    # Retry-After hint on migration/unreachable 503s: a migration is a
    # release+replay+flip, normally sub-second at bench scale.
    migrate_retry_after_s: float = 1.0
    # Load-adaptive rebalancing off the /healthz overload signals.
    rebalance: bool = True
    rebalance_min_load: float = 256.0
    rebalance_ratio: float = 4.0
    # journal_lag_ops (ops) -> load units (undecided segments are the
    # base unit; ~100 ops of journal lag weigh like one segment).
    lag_weight: float = 0.01
    register_live: bool = True


class Backend:
    """One backend service process as the router sees it."""

    def __init__(self, name: str, url: str,
                 journal_dir: Optional[str] = None,
                 proc: Optional[subprocess.Popen] = None,
                 metrics=None, failure_threshold: int = 3,
                 cooldown_s: float = 30.0) -> None:
        self.name = name
        self.url = url.rstrip("/")
        self.journal_dir = journal_dir
        self.proc = proc
        # One breaker per backend: the consecutive-failure /
        # cooldown / half-open-probe protocol is exactly the device
        # path's (parallel/resilience.py) with "device" = "backend".
        self.breaker = _resilience.CircuitBreaker(
            f"router:{name}", failure_threshold=failure_threshold,
            cooldown_s=cooldown_s, metrics=metrics)
        self.health: Optional[dict] = None  # last good /healthz doc
        self.down = False  # declared lost; tenants migrated away

    def snapshot(self) -> dict:
        out = {
            "url": self.url,
            "state": "lost" if self.down else self.breaker.state,
            "down": self.down,
        }
        if self.proc is not None:
            out["pid"] = self.proc.pid
            out["exited"] = self.proc.poll()
        if self.health is not None:
            out["tenant_count"] = self.health.get("tenant_count")
            out["scheduler_backlog"] = self.health.get(
                "scheduler_backlog")
        return out


# ---------------------------------------------------------------------------
# Pure rebalance planning (closed-form-testable; the advisor's
# rebalance_tenants rule applies the same load model to bench rounds).


def backend_load(health: Optional[dict],
                 lag_weight: float = 0.01) -> float:
    """One backend's load in scheduler-backlog units from its
    ``/healthz`` doc: undecided segments + queued ops + weighted
    journal lag (what a migration NOW would force clients to
    resubmit)."""
    h = health or {}
    tenants = h.get("tenants") or {}
    load = float(h.get("scheduler_backlog") or 0)
    for row in tenants.values():
        row = row or {}
        load += float(row.get("queue_depth") or 0)
        load += lag_weight * float(row.get("journal_lag_ops") or 0)
    return load


def tenant_load(row: Optional[dict], lag_weight: float = 0.01) -> float:
    r = row or {}
    return (float(r.get("backlog") or 0)
            + float(r.get("queue_depth") or 0)
            + lag_weight * float(r.get("journal_lag_ops") or 0))


def plan_rebalance(health_by_backend: dict, placement: dict, *,
                   min_load: float = 256.0, ratio: float = 4.0,
                   lag_weight: float = 0.01
                   ) -> Optional[tuple[str, str, str]]:
    """Pick at most ONE (tenant, src, dst) live migration: fires only
    when the loaded backend exceeds both an absolute floor and
    ``ratio``× the least-loaded backend, and moves the heaviest tenant
    (deterministic tie-break). Pure — pinned closed-form in
    tests/test_router.py and mirrored by the advisor's
    ``rebalance_tenants`` rule."""
    if len(health_by_backend) < 2:
        return None
    loads = {n: backend_load(h, lag_weight)
             for n, h in health_by_backend.items()}
    src = max(sorted(loads), key=lambda n: loads[n])
    dst = min(sorted(loads), key=lambda n: loads[n])
    if src == dst:
        return None
    if loads[src] < min_load or loads[src] < ratio * (loads[dst] + 1.0):
        return None
    rows = (health_by_backend[src] or {}).get("tenants") or {}
    cands = [t for t, n in placement.items()
             if n == src and t in rows]
    if not cands:
        return None
    tenant = max(sorted(cands),
                 key=lambda t: tenant_load(rows[t], lag_weight))
    if tenant_load(rows[tenant], lag_weight) <= 0:
        return None
    return tenant, src, dst


# ---------------------------------------------------------------------------


class Router:
    """The scale-out front-end: sticky tenant placement over N backend
    service processes, health-checked, with journal-backed live
    migration. See the module docstring."""

    def __init__(self, backends: list[Backend],
                 config: Optional[RouterConfig] = None, *,
                 metrics=None, name: str = "router",
                 **overrides) -> None:
        cfg = config or RouterConfig()
        if overrides:
            cfg = replace(cfg, **overrides)
        if not backends:
            raise ValueError("router needs at least one backend")
        self.config = cfg
        self.metrics = metrics
        self.name = name
        self._backends: dict[str, Backend] = {}
        for b in backends:
            if b.name in self._backends:
                raise ValueError(f"duplicate backend name {b.name!r}")
            self._backends[b.name] = b
            # ONE source of truth for the probe-circuit policy: the
            # router's config re-arms every backend breaker, so a
            # Backend constructed with different defaults cannot
            # silently diverge from what the router believes (and
            # logs) about its own thresholds.
            b.breaker.failure_threshold = cfg.failure_threshold
            b.breaker.cooldown_s = cfg.probe_cooldown_s
        self._lock = threading.RLock()
        self._placement: dict[str, str] = {}  # tenant -> backend name
        self._migrating: set[str] = set()
        # tenant -> {"from": backend, "causes": {code: n}, "note": …}:
        # tenants the router could NOT move — their router-level rows
        # fold unknown with these causes, never a definite verdict.
        self._orphans: dict[str, dict] = {}
        self.migrations: list[dict] = []  # bounded audit trail
        self._draining = False
        self._finished: Optional[dict] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._health_loop, name="jepsen-router-health",
            daemon=True)
        self._thread.start()
        if cfg.register_live:
            try:
                from .. import web

                web.register_live_source(self.name, self.live_snapshot)
            except Exception:  # noqa: BLE001 - observability only
                LOG.warning("could not register router live source",
                            exc_info=True)

    # -- metrics -------------------------------------------------------------

    def _count_placement(self, backend: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "router_placements_total",
                "Tenant placements decided by the router (first "
                "placement + every migration flip), by backend",
                labelnames=("backend",)).labels(backend=backend).inc()

    def _count_failed_probe(self, backend: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "router_failed_probes_total",
                "Backend health probes that failed (timeout, refused, "
                "unhealthy, chaos-injected), by backend",
                labelnames=("backend",)).labels(backend=backend).inc()

    def _count_migration(self, reason: str, seconds: float) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "router_migrations_total",
                "Journal-backed tenant migrations completed, by reason "
                "(backend_lost / rebalance)",
                labelnames=("reason",)).labels(reason=reason).inc()
            self.metrics.histogram(
                "router_migration_seconds",
                "Wall seconds per tenant migration (checkpoint "
                "handover + adopt replay + placement flip)",
                buckets=MIGRATION_SECONDS_BUCKETS).observe(seconds)

    def _set_orphans_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "router_orphaned_tenants",
                "Tenants whose backend was lost and whose migration "
                "could not complete — their verdicts fold unknown "
                "(backend_lost / migration_interrupted)").set(
                    len(self._orphans))

    # -- backend HTTP --------------------------------------------------------

    def _request(self, b: Backend, path: str,
                 data: Optional[bytes] = None,
                 timeout: Optional[float] = None) -> tuple[int, dict]:
        """One backend call; never raises. status 0 = unreachable."""
        req = _urequest.Request(
            b.url + path, data=data,
            method="POST" if data is not None else "GET")
        try:
            with _urequest.urlopen(
                    req, timeout=timeout
                    or self.config.http_timeout_s) as r:
                doc = json.loads(r.read().decode() or "{}")
                return r.status, doc if isinstance(doc, dict) else {}
        except _uerror.HTTPError as e:
            try:
                doc = json.loads(e.read().decode() or "{}")
            except ValueError:
                doc = {}
            return e.code, doc if isinstance(doc, dict) else {}
        except Exception as e:  # noqa: BLE001 - dead socket, timeout
            return 0, {"error": "unreachable", "detail": str(e)}

    # -- placement + ingestion proxy -----------------------------------------

    def _place(self, tenant: str) -> Backend:
        with self._lock:
            name = self._placement.get(tenant)
            if name is not None:
                b = self._backends.get(name)
                if b is not None:
                    return b
            cands = [b for b in self._backends.values() if not b.down]
            if not cands:
                raise NoBackendError("no live backend to place on")
            # Prefer backends whose probe circuit is quiet: a breaker
            # opened by submit-path failures marks a backend the
            # supervision tick has not yet declared lost — placing a
            # NEW tenant there would just bounce. Fall back to any
            # not-down backend when every circuit is engaged.
            quiet = [b for b in cands if not b.breaker.engaged()]
            counts: dict[str, int] = {}
            for _t, n in self._placement.items():
                counts[n] = counts.get(n, 0) + 1
            b = min(quiet or cands,
                    key=lambda bb: (counts.get(bb.name, 0), bb.name))
            self._placement[tenant] = b.name
        self._count_placement(b.name)
        LOG.info("placed tenant %s on backend %s", tenant, b.name)
        return b

    def placement(self) -> dict[str, str]:
        with self._lock:
            return dict(self._placement)

    def submit(self, tenant: str, body: bytes) -> tuple[int, dict]:
        """Proxy one ndjson POST to the tenant's backend. Returns
        (status, response doc); 503s carry ``retry_after_s`` +
        ``retryable`` so the resume-aware client backs off and
        re-anchors on the journaled watermark."""
        cfg = self.config
        with self._lock:
            if self._draining:
                return 503, {"error": "draining", "tenant": tenant,
                             "accepted": 0, "retryable": False}
            migrating = tenant in self._migrating
            orphan = self._orphans.get(tenant)
        if orphan is not None:
            # The tenant's state is unrecoverable: the honest answer
            # is a terminal refusal, not a silent fresh stream that
            # would fork its history.
            return 503, {"error": "orphaned", "tenant": tenant,
                         "accepted": 0, "retryable": False,
                         "causes": dict(orphan.get("causes") or {})}
        if migrating:
            return 503, {"error": "migrating", "tenant": tenant,
                         "accepted": 0, "retryable": True,
                         "retry_after_s": cfg.migrate_retry_after_s}
        try:
            b = self._place(tenant)
        except NoBackendError:
            return 503, {"error": "no_backend", "tenant": tenant,
                         "accepted": 0, "retryable": True,
                         "retry_after_s": cfg.migrate_retry_after_s}
        status, doc = self._request(
            b, f"/submit/{quote(tenant, safe='')}", data=body)
        if status == 0:
            # Fast-path death detection: the proxy saw the dead socket
            # before the probe loop did. Feed the breaker and let the
            # supervision tick decide; the client retries against the
            # migrated placement.
            b.breaker.record_failure()
            self._count_failed_probe(b.name)
            return 503, {"error": "backend_unreachable",
                         "tenant": tenant, "accepted": 0,
                         "retryable": True,
                         "retry_after_s": cfg.migrate_retry_after_s}
        doc.setdefault("backend", b.name)
        return status, doc

    # -- health / supervision ------------------------------------------------

    def _probe(self, b: Backend) -> dict:
        # Chaos seam INSIDE the probe's failure domain: an injected
        # raise is indistinguishable from a timed-out /healthz — the
        # false-positive migration path under test.
        _chaos.fire("router.probe")
        with _urequest.urlopen(b.url + "/healthz",
                               timeout=self.config.probe_timeout_s) as r:
            doc = json.loads(r.read().decode() or "{}")
        if not isinstance(doc, dict) or not doc.get("ok"):
            raise RuntimeError(f"backend {b.name} unhealthy: {doc!r}")
        return doc

    def _chaos_kill_tick(self) -> None:
        """``backend.process``: an armed raise is the KILL ORDER — the
        router SIGKILLs one live spawned backend child (a real kill-9:
        torn journal line, dead socket) and then recovers through its
        own probe/migration machinery."""
        try:
            _chaos.fire("backend.process")
        except Exception:  # noqa: BLE001 - the armed fault
            victim = next(
                (b for b in self._backends.values()
                 if b.proc is not None and b.proc.poll() is None
                 and not b.down), None)
            if victim is None:
                LOG.warning("chaos backend.process fired with no live "
                            "spawned backend to kill")
                return
            LOG.warning("chaos: kill -9 backend %s (pid %d)",
                        victim.name, victim.proc.pid)
            victim.proc.kill()

    def _health_loop(self) -> None:
        while not self._stop.wait(self.config.probe_interval_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - supervision must survive
                LOG.warning("router health tick failed", exc_info=True)

    def _tick(self) -> None:
        self._chaos_kill_tick()
        for b in list(self._backends.values()):
            if b.down:
                continue
            if b.proc is not None and b.proc.poll() is not None:
                # A spawned child's exit needs no probe quorum.
                self._on_backend_down(
                    b, f"process exited rc={b.proc.poll()}")
                continue
            if b.breaker.state == "open":
                # The circuit can open BETWEEN ticks off submit-path
                # failures (the --backend-urls case with no child to
                # poll): without this, the tick would silently skip
                # the backend for a whole cooldown while clients
                # exhaust their retries against a dead placement.
                self._on_backend_down(
                    b, "circuit open (consecutive submit/probe "
                       "failures)")
                continue
            if not b.breaker.allow():
                continue  # open, cooldown pending: skip doomed probes
            try:
                doc = self._probe(b)
            except Exception as e:  # noqa: BLE001 - probe failure
                b.breaker.record_failure()
                self._count_failed_probe(b.name)
                LOG.warning("probe of backend %s failed (%s: %s)",
                            b.name, type(e).__name__, e)
                if b.breaker.state == "open":
                    self._on_backend_down(
                        b, "probe circuit open "
                        f"({self.config.failure_threshold} consecutive "
                        "failures)")
                continue
            b.breaker.record_success()
            b.health = doc
        if (self.config.rebalance and not self._draining
                and not migration_disabled()):
            self._maybe_rebalance()

    def _on_backend_down(self, b: Backend, why: str) -> None:
        if b.down:
            return
        b.down = True
        b.breaker.record_failure()
        LOG.warning("backend %s declared LOST (%s); migrating its "
                    "tenants", b.name, why)
        with self._lock:
            tenants = sorted(t for t, n in self._placement.items()
                             if n == b.name)
            self._migrating.update(tenants)
        for t in tenants:
            self._migrate(t, b, reason="backend_lost")

    # -- migration -----------------------------------------------------------

    def migrate(self, tenant: str, target: Optional[str] = None,
                reason: str = "manual") -> bool:
        """Operator/rebalance entry point: live-migrate one tenant off
        its current backend (release → adopt → flip)."""
        # Resolve and validate EVERYTHING before marking the tenant
        # migrating: a raise after the mark (with _migrate's finally
        # never entered) would wedge the tenant in 503-migrating
        # forever and stall rebalancing router-wide.
        with self._lock:
            src_name = self._placement.get(tenant)
            if src_name is None:
                raise KeyError(f"tenant {tenant!r} is not placed")
            src = self._backends[src_name]
            dst = None
            if target is not None:
                dst = self._backends.get(target)
                if dst is None:
                    raise KeyError(
                        f"unknown target backend {target!r}")
            if tenant in self._migrating:
                return False
            self._migrating.add(tenant)
        return self._migrate(tenant, src, reason=reason, target=dst)

    def _pick_target(self, exclude: Backend) -> Optional[Backend]:
        with self._lock:
            cands = [b for b in self._backends.values()
                     if not b.down and b.name != exclude.name]
            if not cands:
                return None
            counts: dict[str, int] = {}
            for _t, n in self._placement.items():
                counts[n] = counts.get(n, 0) + 1
            return min(cands,
                       key=lambda bb: (counts.get(bb.name, 0), bb.name))

    def _checkpoint(self, tenant: str, src: Backend
                    ) -> tuple[Optional[str], Optional[str]]:
        """Obtain the tenant's journal checkpoint: live release first
        (also the recovery from a FALSE-POSITIVE probe death — a
        healthy backend answers and quiesces), else off the source's
        journal_dir. Returns (journal_text, adopt_cause)."""
        # Socket timeout strictly ABOVE the backend's own quiesce
        # deadline: a release that takes the full quiesce window must
        # not be abandoned on the wire just as it completes.
        status, doc = self._request(
            src, f"/release/{quote(tenant, safe='')}", data=b"",
            timeout=self.config.release_timeout_s + 15.0)
        if status == 200 and isinstance(doc.get("journal"), str):
            return doc["journal"], None
        dead = src.down or (src.proc is not None
                            and src.proc.poll() is not None)
        path = (_journal.tenant_path(src.journal_dir, tenant)
                if src.journal_dir else None)
        if path and dead:
            # The backend is demonstrably gone: its journal file IS
            # the checkpoint (PR 10's whole point). Renamed after
            # reading so a RESTARTED backend on the same dir cannot
            # re-own a tenant that now lives elsewhere. NEVER taken
            # from a live backend (a transient connect blip must not
            # seize the file from under the owner's open fd — split
            # ownership).
            try:
                with open(path, "rb") as f:
                    data = f.read()
                try:
                    os.replace(path, path + ".migrated")
                except OSError:
                    pass
                return data.decode("utf-8", "replace"), "backend_lost"
            except OSError:
                pass
        if path:
            # Release may have COMPLETED server-side with the response
            # lost on the wire: the source then already renamed the
            # file `.migrated` and tombstoned the tenant — the renamed
            # file is a complete checkpoint nobody owns, safe to adopt
            # whether or not the process is alive. (A successful adopt
            # back onto this backend deletes the stale artifact, so a
            # leftover here always describes the LATEST release.)
            try:
                with open(path + ".migrated", "rb") as f:
                    return (f.read().decode("utf-8", "replace"),
                            "backend_lost" if dead else None)
            except OSError:
                pass
        return None, None

    def _migrate(self, tenant: str, src: Backend, reason: str,
                 target: Optional[Backend] = None) -> bool:
        t0 = _time.monotonic()
        entry: dict = {"tenant": tenant, "from": src.name,
                       "reason": reason, "ok": False}
        # Orphaning is for tenants whose SOURCE is gone (reason
        # backend_lost): a refused migration off a LIVE backend —
        # kill-switch, typo'd target, transient checkpoint failure —
        # must leave the tenant serving where it is, not destroy a
        # healthy stream behind a terminal 503 (review finding).
        lost = reason == "backend_lost"
        try:
            if migration_disabled():
                entry["error"] = "migration_disabled"
                if lost:
                    self._orphan(tenant, src,
                                 ["backend_lost",
                                  "migration_interrupted"],
                                 note="JEPSEN_NO_MIGRATION=1")
                return False
            dst = target if target is not None \
                else self._pick_target(exclude=src)
            if dst is None or dst.down:
                entry["error"] = "no_target"
                if lost:
                    self._orphan(tenant, src, ["backend_lost"],
                                 note="no live target backend")
                return False
            entry["to"] = dst.name
            jtext, cause = self._checkpoint(tenant, src)
            if jtext is None:
                entry["error"] = "no_checkpoint"
                if lost:
                    self._orphan(tenant, src, ["backend_lost"],
                                 note="no journal checkpoint "
                                      "recoverable")
                return False
            path = f"/adopt/{quote(tenant, safe='')}"
            if cause:
                path += f"?cause={quote(cause, safe='')}"
            status, doc = self._request(dst, path,
                                        data=jtext.encode("utf-8"))
            if status != 200:
                entry["error"] = (f"adopt_{status}_"
                                  f"{doc.get('error') or 'failed'}")
                # A live release already made the SOURCE forget the
                # tenant — the checkpoint now exists only in this
                # router's memory. Spill it next to the source's
                # journals so an operator can re-adopt by hand instead
                # of losing a recoverable stream.
                self._spill_checkpoint(tenant, src, jtext)
                self._orphan(
                    tenant, src,
                    ["backend_lost", "migration_interrupted"]
                    if reason == "backend_lost"
                    else ["migration_interrupted"],
                    note=f"adopt on {dst.name} failed: {status} "
                         f"{doc.get('error')}")
                return False
            with self._lock:
                self._placement[tenant] = dst.name
                # "Orphaned ... until a later migration succeeds"
                # (docs/verdicts.md): this IS the later migration — a
                # recovered tenant must serve again, not stay bricked
                # behind the stale orphan record.
                if self._orphans.pop(tenant, None) is not None:
                    self._set_orphans_gauge()
            self._count_placement(dst.name)
            entry["ok"] = True
            entry["watermark"] = doc.get("watermark")
            LOG.info("migrated tenant %s %s -> %s (%s, watermark %s)",
                     tenant, src.name, dst.name, reason,
                     doc.get("watermark"))
            return True
        finally:
            seconds = _time.monotonic() - t0
            entry["seconds"] = round(seconds, 4)
            with self._lock:
                self.migrations.append(entry)
                if len(self.migrations) > 1000:
                    del self.migrations[:-1000]
                self._migrating.discard(tenant)
            if entry["ok"]:
                self._count_migration(reason, seconds)

    def _spill_checkpoint(self, tenant: str, src: Backend,
                          jtext: str) -> None:
        if not src.journal_dir:
            return
        try:
            path = (_journal.tenant_path(src.journal_dir, tenant)
                    + ".orphaned")
            with open(path, "w", encoding="utf-8") as f:
                f.write(jtext)
            LOG.warning("spilled tenant %s's checkpoint to %s",
                        tenant, path)
        except OSError:
            LOG.warning("could not spill tenant %s's checkpoint",
                        tenant, exc_info=True)

    def _orphan(self, tenant: str, src: Backend, codes: list,
                note: str = "") -> None:
        with self._lock:
            o = self._orphans.setdefault(
                tenant, {"from": src.name, "causes": {}})
            _prov.add_counts(o["causes"], codes)
            if note:
                o["note"] = note
            self._set_orphans_gauge()
        _prov.count_metric(self.metrics,
                           [_prov.cause(c) for c in codes],
                           tenant=tenant)
        LOG.warning("tenant %s ORPHANED (%s): %s — verdict folds "
                    "unknown", tenant, "/".join(codes), note)

    # -- rebalancing ---------------------------------------------------------

    def _maybe_rebalance(self) -> None:
        cfg = self.config
        with self._lock:
            if self._migrating:
                return  # one migration at a time keeps causality easy
            health = {n: b.health for n, b in self._backends.items()
                      if not b.down and b.health is not None}
            placement = dict(self._placement)
        plan = plan_rebalance(health, placement,
                              min_load=cfg.rebalance_min_load,
                              ratio=cfg.rebalance_ratio,
                              lag_weight=cfg.lag_weight)
        if plan is None:
            return
        tenant, src, dst = plan
        LOG.info("rebalance: migrating tenant %s %s -> %s",
                 tenant, src, dst)
        try:
            self.migrate(tenant, target=dst, reason="rebalance")
        except KeyError:
            pass  # placement changed under us; next tick re-plans

    # -- aggregation ---------------------------------------------------------

    def tenants_snapshot(self) -> dict:
        """Router-level ``GET /tenants``: every tenant's row from its
        OWN backend, plus synthesized unknown rows for orphans — the
        one place a reconnecting client reads its watermark from,
        wherever the tenant lives now."""
        with self._lock:
            placement = dict(self._placement)
            orphans = {t: dict(o) for t, o in self._orphans.items()}
        rows: dict[str, dict] = {}
        backends_doc: dict[str, dict] = {}
        for b in self._backends.values():
            backends_doc[b.name] = b.snapshot()
            if b.down:
                continue
            # Probe-class timeout, not the proxy one: this aggregation
            # backs every /live tick and every reconnecting client's
            # watermark read — one slow backend must not freeze it for
            # N × http_timeout_s.
            status, doc = self._request(
                b, "/tenants",
                timeout=max(self.config.probe_timeout_s, 2.0))
            if status != 200:
                backends_doc[b.name]["unreachable"] = True
                continue
            for t, row in (doc.get("tenants") or {}).items():
                if placement.get(t) == b.name and t not in orphans:
                    row = dict(row or {})
                    row["backend"] = b.name
                    rows[t] = row
        for t, o in orphans.items():
            causes = dict(o.get("causes") or {})
            rows[t] = {
                "verdict": "unknown",
                "orphaned": True,
                "degraded": True,
                "backend": o.get("from"),
                "provenance": _prov.block(causes),
                "dominant_unknown_cause": _prov.dominant(causes),
            }
        return {
            "router": self.name,
            "t": round(_time.time(), 3),
            "tenant_count": len(rows),
            "tenants": rows,
            "backends": backends_doc,
            "migrations": len(self.migrations),
        }

    def health_snapshot(self) -> dict:
        """Router ``GET /healthz``: router liveness + the backend
        table (state, last-known load)."""
        with self._lock:
            n_orphans = len(self._orphans)
            n_migrating = len(self._migrating)
        return {
            "ok": True,
            "router": self.name,
            "draining": self._draining,
            "backends": {n: b.snapshot()
                         for n, b in self._backends.items()},
            "orphaned_tenants": n_orphans,
            "migrating_tenants": n_migrating,
        }

    def live_snapshot(self) -> dict:
        """The web ``/live`` row: the service-shaped tenant table (the
        dashboard renders it unchanged) plus the backend table."""
        snap = self.tenants_snapshot()
        rows = snap["tenants"]
        return {
            "run": self.name,
            "service": True,
            "router": True,
            "t": snap["t"],
            "draining": self._draining,
            "tenant_count": len(rows),
            "ops_observed": sum((r or {}).get("ops_observed") or 0
                                for r in rows.values()),
            "scheduler_backlog": sum(
                (b.health or {}).get("scheduler_backlog") or 0
                for b in self._backends.values() if not b.down),
            "decision_latency": {},
            "tenants": rows,
            "backends": snap["backends"],
        }

    def stats(self) -> dict:
        """Router counters for bench/tests (migration audit included;
        ``backend_loads`` feeds the advisor's rebalance rule)."""
        with self._lock:
            migrations = [dict(m) for m in self.migrations]
            orphans = {t: dict(o) for t, o in self._orphans.items()}
            placement = dict(self._placement)
        return {
            "placement": placement,
            "migrations": migrations,
            "orphaned": orphans,
            # LIVE backends only (like _maybe_rebalance): a lost
            # backend's last-good health doc is stale — feeding it to
            # the advisor would compute skew against (and point advice
            # at) a backend that no longer exists.
            "backend_loads": {
                n: {
                    "load": backend_load(b.health,
                                         self.config.lag_weight),
                    "scheduler_backlog": (b.health or {}).get(
                        "scheduler_backlog") or 0,
                    "journal_lag_ops": sum(
                        (r or {}).get("journal_lag_ops") or 0
                        for r in ((b.health or {}).get("tenants")
                                  or {}).values()),
                }
                for n, b in self._backends.items() if not b.down
            },
        }

    # -- drain / shutdown ----------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> dict:
        """Drain every live backend, merge the per-tenant results
        (orphans fold unknown with their causes), stop supervision and
        reap spawned children. Idempotent."""
        with self._lock:
            if self._finished is not None:
                return self._finished
            self._draining = True
        timeout = timeout if timeout is not None \
            else self.config.drain_timeout_s
        self._stop.set()
        # Let an in-flight supervision tick (and its migrations)
        # finish before draining the backends: a /drain racing a
        # mid-tick adopt would 503 it and spuriously orphan a tenant
        # whose migration had every right to complete.
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=min(timeout, 60.0))
        results: dict[str, dict] = {}
        per_backend: dict[str, dict] = {}
        p99s: list[float] = []
        with self._lock:
            placement = dict(self._placement)
            orphans = {t: dict(o) for t, o in self._orphans.items()}
        for b in self._backends.values():
            if b.down:
                per_backend[b.name] = {"error": "lost"}
                continue
            status, doc = self._request(b, "/drain", data=b"",
                                        timeout=timeout)
            if status != 200:
                per_backend[b.name] = {
                    "error": f"drain_{status}_"
                             f"{doc.get('error') or 'failed'}"}
                # Its tenants' verdicts are unrecoverable now.
                for t, n in placement.items():
                    if n == b.name and t not in orphans:
                        orphans[t] = {"from": b.name,
                                      "causes": {"backend_lost": 1}}
                continue
            per_backend[b.name] = {
                "valid": doc.get("valid"),
                "wall_s": doc.get("wall_s"),
                "tenant_count": doc.get("tenant_count"),
            }
            lat = doc.get("decision_latency") or {}
            if isinstance(lat.get("p99_s"), (int, float)):
                p99s.append(float(lat["p99_s"]))
            for t, r in (doc.get("tenants") or {}).items():
                if placement.get(t) == b.name and t not in orphans:
                    r = dict(r or {})
                    r["backend"] = b.name
                    results[t] = r
        for t, o in orphans.items():
            causes = dict(o.get("causes") or {})
            results[t] = {
                "valid": "unknown",
                "orphaned": True,
                "backend": o.get("from"),
                "provenance": _prov.block(causes),
                "info": "tenant orphaned by a lost backend; verdict "
                        "degraded to unknown",
            }
        # A tenant whose backend died between the last probe and this
        # drain (or whose migration the drain interrupted) has no row
        # anywhere — it must surface as an honest unknown, never
        # vanish from the results document.
        with self._lock:
            interrupted = set(self._migrating)
        for t, n in placement.items():
            if t in results:
                continue
            causes = {"migration_interrupted": 1} if t in interrupted \
                else {"backend_lost": 1}
            _prov.count_metric(self.metrics,
                               [_prov.cause(c) for c in causes],
                               tenant=t)
            results[t] = {
                "valid": "unknown",
                "backend": n,
                "provenance": _prov.block(causes),
                "info": "tenant unreachable at drain (backend lost / "
                        "migration interrupted); verdict degraded to "
                        "unknown",
            }
        from ..checker import merge_valid

        with self._lock:
            migrations = [dict(m) for m in self.migrations]
        fin = {
            "router": self.name,
            "tenants": results,
            "tenant_count": len(results),
            "backends": per_backend,
            "valid": merge_valid(r.get("valid")
                                 for r in results.values()),
            # Per-tenant p99s don't compose into one histogram across
            # processes; the conservative router-level number is the
            # worst backend's aggregate p99.
            "p99_decision_latency_s": max(p99s) if p99s else None,
            "migrations": migrations,
        }
        run_prov = _prov.block(_prov.merge_counts(
            *(((r.get("provenance") or {}).get("causes"))
              for r in results.values())))
        if run_prov is not None:
            fin["provenance"] = run_prov
        self._finished = fin
        self._shutdown_children()
        if self.config.register_live:
            try:
                from .. import web

                web.unregister_live_source(self.name)
            except Exception:  # noqa: BLE001
                pass
        return fin

    def _shutdown_children(self) -> None:
        for b in self._backends.values():
            p = b.proc
            if p is None or p.poll() is not None:
                continue
            try:
                p.terminate()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                    p.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    pass

    def close(self) -> None:
        """Stop supervision without draining (test teardown)."""
        self._stop.set()
        self._thread.join(timeout=5)
        self._shutdown_children()
        if self.config.register_live:
            try:
                from .. import web

                web.unregister_live_source(self.name)
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# Spawning real backend processes (the router CLI / bench / e2e tests).


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_backends(n: int, *, journal_root: str,
                   model: str = "cas-register", engine: str = "host",
                   max_configs: int = 500_000,
                   name_prefix: str = "backend",
                   extra_args: tuple = (), env: Optional[dict] = None,
                   metrics=None, failure_threshold: int = 3,
                   cooldown_s: float = 30.0,
                   wait_ready_s: float = 120.0) -> list[Backend]:
    """Spawn N backend service processes (``python -m
    jepsen_tpu.service``), each with its own port and
    ``--journal-dir`` under ``journal_root``, and wait for their
    ``/healthz``. The returned Backends carry the child handles so the
    router can detect exits and the ``backend.process`` chaos seam has
    real processes to kill."""
    backends: list[Backend] = []
    try:
        for i in range(n):
            port = _free_port()
            name = f"{name_prefix}-{i}"
            jdir = os.path.join(journal_root, name)
            cmd = [sys.executable, "-m", "jepsen_tpu.service",
                   "--port", str(port), "--model", model,
                   "--engine", engine, "--max-configs",
                   str(max_configs), "--journal-dir", jdir,
                   "--name", name, *extra_args]
            proc = subprocess.Popen(cmd, env=env,
                                    stdout=subprocess.DEVNULL,
                                    stderr=subprocess.DEVNULL)
            backends.append(Backend(
                name, f"http://127.0.0.1:{port}", journal_dir=jdir,
                proc=proc, metrics=metrics,
                failure_threshold=failure_threshold,
                cooldown_s=cooldown_s))
        deadline = _time.monotonic() + wait_ready_s
        for b in backends:
            while True:
                try:
                    with _urequest.urlopen(b.url + "/healthz",
                                           timeout=2) as r:
                        if r.status == 200:
                            break
                except Exception:  # noqa: BLE001 - not up yet
                    pass
                if b.proc.poll() is not None:
                    raise RuntimeError(
                        f"backend {b.name} exited rc={b.proc.poll()} "
                        "before becoming healthy")
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"backend {b.name} not healthy after "
                        f"{wait_ready_s}s")
                _time.sleep(0.1)
        return backends
    except BaseException:
        for b in backends:
            if b.proc is not None and b.proc.poll() is None:
                b.proc.kill()
        raise


# ---------------------------------------------------------------------------
# The router's own HTTP front door (same machinery as service/http.py).


def make_router_handler(router: Router):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            LOG.debug(fmt, *args)

        def _json(self, code: int, doc: dict) -> None:
            import math

            body = json.dumps(doc, sort_keys=True,
                              default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            ra = doc.get("retry_after_s")
            if code in (429, 503) and isinstance(ra, (int, float)):
                self.send_header("Retry-After",
                                 str(max(1, math.ceil(ra))))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = unquote(urlsplit(self.path).path)
            try:
                if path in ("/", "/tenants", "/tenants/"):
                    self._json(200, router.tenants_snapshot())
                elif path == "/healthz":
                    self._json(200, router.health_snapshot())
                elif path in ("/live", "/live/"):
                    self._json(200, router.live_snapshot())
                elif path in ("/backends", "/backends/"):
                    self._json(200, router.health_snapshot())
                else:
                    self._json(404, {"error": "not_found"})
            except Exception as e:  # noqa: BLE001
                LOG.warning("router error serving %s", path,
                            exc_info=True)
                self._json(500, {"error": "internal",
                                 "detail": f"{type(e).__name__}: {e}"})

        def do_POST(self):
            parts = urlsplit(self.path)
            path = unquote(parts.path)
            query = parse_qs(parts.query)
            try:
                if path.startswith("/submit/"):
                    tenant = path[len("/submit/"):].strip("/")
                    length = int(self.headers.get("Content-Length")
                                 or 0)
                    # Same bounded-memory contract as the backend's
                    # transport layer: the proxy must not buffer what
                    # the backend would refuse anyway.
                    from .http import MAX_BODY_BYTES

                    if length > MAX_BODY_BYTES:
                        self._json(413, {
                            "error": "body_too_large",
                            "tenant": tenant, "accepted": 0,
                            "max_bytes": MAX_BODY_BYTES})
                        return
                    body = self.rfile.read(length)
                    status, doc = router.submit(tenant, body)
                    self._json(status, doc)
                elif path.startswith("/migrate/"):
                    tenant = path[len("/migrate/"):].strip("/")
                    target = (query.get("target") or [None])[0]
                    ok = router.migrate(tenant, target=target)
                    self._json(200 if ok else 409,
                               {"tenant": tenant, "migrated": ok})
                elif path in ("/drain", "/drain/"):
                    self._json(200, router.drain())
                else:
                    self._json(404, {"error": "not_found"})
            except KeyError as e:
                self._json(404, {"error": "unknown_tenant",
                                 "detail": str(e)})
            except Exception as e:  # noqa: BLE001
                LOG.warning("router error serving %s", path,
                            exc_info=True)
                self._json(500, {"error": "internal",
                                 "detail": f"{type(e).__name__}: {e}"})

    return Handler


def server(router: Router, port: int = 0):
    from http.server import ThreadingHTTPServer

    return ThreadingHTTPServer(("", port), make_router_handler(router))


def serve(router: Router, port: int = 8088) -> None:
    srv = server(router, port)
    LOG.info("Router %s fronting %d backend(s) on http://0.0.0.0:%d",
             router.name, len(router._backends),
             srv.server_address[1])
    print(f"Router {router.name} fronting "
          f"{len(router._backends)} backend(s) on "
          f"http://0.0.0.0:{srv.server_address[1]} "
          "(POST /submit/<tenant>, GET /tenants, POST /drain)")
    srv.serve_forever()
