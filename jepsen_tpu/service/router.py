"""Tenant router: horizontal scale-out for the checking service.

One :class:`~jepsen_tpu.service.service.Service` process lives or dies
as a unit — PR 10 made a *restart* of that unit lossless (the
per-tenant verdict journal is the tenant's complete checkpoint), and
this module cashes that enabler in for *horizontal* resilience
(ROADMAP item 3): a front-end that places tenants across N backend
service processes (each with its own scheduler/mesh slice and its own
``--journal-dir``) and survives losing an ENTIRE backend the same way
the single process survives a restart — by journal replay, one-sided,
never a flipped verdict.

The pieces:

- **Sticky placement** — a tenant's first submit places it on the
  least-loaded live backend; every later submit proxies to the same
  backend (the fold is stateful; bouncing a tenant would fork it).
- **Health checking** — a probe loop GETs each backend's ``/healthz``
  (now carrying per-tenant backlog / ``journal_lag_ops`` / degraded
  flags) under a deadline, feeding a per-backend
  :class:`~jepsen_tpu.parallel.resilience.CircuitBreaker`:
  ``failure_threshold`` consecutive failures open the circuit and the
  backend is declared LOST (a spawned child's exit is detected
  directly).
- **Journal-backed migration** — losing a backend (or an overload
  rebalance) moves each of its tenants: quiesce + ``POST
  /release/<tenant>`` on a live source (the journal handover), or —
  when the backend is dead — read the journal straight from its
  ``--journal-dir`` (the journal IS the checkpoint; there is nothing
  else to save), then ``POST /adopt/<tenant>`` on the target (replay
  behind admission) and atomically flip placement. Clients mid-stream
  get 503 + ``Retry-After`` and resume from the journaled watermark
  exactly as after a PR-10 restart; resubmitted covered ops are
  dropped server-side. Soundness is the PR-5/PR-10 quiescent-cut
  argument: every journal record ends at a cut carrying the exact
  feasible end-state set, so the target re-decides nothing that was
  covered and checks everything above the watermark from the carried
  states.
- **Load-adaptive rebalancing** — :func:`plan_rebalance` is a pure
  function over the ``/healthz`` overload signals (scheduler backlog,
  queue depths, ``journal_lag_ops``); when one backend's load exceeds
  the least-loaded's by ``rebalance_ratio`` (and an absolute floor),
  the heaviest tenant is live-migrated off it.
- **Failure attribution** — a tenant that cannot be migrated (no
  target, no checkpoint, adopt refused, ``JEPSEN_NO_MIGRATION=1``) is
  ORPHANED: its router-level row folds ``unknown`` with the typed
  ``backend_lost`` / ``migration_interrupted`` causes
  (checker/provenance.py) — degraded one-sidedly, never flipped.
- **Self-healing** (``service/supervisor.py``) — a dead spawned
  backend is RESPAWNED (bounded exponential backoff, flap-damping
  circuit, ``JEPSEN_NO_RESPAWN=1`` kill-switch) against the same
  ``--journal-dir``; once the replacement passes ``/healthz`` the
  router re-adopts tenants toward it (:func:`plan_readopt` over the
  live ``/migrate`` machinery) so capacity returns to N.
- **Crash-safe router state** — with ``state_path`` the placement
  map, orphan records and a monotone placement *epoch* persist to an
  append-only ``router_state.jsonl``; a restarted router replays it
  and reconciles against live ``/healthz`` + journal-dir reality (a
  record is a hint, reality wins), and the epoch rides every
  ``/release``/``/adopt`` so a stale ex-router's in-flight migration
  is refused with a typed 409 ``stale_epoch``.
- **Rolling restart** — ``POST /roll`` (CLI ``--roll``) drains,
  respawns and re-adopts one backend at a time through the live
  ``/release`` path: zero-unknown-verdict upgrades.
- **Chaos seams** — ``router.probe`` (an injected raise counts as a
  failed health probe: the false-positive path), ``backend.process``
  (the router SIGKILLs one of its own spawned backend children: a
  real kill-9 of a real process) and ``router.crash`` (the router
  itself dies mid-migration — after the checkpoint, before the
  adopt; the restarted router must recover or orphan, never fork).

``JEPSEN_NO_MIGRATION=1`` is the operational kill-switch: no
migrations, no rebalancing — dead backends simply orphan their
tenants (checked per attempt, like every other kill-switch).
``JEPSEN_NO_RESPAWN=1`` does the same for the respawn half.

Telemetry: ``router_placements_total{backend}``,
``router_migrations_total{reason}``,
``router_failed_probes_total{backend}``, ``router_orphaned_tenants``,
``router_migration_seconds``, ``router_respawns_total{backend,
outcome}``, ``router_respawn_seconds``, ``router_epoch``. The router
registers on the web ``/live`` feed and aggregates ``/tenants``
across backends. See docs/service.md "Scale-out & migration" and
"Supervision & rolling restart".

**Fleet observability** (``RouterConfig.federate``, on by default
when a registry is attached): every supervision tick also scrapes
each live backend's ``GET /metrics.json`` and feeds
:class:`~jepsen_tpu.telemetry.fleet.FleetFederation` — the merged
fleet registry (counters sum, gauges keep per-backend children +
fleet totals, histograms bucket-merge so the fleet p99 is real) is
served on the router's own ``GET /metrics`` alongside the router's
registry, with per-backend scrape staleness
(``fleet_scrape_age_seconds{backend}`` et al.) so a dead or
respawning backend reads as STALE, never silently-zero.
:class:`~jepsen_tpu.telemetry.fleet.SloMonitor` turns the federated
histograms into availability / decision-latency burn-rate gauges.
Router operations (placement, migration, respawn, roll, epoch bump)
are minted as spans on the attached collector, and client trace
context (``X-Trace-Id``/``X-Parent-Span``) is forwarded through the
submit proxy and the migration ``/adopt`` — one tenant's life across
kill-9 + migration + resume is ONE trace. ``GET /fleet`` joins the
``router_state.jsonl`` timeline with per-backend utilization for the
web fleet page. See docs/telemetry.md "Fleet federation & SLOs".
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time as _time
from dataclasses import dataclass, replace
from typing import Any, Callable, Optional
from urllib import error as _uerror
from urllib import request as _urequest
from urllib.parse import parse_qs, quote, unquote, urlsplit

from ..checker import provenance as _prov
from ..parallel import resilience as _resilience
from ..telemetry import fleet as _fleet
from ..testing import chaos as _chaos
from .. import trace as _trace
from . import journal as _journal
from . import supervisor as _supervisor

LOG = logging.getLogger("jepsen.router")

MIGRATION_SECONDS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                             10.0, 30.0, 60.0)


def migration_disabled() -> bool:
    """``JEPSEN_NO_MIGRATION=1`` — checked per attempt, so flipping the
    env in a live router takes effect (the kill-switch contract)."""
    return os.environ.get("JEPSEN_NO_MIGRATION", "") == "1"


class NoBackendError(RuntimeError):
    """No live backend is available to place a tenant on."""


@dataclass(frozen=True)
class RouterConfig:
    """Router policy knobs."""

    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    # Consecutive probe failures before a backend's circuit opens and
    # it is declared lost (resilience.CircuitBreaker semantics; the
    # cooldown paces half-open re-probes of a backend that may heal).
    failure_threshold: int = 3
    probe_cooldown_s: float = 30.0
    http_timeout_s: float = 10.0
    release_timeout_s: float = 30.0
    drain_timeout_s: float = 120.0
    # Retry-After hint on migration/unreachable 503s: a migration is a
    # release+replay+flip, normally sub-second at bench scale.
    migrate_retry_after_s: float = 1.0
    # Load-adaptive rebalancing off the /healthz overload signals.
    rebalance: bool = True
    rebalance_min_load: float = 256.0
    rebalance_ratio: float = 4.0
    # journal_lag_ops (ops) -> load units (undecided segments are the
    # base unit; ~100 ops of journal lag weigh like one segment).
    lag_weight: float = 0.01
    register_live: bool = True
    # Self-healing: respawn a dead spawned backend (bounded backoff +
    # flap damping — see service/supervisor.py; JEPSEN_NO_RESPAWN=1
    # overrides) and re-adopt tenants toward the replacement.
    respawn: bool = True
    respawn_base_backoff_s: float = 0.25
    respawn_max_backoff_s: float = 15.0
    respawn_window_s: float = 60.0
    respawn_max_failures: int = 5
    # Crash-safe router state: when set, placement/orphans/epoch
    # persist to this append-only jsonl and a restarted router replays
    # + reconciles it (docs/service.md "Supervision & rolling
    # restart").
    state_path: Optional[str] = None
    # Fleet observability: scrape each live backend's /metrics.json on
    # the probe cadence, merge into one fleet registry and drive the
    # SLO burn-rate monitor (needs a metrics registry to matter; see
    # docs/telemetry.md "Fleet federation & SLOs").
    federate: bool = True
    # Alerting plane (docs/alerts.md): evaluate the built-in rule
    # catalogue over the FEDERATED totals on this same tick — one rule
    # set covers the fleet. Off by default; enabling any of the three
    # lazily imports telemetry/alerts.py. alerts_path defaults to an
    # alerts.jsonl next to state_path, so a kill-9'd router restarted
    # on the same state dir replays its firing set.
    alerts: bool = False
    alerts_path: Optional[str] = None
    alerts_sink: Optional[str] = None


class Backend:
    """One backend service process as the router sees it."""

    def __init__(self, name: str, url: str,
                 journal_dir: Optional[str] = None,
                 proc: Optional[subprocess.Popen] = None,
                 metrics=None, failure_threshold: int = 3,
                 cooldown_s: float = 30.0,
                 respawner: Optional[Callable] = None) -> None:
        self.name = name
        self.url = url.rstrip("/")
        self.journal_dir = journal_dir
        self.proc = proc
        # The (re)spawn recipe: callable(backend) replaces proc/url
        # with a fresh healthy incarnation on the SAME journal dir
        # (service/supervisor.py). None = not respawnable (attached
        # --backend-urls backends).
        self.respawner = respawner
        self.supervisor: Optional[_supervisor.BackendSupervisor] = None
        # One breaker per backend: the consecutive-failure /
        # cooldown / half-open-probe protocol is exactly the device
        # path's (parallel/resilience.py) with "device" = "backend".
        self.breaker = _resilience.CircuitBreaker(
            f"router:{name}", failure_threshold=failure_threshold,
            cooldown_s=cooldown_s, metrics=metrics)
        self.health: Optional[dict] = None  # last good /healthz doc
        # Wall-clock time `health` was observed at: every aggregation
        # that re-serves the doc stamps this alongside it, so a
        # 10-seconds-stale row from a dying backend renders as 10
        # seconds old instead of masquerading as current.
        self.health_at: Optional[float] = None
        self.down = False  # declared lost; tenants migrated away
        # Mid-rolling-restart: excluded from NEW placement (a tenant
        # placed after the drain snapshot would be killed un-drained)
        # but still LIVE to everything else — probes keep running and
        # _checkpoint must not steal journals from under it.
        self.rolling = False

    def snapshot(self) -> dict:
        out = {
            "url": self.url,
            "state": "lost" if self.down else self.breaker.state,
            "down": self.down,
        }
        if self.proc is not None:
            out["pid"] = self.proc.pid
            out["exited"] = self.proc.poll()
        if self.health is not None:
            out["tenant_count"] = self.health.get("tenant_count")
            out["scheduler_backlog"] = self.health.get(
                "scheduler_backlog")
            if self.health_at is not None:
                out["observed_at"] = round(self.health_at, 3)
                out["health_age_s"] = round(
                    max(_time.time() - self.health_at, 0.0), 3)
        if self.supervisor is not None:
            sup = self.supervisor.snapshot()
            out["respawns"] = sup["respawns"]
            if sup["gave_up"]:
                # The typed supervision health state: the flap circuit
                # tripped and this backend stays down until an
                # operator intervenes (advisor rule respawn_backend).
                out["state"] = "respawn_gave_up"
                out["respawn_gave_up"] = True
        return out


# ---------------------------------------------------------------------------
# Pure rebalance planning (closed-form-testable; the advisor's
# rebalance_tenants rule applies the same load model to bench rounds).


def backend_load(health: Optional[dict],
                 lag_weight: float = 0.01) -> float:
    """One backend's load in scheduler-backlog units from its
    ``/healthz`` doc: undecided segments + queued ops + weighted
    journal lag (what a migration NOW would force clients to
    resubmit)."""
    h = health or {}
    tenants = h.get("tenants") or {}
    load = float(h.get("scheduler_backlog") or 0)
    for row in tenants.values():
        row = row or {}
        load += float(row.get("queue_depth") or 0)
        load += lag_weight * float(row.get("journal_lag_ops") or 0)
    return load


def tenant_load(row: Optional[dict], lag_weight: float = 0.01) -> float:
    r = row or {}
    return (float(r.get("backlog") or 0)
            + float(r.get("queue_depth") or 0)
            + lag_weight * float(r.get("journal_lag_ops") or 0))


def plan_rebalance(health_by_backend: dict, placement: dict, *,
                   min_load: float = 256.0, ratio: float = 4.0,
                   lag_weight: float = 0.01
                   ) -> Optional[tuple[str, str, str]]:
    """Pick at most ONE (tenant, src, dst) live migration: fires only
    when the loaded backend exceeds both an absolute floor and
    ``ratio``× the least-loaded backend, and moves the heaviest tenant
    (deterministic tie-break). Pure — pinned closed-form in
    tests/test_router.py and mirrored by the advisor's
    ``rebalance_tenants`` rule."""
    if len(health_by_backend) < 2:
        return None
    loads = {n: backend_load(h, lag_weight)
             for n, h in health_by_backend.items()}
    src = max(sorted(loads), key=lambda n: loads[n])
    dst = min(sorted(loads), key=lambda n: loads[n])
    if src == dst:
        return None
    if loads[src] < min_load or loads[src] < ratio * (loads[dst] + 1.0):
        return None
    rows = (health_by_backend[src] or {}).get("tenants") or {}
    cands = [t for t, n in placement.items()
             if n == src and t in rows]
    if not cands:
        return None
    tenant = max(sorted(cands),
                 key=lambda t: tenant_load(rows[t], lag_weight))
    if tenant_load(rows[tenant], lag_weight) <= 0:
        return None
    return tenant, src, dst


def plan_readopt(placement: dict, target: str,
                 live: set) -> Optional[tuple[str, str]]:
    """Pick at most ONE (tenant, src) move toward ``target`` — a
    just-respawned (or just-rolled), empty backend. Count-based, not
    load-based: the respawned backend has no health doc yet and the
    survivors may be idle, so `plan_rebalance`'s overload thresholds
    would never fire; capacity, not load, is what the re-adoption
    restores. Fires while the most-loaded OTHER live backend holds at
    least two more tenants than ``target`` (so every move strictly
    shrinks the imbalance and the loop terminates); deterministic
    tie-breaks, pure — pinned closed-form in tests/test_router.py."""
    if target not in live:
        return None
    counts = {n: 0 for n in live}
    for t, n in placement.items():
        if n in counts:
            counts[n] += 1
    others = sorted(n for n in live if n != target)
    if not others:
        return None
    src = max(others, key=lambda n: (counts[n], n))
    if counts[src] - counts.get(target, 0) < 2:
        return None
    cands = sorted(t for t, n in placement.items() if n == src)
    if not cands:
        return None
    return cands[0], src


# ---------------------------------------------------------------------------


class Router:
    """The scale-out front-end: sticky tenant placement over N backend
    service processes, health-checked, with journal-backed live
    migration. See the module docstring."""

    def __init__(self, backends: list[Backend],
                 config: Optional[RouterConfig] = None, *,
                 metrics=None, collector=None, name: str = "router",
                 **overrides) -> None:
        cfg = config or RouterConfig()
        if overrides:
            cfg = replace(cfg, **overrides)
        if not backends:
            raise ValueError("router needs at least one backend")
        self.config = cfg
        self.name = name
        self.metrics = metrics
        # Span sink for router operations (place / migrate / respawn /
        # roll / epoch bump) — each span carries the epoch, and
        # migration spans join the tenant's client trace id so the
        # cross-process trace covers the handover.
        self.collector = collector
        self._backends: dict[str, Backend] = {}
        for b in backends:
            if b.name in self._backends:
                raise ValueError(f"duplicate backend name {b.name!r}")
            self._backends[b.name] = b
            # ONE source of truth for the probe-circuit policy: the
            # router's config re-arms every backend breaker, so a
            # Backend constructed with different defaults cannot
            # silently diverge from what the router believes (and
            # logs) about its own thresholds.
            b.breaker.failure_threshold = cfg.failure_threshold
            b.breaker.cooldown_s = cfg.probe_cooldown_s
        self._lock = threading.RLock()
        self._placement: dict[str, str] = {}  # tenant -> backend name
        self._migrating: set[str] = set()
        # tenant -> (trace_id, parent_span_id): the last trace context
        # a submit carried, so router-side spans (placement, the
        # covering migration span) and the forwarded /adopt join the
        # client's trace instead of starting disconnected ones.
        self._tenant_traces: dict[str, tuple] = {}
        # tenant -> {"from": backend, "causes": {code: n}, "note": …}:
        # tenants the router could NOT move — their router-level rows
        # fold unknown with these causes, never a definite verdict.
        self._orphans: dict[str, dict] = {}
        self.migrations: list[dict] = []  # bounded audit trail
        self._draining = False
        self._finished: Optional[dict] = None
        self._stop = threading.Event()
        self._roll_lock = threading.Lock()
        # Supervision: one respawn supervisor per respawnable backend.
        self._supervisors: dict[str, _supervisor.BackendSupervisor] = {}
        if cfg.respawn:
            policy = _supervisor.RespawnPolicy(
                base_backoff_s=cfg.respawn_base_backoff_s,
                max_backoff_s=cfg.respawn_max_backoff_s,
                window_s=cfg.respawn_window_s,
                max_failures_in_window=cfg.respawn_max_failures)
            for b in backends:
                if b.respawner is not None:
                    sup = _supervisor.BackendSupervisor(
                        b, b.respawner, policy, metrics=metrics,
                        on_ready=self._on_backend_respawned)
                    b.supervisor = sup
                    self._supervisors[b.name] = sup
        # Crash-safe router state: replay the journal (placement /
        # orphans / epoch are HINTS), bump the epoch past everything
        # replayed (this router generation supersedes any prior one),
        # then reconcile the hints against live reality BEFORE the
        # health loop starts.
        self._epoch = 1
        self._state: Optional[_supervisor.RouterState] = None
        state_rep: Optional[dict] = None
        if cfg.state_path:
            state_rep = _supervisor.replay_state(cfg.state_path)
            self._epoch = state_rep["epoch"] + 1
            self._placement = dict(state_rep["placement"])
            self._orphans = {t: dict(o)
                             for t, o in state_rep["orphans"].items()}
            self._state = _supervisor.RouterState(
                cfg.state_path, epoch=self._epoch,
                truncate_to=(state_rep["consistent_bytes"]
                             if state_rep["torn_tail"] else None))
        if metrics is not None:
            metrics.gauge(
                "router_epoch",
                "This router generation's placement epoch (every "
                "/release and /adopt carries it; stale epochs are "
                "fenced with a typed 409)").set(self._epoch)
        # Fleet federation + SLO burn-rate monitor: the supervision
        # tick scrapes each backend's /metrics.json into `federation`
        # and feeds the merged view to `slo` (None when federation is
        # off or there is no registry to export through).
        self.federation: Optional[_fleet.FleetFederation] = None
        self.slo: Optional[_fleet.SloMonitor] = None
        self._slo_doc: Optional[dict] = None
        if cfg.federate and metrics is not None:
            self.federation = _fleet.FleetFederation(metrics)
            self.slo = _fleet.SloMonitor(metrics)
        # Alerting plane: built ONLY when configured (the off-path pin
        # — telemetry/alerts.py is never imported otherwise).
        self.alert_engine = None
        self._sentinel = None
        if cfg.alerts or cfg.alerts_path or cfg.alerts_sink:
            from ..telemetry import alerts as _alerts

            apath = cfg.alerts_path
            if apath is None and cfg.state_path:
                apath = os.path.join(
                    os.path.dirname(os.path.abspath(cfg.state_path)),
                    "alerts.jsonl")
            sink = (_alerts.AlertSink(cfg.alerts_sink)
                    if cfg.alerts_sink else None)
            self._sentinel = _alerts.RegressionSentinel()
            self.alert_engine = _alerts.AlertEngine(
                metrics=metrics, path=apath, sink=sink,
                source=self.name)
        if state_rep is not None:
            # The epoch bump IS a fleet-visible operation: every
            # /release//adopt from here on carries the new epoch.
            self._span("router.epoch_bump",
                       prev_epoch=state_rep["epoch"])
        if state_rep is not None and (state_rep["records"]
                                      or state_rep["torn_tail"]):
            self._reconcile()
        self._thread = threading.Thread(
            target=self._health_loop, name="jepsen-router-health",
            daemon=True)
        self._thread.start()
        if cfg.register_live:
            try:
                from .. import web

                web.register_live_source(self.name, self.live_snapshot)
                web.register_fleet_source(self.name,
                                          self.fleet_snapshot)
            except Exception:  # noqa: BLE001 - observability only
                LOG.warning("could not register router live source",
                            exc_info=True)

    # -- tracing -------------------------------------------------------------

    def _span(self, name: str, *, t0_ns: Optional[int] = None,
              trace: Optional[tuple] = None, **attrs) -> None:
        """Mint one router-operation span (no-op without a collector).
        ``trace`` is a (trace_id, parent_span_id) propagation tuple;
        ``t0_ns`` makes it a covering span instead of a point."""
        if self.collector is None:
            return
        now = _time.monotonic_ns()
        tid = pid = None
        if trace:
            tid = trace[0]
            pid = trace[1] if len(trace) > 1 else None
        try:
            self.collector.record(
                name, start_ns=t0_ns if t0_ns is not None else now,
                end_ns=now, trace_id=tid, parent_id=pid,
                stage="router", router=self.name, epoch=self._epoch,
                **attrs)
        except Exception:  # noqa: BLE001 - observability only
            LOG.debug("router span %s failed", name, exc_info=True)

    def _trace_for(self, tenant: str) -> Optional[tuple]:
        with self._lock:
            return self._tenant_traces.get(tenant)

    # -- metrics -------------------------------------------------------------

    def _count_placement(self, backend: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "router_placements_total",
                "Tenant placements decided by the router (first "
                "placement + every migration flip), by backend",
                labelnames=("backend",)).labels(backend=backend).inc()

    def _count_failed_probe(self, backend: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "router_failed_probes_total",
                "Backend health probes that failed (timeout, refused, "
                "unhealthy, chaos-injected), by backend",
                labelnames=("backend",)).labels(backend=backend).inc()

    def _count_migration(self, reason: str, seconds: float) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                "router_migrations_total",
                "Journal-backed tenant migrations completed, by reason "
                "(backend_lost / rebalance)",
                labelnames=("reason",)).labels(reason=reason).inc()
            self.metrics.histogram(
                "router_migration_seconds",
                "Wall seconds per tenant migration (checkpoint "
                "handover + adopt replay + placement flip)",
                buckets=MIGRATION_SECONDS_BUCKETS).observe(seconds)

    def _set_orphans_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "router_orphaned_tenants",
                "Tenants whose backend was lost and whose migration "
                "could not complete — their verdicts fold unknown "
                "(backend_lost / migration_interrupted)").set(
                    len(self._orphans))

    # -- backend HTTP --------------------------------------------------------

    def _request(self, b: Backend, path: str,
                 data: Optional[bytes] = None,
                 timeout: Optional[float] = None,
                 headers: Optional[dict] = None) -> tuple[int, dict]:
        """One backend call; never raises. status 0 = unreachable."""
        req = _urequest.Request(
            b.url + path, data=data,
            method="POST" if data is not None else "GET",
            headers=headers or {})
        try:
            with _urequest.urlopen(
                    req, timeout=timeout
                    or self.config.http_timeout_s) as r:
                doc = json.loads(r.read().decode() or "{}")
                return r.status, doc if isinstance(doc, dict) else {}
        except _uerror.HTTPError as e:
            try:
                doc = json.loads(e.read().decode() or "{}")
            except ValueError:
                doc = {}
            return e.code, doc if isinstance(doc, dict) else {}
        except Exception as e:  # noqa: BLE001 - dead socket, timeout
            return 0, {"error": "unreachable", "detail": str(e)}

    # -- placement + ingestion proxy -----------------------------------------

    def _place(self, tenant: str) -> Backend:
        with self._lock:
            name = self._placement.get(tenant)
            if name is not None:
                b = self._backends.get(name)
                if b is not None:
                    return b
            cands = [b for b in self._backends.values()
                     if not b.down and not b.rolling]
            if not cands:
                raise NoBackendError("no live backend to place on")
            # Prefer backends whose probe circuit is quiet: a breaker
            # opened by submit-path failures marks a backend the
            # supervision tick has not yet declared lost — placing a
            # NEW tenant there would just bounce. Fall back to any
            # not-down backend when every circuit is engaged.
            quiet = [b for b in cands if not b.breaker.engaged()]
            counts: dict[str, int] = {}
            for _t, n in self._placement.items():
                counts[n] = counts.get(n, 0) + 1
            b = min(quiet or cands,
                    key=lambda bb: (counts.get(bb.name, 0), bb.name))
            self._placement[tenant] = b.name
        self._count_placement(b.name)
        self._state_append({"kind": "place", "tenant": tenant,
                            "backend": b.name})
        self._span("router.place", trace=self._trace_for(tenant),
                   tenant=tenant, backend=b.name)
        LOG.info("placed tenant %s on backend %s", tenant, b.name)
        return b

    def _state_append(self, rec: dict) -> None:
        if self._state is not None:
            self._state.append(rec)

    def placement(self) -> dict[str, str]:
        with self._lock:
            return dict(self._placement)

    def submit(self, tenant: str, body: bytes,
               trace: Optional[tuple] = None) -> tuple[int, dict]:
        """Proxy one ndjson POST to the tenant's backend. Returns
        (status, response doc); 503s carry ``retry_after_s`` +
        ``retryable`` so the resume-aware client backs off and
        re-anchors on the journaled watermark. ``trace`` is the
        client's (trace_id, parent_span_id) propagation context —
        remembered per tenant (so the covering migration span and the
        forwarded ``/adopt`` join the same trace) and forwarded on the
        proxied request."""
        cfg = self.config
        if trace is not None and trace[0]:
            with self._lock:
                self._tenant_traces[tenant] = trace
        with self._lock:
            if self._draining:
                return 503, {"error": "draining", "tenant": tenant,
                             "accepted": 0, "retryable": False}
            migrating = tenant in self._migrating
            orphan = self._orphans.get(tenant)
        if orphan is not None:
            # The tenant's state is unrecoverable: the honest answer
            # is a terminal refusal, not a silent fresh stream that
            # would fork its history.
            return 503, {"error": "orphaned", "tenant": tenant,
                         "accepted": 0, "retryable": False,
                         "causes": dict(orphan.get("causes") or {})}
        if migrating:
            return 503, {"error": "migrating", "tenant": tenant,
                         "accepted": 0, "retryable": True,
                         "retry_after_s": cfg.migrate_retry_after_s}
        try:
            b = self._place(tenant)
        except NoBackendError:
            return 503, {"error": "no_backend", "tenant": tenant,
                         "accepted": 0, "retryable": True,
                         "retry_after_s": cfg.migrate_retry_after_s}
        hdrs = None
        if trace is not None and trace[0]:
            hdrs = _trace.trace_headers(
                trace[0], trace[1] if len(trace) > 1 else None)
        status, doc = self._request(
            b, f"/submit/{quote(tenant, safe='')}", data=body,
            headers=hdrs)
        if status == 0:
            # Fast-path death detection: the proxy saw the dead socket
            # before the probe loop did. Feed the breaker and let the
            # supervision tick decide; the client retries against the
            # migrated placement.
            b.breaker.record_failure()
            self._count_failed_probe(b.name)
            return 503, {"error": "backend_unreachable",
                         "tenant": tenant, "accepted": 0,
                         "retryable": True,
                         "retry_after_s": cfg.migrate_retry_after_s}
        doc.setdefault("backend", b.name)
        return status, doc

    # -- health / supervision ------------------------------------------------

    def _probe(self, b: Backend) -> dict:
        # Chaos seam INSIDE the probe's failure domain: an injected
        # raise is indistinguishable from a timed-out /healthz — the
        # false-positive migration path under test.
        _chaos.fire("router.probe")
        with _urequest.urlopen(b.url + "/healthz",
                               timeout=self.config.probe_timeout_s) as r:
            doc = json.loads(r.read().decode() or "{}")
        if not isinstance(doc, dict) or not doc.get("ok"):
            raise RuntimeError(f"backend {b.name} unhealthy: {doc!r}")
        return doc

    def _chaos_kill_tick(self) -> None:
        """``backend.process``: an armed raise is the KILL ORDER — the
        router SIGKILLs one live spawned backend child (a real kill-9:
        torn journal line, dead socket) and then recovers through its
        own probe/migration machinery."""
        try:
            _chaos.fire("backend.process")
        except Exception:  # noqa: BLE001 - the armed fault
            victim = next(
                (b for b in self._backends.values()
                 if b.proc is not None and b.proc.poll() is None
                 and not b.down), None)
            if victim is None:
                LOG.warning("chaos backend.process fired with no live "
                            "spawned backend to kill")
                return
            LOG.warning("chaos: kill -9 backend %s (pid %d)",
                        victim.name, victim.proc.pid)
            victim.proc.kill()

    def _health_loop(self) -> None:
        while not self._stop.wait(self.config.probe_interval_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - supervision must survive
                LOG.warning("router health tick failed", exc_info=True)

    def _tick(self) -> None:
        self._chaos_kill_tick()
        for b in list(self._backends.values()):
            if b.down:
                continue
            if b.proc is not None and b.proc.poll() is not None:
                # A spawned child's exit needs no probe quorum.
                self._on_backend_down(
                    b, f"process exited rc={b.proc.poll()}")
                continue
            if b.breaker.state == "open":
                # The circuit can open BETWEEN ticks off submit-path
                # failures (the --backend-urls case with no child to
                # poll): without this, the tick would silently skip
                # the backend for a whole cooldown while clients
                # exhaust their retries against a dead placement.
                self._on_backend_down(
                    b, "circuit open (consecutive submit/probe "
                       "failures)")
                continue
            if not b.breaker.allow():
                continue  # open, cooldown pending: skip doomed probes
            try:
                doc = self._probe(b)
            except Exception as e:  # noqa: BLE001 - probe failure
                b.breaker.record_failure()
                self._count_failed_probe(b.name)
                LOG.warning("probe of backend %s failed (%s: %s)",
                            b.name, type(e).__name__, e)
                if b.breaker.state == "open":
                    self._on_backend_down(
                        b, "probe circuit open "
                        f"({self.config.failure_threshold} consecutive "
                        "failures)")
                continue
            b.breaker.record_success()
            b.health = doc
            b.health_at = _time.time()
            self._scrape_metrics(b)
        if self.federation is not None:
            # A backend that is down (or has never answered a scrape)
            # must read as STALE in the fleet view — its last-good
            # snapshot stays in the merge (its counters really did
            # happen) but the staleness gauges mark the numbers as
            # frozen, never silently current. Expected is every
            # CONFIGURED backend (down included — a kill-9'd backend
            # mid-respawn still belongs to the fleet); a snapshot held
            # for a name no longer configured at all is decommissioned
            # and expires instead of pinning the staleness signal.
            self.federation.stale_backends(
                expected=list(self._backends))
            if self.slo is not None:
                try:
                    self._slo_doc = self.slo.observe(
                        self.federation.merged())
                except Exception:  # noqa: BLE001 - observability only
                    LOG.warning("SLO observe failed", exc_info=True)
        if self.alert_engine is not None:
            try:
                self._evaluate_alerts()
            except Exception:  # noqa: BLE001 - observability only
                LOG.warning("alert evaluation failed", exc_info=True)
        if (self.config.rebalance and not self._draining
                and not migration_disabled()):
            self._maybe_rebalance()

    def _scrape_metrics(self, b: Backend) -> None:
        """Federation scrape, piggybacked on a SUCCESSFUL probe (same
        cadence, same failure domain): pull the backend's live
        registry snapshot and merge it under ``backend=<name>``."""
        if self.federation is None:
            return
        status, doc = self._request(
            b, "/metrics.json",
            timeout=max(self.config.probe_timeout_s, 2.0))
        if status == 200 and isinstance(doc.get("samples"), list):
            self.federation.record_scrape(b.name, doc)
        else:
            self.federation.record_failure(b.name)

    def _on_backend_down(self, b: Backend, why: str) -> None:
        if b.down:
            return
        b.down = True
        b.breaker.record_failure()
        LOG.warning("backend %s declared LOST (%s); migrating its "
                    "tenants", b.name, why)
        self._state_append({"kind": "lost", "backend": b.name,
                            "why": why})
        self._span("router.backend_lost", backend=b.name, why=why)
        sup = self._supervisors.get(b.name)
        if sup is not None:
            sup.note_exit()  # count the death in the flap window
        self._migrate_lost_tenants(b)
        if sup is not None:
            # Start the respawn worker only AFTER the migrations
            # stole/renamed every recoverable journal: a replacement
            # child booting mid-steal would replay a journal the
            # router is about to hand to another backend — the same
            # tenant live on two backends (the fork this module
            # exists to prevent). Journals that could NOT be migrated
            # (orphans) deliberately stay in place for the child's
            # replay + the rescue path.
            sup.kick()

    def _migrate_lost_tenants(self, b: Backend) -> None:
        with self._lock:
            tenants = sorted(t for t, n in self._placement.items()
                             if n == b.name)
            self._migrating.update(tenants)
        for t in tenants:
            try:
                self._migrate(t, b, reason="backend_lost")
            except Exception:  # noqa: BLE001 - incl. chaos raise
                # A migration that RAISES (the router.crash seam's
                # raise mode, an unexpected bug) must not abort the
                # loop: the remaining tenants would sit in _migrating
                # forever (terminal 503s, rebalancing wedged
                # router-wide) with no typed record anywhere. The
                # raising tenant gets an honest typed orphan — a
                # later successful migration / respawn rescue clears
                # it.
                LOG.warning("migration of tenant %s raised mid-"
                            "flight; orphaning", t, exc_info=True)
                self._orphan(t, b,
                             ["backend_lost", "migration_interrupted"],
                             note="migration raised mid-flight")
                with self._lock:
                    self._migrating.discard(t)

    # -- self-healing (service/supervisor.py drives these) -------------------

    def _fence_backend(self, b: Backend) -> bool:
        """Apply this generation's epoch fence to one backend (a few
        attempts). A refusal is meaningful: a NEWER router generation
        has fenced it higher, and this router must not bring it into
        its own fleet."""
        for _ in range(3):
            status, _doc = self._request(
                b, f"/fence?epoch={self._epoch}", data=b"")
            if status == 200:
                return True
            _time.sleep(0.1)
        return False

    def _bring_up(self, b: Backend, why: str) -> bool:
        """The ONE bring-up sequence respawn and roll share: fence the
        fresh child at this generation's epoch (its in-memory fence
        starts empty — serving unfenced would admit a stale
        ex-router's in-flight /adopt), then mark it live and record
        it. False = NOT brought up (fence refused/unreachable, or the
        router is draining): the backend stays down."""
        if not self._fence_backend(b):
            LOG.error("backend %s passed /healthz but the epoch "
                      "fence could not be applied; keeping it DOWN",
                      b.name)
            return False
        with self._lock:
            if self._draining:
                return False
            b.down = False
            b.health = None
            b.health_at = None
        b.breaker.record_success()
        if self.federation is not None:
            # The replacement process starts its counters from its
            # journal replay, NOT from the dead generation's totals:
            # dropping the old snapshot here is what makes the fleet
            # merge generation-safe (no double count across respawns —
            # the next scrape replaces, never accumulates).
            self.federation.forget(b.name)
        self._state_append({"kind": "respawned", "backend": b.name,
                            "url": b.url, "why": why})
        self._span("router.respawn", backend=b.name, why=why,
                   url=b.url)
        return True

    def _on_backend_respawned(self, b: Backend) -> bool:
        """The supervisor's on_ready hook: the replacement child
        passed /healthz — fence + mark the backend live, rescue any
        orphans its journal replay restored, and re-adopt tenants
        toward it so capacity returns to N. Returning False tells the
        supervisor the bring-up failed (counted as a failed attempt,
        backed off and retried under the flap circuit)."""
        if not self._bring_up(b, "respawn"):
            with self._lock:
                draining = self._draining
            return draining  # draining: nothing left to retry
        LOG.info("backend %s is back (%s); re-adopting tenants",
                 b.name, b.url)
        self._rescue_orphans(b)
        self._readopt(b)
        return True

    def _rescue_orphans(self, b: Backend) -> None:
        """Orphans of this backend whose journals were never migrated
        away are restored by the respawned child's own PR-10 replay —
        they are LIVE there again. Flip placement back and clear the
        orphan record (this IS the 'later migration that succeeds',
        executed by the restart instead of a move)."""
        with self._lock:
            mine = sorted(t for t, o in self._orphans.items()
                          if o.get("from") == b.name)
        if not mine:
            return
        status, doc = self._request(
            b, "/tenants", timeout=max(self.config.probe_timeout_s,
                                       2.0))
        if status != 200:
            return
        rows = doc.get("tenants") or {}
        for t in mine:
            if t not in rows:
                continue
            with self._lock:
                self._placement[t] = b.name
                if self._orphans.pop(t, None) is not None:
                    self._set_orphans_gauge()
            self._count_placement(b.name)
            self._state_append({"kind": "place", "tenant": t,
                                "backend": b.name,
                                "why": "respawn_rescue"})
            self._state_append({"kind": "orphan_clear", "tenant": t})
            LOG.info("orphaned tenant %s restored by the respawn of "
                     "backend %s", t, b.name)

    def _readopt(self, target: Backend) -> int:
        """Re-adopt tenants toward a just-respawned (or just-rolled)
        backend via live migrations until the placement counts are
        balanced (plan_readopt). Stops at the first refusal — a
        half-balanced fleet still serves."""
        if migration_disabled():
            return 0
        moved = 0
        while moved < 256:
            with self._lock:
                placement = dict(self._placement)
                live = {bb.name for bb in self._backends.values()
                        if not bb.down}
            plan = plan_readopt(placement, target.name, live)
            if plan is None:
                break
            tenant, _src = plan
            try:
                if not self.migrate(tenant, target=target.name,
                                    reason="readopt"):
                    break
            except Exception:  # noqa: BLE001 - re-adoption is
                # best-effort: a half-balanced fleet still serves.
                LOG.warning("re-adoption of tenant %s raised",
                            tenant, exc_info=True)
                break
            moved += 1
        return moved

    def _reconcile(self) -> None:
        """Router restart: the replayed state is a HINT — probe every
        backend, fence the live ones at this generation's epoch, and
        make reality win: a tenant a live backend actually hosts is
        placed there; a backend dead while the router was down gets
        the exact watched-death treatment (journal-backed migration or
        typed orphaning); a tenant placed on a live backend that does
        NOT host it (an interrupted migration's released stream) is
        recovered through the ordinary checkpoint-rescue path."""
        cfg = self.config
        alive: dict[str, dict] = {}
        for b in self._backends.values():
            doc = None
            # Match the declared liveness policy: a backend only
            # counts as dead-at-restart after failure_threshold
            # consecutive probe failures, same as the watched path.
            for _ in range(max(cfg.failure_threshold, 1)):
                try:
                    doc = self._probe(b)
                    break
                except Exception:  # noqa: BLE001 - probe failure
                    self._count_failed_probe(b.name)
                    _time.sleep(0.05)
            if doc is None:
                continue
            b.health = doc
            b.health_at = _time.time()
            alive[b.name] = doc.get("tenants") or {}
            # Fence: this router generation supersedes any prior one;
            # a stale ex-router's in-flight /adopt into this backend
            # now gets the typed 409. A refusal here means a NEWER
            # router already owns the fleet — surface it loudly (full
            # concurrent-router HA is the ROADMAP's named remainder).
            if not self._fence_backend(b):
                LOG.error("backend %s refused epoch %d at reconcile "
                          "— a newer router generation may own this "
                          "fleet", b.name, self._epoch)
        # Reality wins, pass 1: tenants a live backend actually hosts.
        for name, rows in alive.items():
            for t in rows:
                with self._lock:
                    stale = self._placement.get(t) != name
                    if stale:
                        self._placement[t] = name
                    cleared = self._orphans.pop(t, None) is not None
                    if cleared:
                        self._set_orphans_gauge()
                if stale or cleared:
                    self._count_placement(name)
                    self._state_append({"kind": "place", "tenant": t,
                                        "backend": name,
                                        "why": "reconcile"})
                    if cleared:
                        self._state_append({"kind": "orphan_clear",
                                            "tenant": t})
        # Pass 2: backends dead while the router was down — exactly as
        # if the router had watched them die. Mark ALL dead first so a
        # dead backend can never be picked as a migration target.
        dead = [b for b in self._backends.values()
                if b.name not in alive and not b.down]
        for b in dead:
            b.down = True
            b.breaker.record_failure()
            self._state_append({"kind": "lost", "backend": b.name,
                                "why": "dead at router restart"})
            sup = self._supervisors.get(b.name)
            if sup is not None:
                sup.note_exit()
            LOG.warning("backend %s dead at router restart; migrating "
                        "its tenants", b.name)
        for b in dead:
            self._migrate_lost_tenants(b)
        for b in dead:
            # Respawn only after the steals (same ordering as
            # _on_backend_down: a child booting mid-steal would
            # re-own a journal the router is handing elsewhere).
            sup = self._supervisors.get(b.name)
            if sup is not None:
                sup.kick()
        # Pass 3: placed on a live backend that does not host it — an
        # interrupted migration released the stream (the `.migrated`
        # checkpoint is recoverable) or the tenant was never admitted
        # (no checkpoint: the placement stays a hint and the next
        # submit admits it fresh, which is correct — it has no decided
        # past anywhere).
        hosted = {t for rows in alive.values() for t in rows}
        with self._lock:
            placement = dict(self._placement)
            orphans = set(self._orphans)
        for t, n in sorted(placement.items()):
            if n not in alive or t in hosted or t in orphans:
                continue
            src = self._backends.get(n)
            if src is None:
                continue
            with self._lock:
                if t in self._migrating:
                    continue
                self._migrating.add(t)
            try:
                self._migrate(t, src, reason="router_restart")
            except Exception:  # noqa: BLE001 - recovery best-effort
                LOG.warning("restart recovery of tenant %s raised; "
                            "it stays placed as a hint", t,
                            exc_info=True)
                with self._lock:
                    self._migrating.discard(t)

    # -- migration -----------------------------------------------------------

    def migrate(self, tenant: str, target: Optional[str] = None,
                reason: str = "manual") -> bool:
        """Operator/rebalance entry point: live-migrate one tenant off
        its current backend (release → adopt → flip)."""
        # Resolve and validate EVERYTHING before marking the tenant
        # migrating: a raise after the mark (with _migrate's finally
        # never entered) would wedge the tenant in 503-migrating
        # forever and stall rebalancing router-wide.
        with self._lock:
            src_name = self._placement.get(tenant)
            if src_name is None:
                raise KeyError(f"tenant {tenant!r} is not placed")
            src = self._backends[src_name]
            dst = None
            if target is not None:
                dst = self._backends.get(target)
                if dst is None:
                    raise KeyError(
                        f"unknown target backend {target!r}")
            if tenant in self._migrating:
                return False
            self._migrating.add(tenant)
        return self._migrate(tenant, src, reason=reason, target=dst)

    def _pick_target(self, exclude: Backend) -> Optional[Backend]:
        with self._lock:
            cands = [b for b in self._backends.values()
                     if not b.down and not b.rolling
                     and b.name != exclude.name]
            if not cands:
                return None
            counts: dict[str, int] = {}
            for _t, n in self._placement.items():
                counts[n] = counts.get(n, 0) + 1
            return min(cands,
                       key=lambda bb: (counts.get(bb.name, 0), bb.name))

    def _checkpoint(self, tenant: str, src: Backend
                    ) -> tuple[Optional[str], Optional[str]]:
        """Obtain the tenant's journal checkpoint: live release first
        (also the recovery from a FALSE-POSITIVE probe death — a
        healthy backend answers and quiesces), else off the source's
        journal_dir. Returns (journal_text, adopt_cause)."""
        # Socket timeout strictly ABOVE the backend's own quiesce
        # deadline: a release that takes the full quiesce window must
        # not be abandoned on the wire just as it completes. The
        # epoch rides along: a stale ex-router's release is fenced
        # with a typed 409 before it can quiesce anything.
        status, doc = self._request(
            src, f"/release/{quote(tenant, safe='')}"
                 f"?epoch={self._epoch}", data=b"",
            timeout=self.config.release_timeout_s + 15.0)
        if status == 200 and isinstance(doc.get("journal"), str):
            return doc["journal"], None
        dead = src.down or (src.proc is not None
                            and src.proc.poll() is not None)
        path = (_journal.tenant_path(src.journal_dir, tenant)
                if src.journal_dir else None)
        if path and dead:
            # The backend is demonstrably gone: its journal file IS
            # the checkpoint (PR 10's whole point). Renamed after
            # reading so a RESTARTED backend on the same dir cannot
            # re-own a tenant that now lives elsewhere. NEVER taken
            # from a live backend (a transient connect blip must not
            # seize the file from under the owner's open fd — split
            # ownership).
            try:
                with open(path, "rb") as f:
                    data = f.read()
                try:
                    os.replace(path, path + ".migrated")
                except OSError:
                    pass
                return data.decode("utf-8", "replace"), "backend_lost"
            except OSError:
                pass
        if path:
            # Release may have COMPLETED server-side with the response
            # lost on the wire: the source then already renamed the
            # file `.migrated` and tombstoned the tenant — the renamed
            # file is a complete checkpoint nobody owns, safe to adopt
            # whether or not the process is alive. (A successful adopt
            # back onto this backend deletes the stale artifact, so a
            # leftover here always describes the LATEST release.)
            try:
                with open(path + ".migrated", "rb") as f:
                    return (f.read().decode("utf-8", "replace"),
                            "backend_lost" if dead else None)
            except OSError:
                pass
        return None, None

    def _migrate(self, tenant: str, src: Backend, reason: str,
                 target: Optional[Backend] = None) -> bool:
        t0 = _time.monotonic()
        t0_ns = _time.monotonic_ns()
        entry: dict = {"tenant": tenant, "from": src.name,
                       "reason": reason, "ok": False}
        # Orphaning is for tenants whose SOURCE is gone (reason
        # backend_lost): a refused migration off a LIVE backend —
        # kill-switch, typo'd target, transient checkpoint failure —
        # must leave the tenant serving where it is, not destroy a
        # healthy stream behind a terminal 503 (review finding).
        lost = reason == "backend_lost"
        try:
            if migration_disabled():
                entry["error"] = "migration_disabled"
                if lost:
                    self._orphan(tenant, src,
                                 ["backend_lost",
                                  "migration_interrupted"],
                                 note="JEPSEN_NO_MIGRATION=1")
                return False
            dst = target if target is not None \
                else self._pick_target(exclude=src)
            if dst is None or dst.down or dst.rolling:
                entry["error"] = "no_target"
                if lost:
                    self._orphan(tenant, src, ["backend_lost"],
                                 note="no live target backend")
                return False
            entry["to"] = dst.name
            jtext, cause = self._checkpoint(tenant, src)
            if jtext is None:
                entry["error"] = "no_checkpoint"
                if lost:
                    self._orphan(tenant, src, ["backend_lost"],
                                 note="no journal checkpoint "
                                      "recoverable")
                return False
            # Chaos seam: the router dying MID-MIGRATION — checkpoint
            # in hand, adopt not yet issued. `crash` mode is the real
            # kill-9 (the restarted router's reconcile must recover
            # the released stream or orphan it, never fork it);
            # `raise` aborts the migration at the same point
            # in-process.
            _chaos.fire("router.crash")
            path = f"/adopt/{quote(tenant, safe='')}" \
                   f"?epoch={self._epoch}"
            if cause:
                path += f"&cause={quote(cause, safe='')}"
            # Forward the tenant's trace context on the adopt: the
            # TARGET backend's service.adopt span then joins the same
            # trace the client and the source backend recorded under.
            tctx = self._trace_for(tenant)
            hdrs = (_trace.trace_headers(tctx[0],
                                         tctx[1] if len(tctx) > 1
                                         else None)
                    if tctx and tctx[0] else None)
            status, doc = self._request(dst, path,
                                        data=jtext.encode("utf-8"),
                                        headers=hdrs)
            if status != 200:
                entry["error"] = (f"adopt_{status}_"
                                  f"{doc.get('error') or 'failed'}")
                # A live release already made the SOURCE forget the
                # tenant — the checkpoint now exists only in this
                # router's memory. Spill it next to the source's
                # journals so an operator can re-adopt by hand instead
                # of losing a recoverable stream.
                self._spill_checkpoint(tenant, src, jtext)
                self._orphan(
                    tenant, src,
                    ["backend_lost", "migration_interrupted"]
                    if reason == "backend_lost"
                    else ["migration_interrupted"],
                    note=f"adopt on {dst.name} failed: {status} "
                         f"{doc.get('error')}")
                return False
            with self._lock:
                self._placement[tenant] = dst.name
                # "Orphaned ... until a later migration succeeds"
                # (docs/verdicts.md): this IS the later migration — a
                # recovered tenant must serve again, not stay bricked
                # behind the stale orphan record.
                cleared = self._orphans.pop(tenant, None) is not None
                if cleared:
                    self._set_orphans_gauge()
            self._count_placement(dst.name)
            # The durable placement flip; "from" is the tombstone of
            # the previous owner (its `.migrated` file enforces it
            # backend-side).
            self._state_append({"kind": "place", "tenant": tenant,
                                "backend": dst.name,
                                "from": src.name})
            if cleared:
                self._state_append({"kind": "orphan_clear",
                                    "tenant": tenant})
            entry["ok"] = True
            entry["watermark"] = doc.get("watermark")
            LOG.info("migrated tenant %s %s -> %s (%s, watermark %s)",
                     tenant, src.name, dst.name, reason,
                     doc.get("watermark"))
            return True
        finally:
            seconds = _time.monotonic() - t0
            entry["seconds"] = round(seconds, 4)
            with self._lock:
                self.migrations.append(entry)
                if len(self.migrations) > 1000:
                    del self.migrations[:-1000]
                self._migrating.discard(tenant)
            if entry["ok"]:
                self._count_migration(reason, seconds)
            # EXACTLY ONE covering span per migration attempt (the
            # whole checkpoint → adopt → flip window), joined to the
            # tenant's client trace; a completed handover is the one
            # span with ok=True.
            extra = ({"error": entry["error"]}
                     if entry.get("error") else {})
            self._span("router.migrate", t0_ns=t0_ns,
                       trace=self._trace_for(tenant), tenant=tenant,
                       src=src.name, dst=entry.get("to"),
                       reason=reason, ok=entry["ok"], **extra)

    def _spill_checkpoint(self, tenant: str, src: Backend,
                          jtext: str) -> None:
        if not src.journal_dir:
            return
        try:
            path = (_journal.tenant_path(src.journal_dir, tenant)
                    + ".orphaned")
            with open(path, "w", encoding="utf-8") as f:
                f.write(jtext)
            LOG.warning("spilled tenant %s's checkpoint to %s",
                        tenant, path)
        except OSError:
            LOG.warning("could not spill tenant %s's checkpoint",
                        tenant, exc_info=True)

    def _orphan(self, tenant: str, src: Backend, codes: list,
                note: str = "") -> None:
        with self._lock:
            o = self._orphans.setdefault(
                tenant, {"from": src.name, "causes": {}})
            _prov.add_counts(o["causes"], codes)
            if note:
                o["note"] = note
            self._set_orphans_gauge()
            rec = {"kind": "orphan", "tenant": tenant,
                   "from": o["from"], "causes": dict(o["causes"])}
            if note:
                rec["note"] = note
        self._state_append(rec)
        _prov.count_metric(self.metrics,
                           [_prov.cause(c) for c in codes],
                           tenant=tenant)
        LOG.warning("tenant %s ORPHANED (%s): %s — verdict folds "
                    "unknown", tenant, "/".join(codes), note)

    # -- rebalancing ---------------------------------------------------------

    def _maybe_rebalance(self) -> None:
        cfg = self.config
        with self._lock:
            if self._migrating:
                return  # one migration at a time keeps causality easy
            # A mid-roll backend is being EMPTIED — it reads as the
            # least-loaded and would attract exactly the tenant the
            # roll is about to kill un-drained.
            health = {n: b.health for n, b in self._backends.items()
                      if not b.down and not b.rolling
                      and b.health is not None}
            placement = dict(self._placement)
        plan = plan_rebalance(health, placement,
                              min_load=cfg.rebalance_min_load,
                              ratio=cfg.rebalance_ratio,
                              lag_weight=cfg.lag_weight)
        if plan is None:
            return
        tenant, src, dst = plan
        LOG.info("rebalance: migrating tenant %s %s -> %s",
                 tenant, src, dst)
        try:
            self.migrate(tenant, target=dst, reason="rebalance")
        except KeyError:
            pass  # placement changed under us; next tick re-plans

    # -- rolling restart -----------------------------------------------------

    def roll(self) -> dict:
        """Rolling restart (``POST /roll`` / CLI ``--roll``): one
        backend at a time, drain-migrate its tenants via the live
        ``/release`` path, restart the process (respawner: fresh
        child, same journal dir), wait for ``/healthz``, re-adopt a
        fair share back — the fleet never drops below N-1 and every
        move is a quiesced journal handover, so an upgrade costs zero
        unknown verdicts. A backend whose tenants cannot all be moved
        is NOT restarted (the moved ones stay moved; the fleet still
        serves)."""
        with self._lock:
            if self._draining:
                return {"router": self.name, "ok": False,
                        "error": "draining", "backends": []}
        if not self._roll_lock.acquire(blocking=False):
            return {"router": self.name, "ok": False,
                    "error": "roll_in_progress", "backends": []}
        try:
            return self._roll_locked()
        finally:
            self._roll_lock.release()

    def _roll_locked(self) -> dict:
        roll_t0_ns = _time.monotonic_ns()
        out: dict = {"router": self.name, "ok": True,
                     "epoch": self._epoch, "backends": []}
        for b in list(self._backends.values()):
            entry: dict = {"backend": b.name}
            out["backends"].append(entry)
            if b.down:
                entry["skipped"] = "down"
                continue
            if b.respawner is None:
                entry["skipped"] = "no_respawner"
                continue
            t0 = _time.monotonic()
            # Out of NEW placement from before the drain snapshot: a
            # tenant placed onto the emptying backend after the
            # snapshot would be killed un-drained, breaking the
            # zero-unknown contract. `rolling` (unlike `down`) keeps
            # the backend fully LIVE for everything else — probes,
            # its existing tenants' ingestion, and _checkpoint's
            # never-steal-from-a-live-backend invariant.
            b.rolling = True
            try:
                with self._lock:
                    tenants = sorted(t for t, n in
                                     self._placement.items()
                                     if n == b.name)
                moved = []
                fail = None
                for t in tenants:
                    try:
                        if self.migrate(t, reason="roll"):
                            moved.append(t)
                        else:
                            fail = t
                            break
                    except Exception:  # noqa: BLE001 - a raising
                        # drain-migrate = this backend is not safely
                        # drainable; don't restart it.
                        fail = t
                        break
                entry["drained"] = moved
                if fail is not None:
                    # A healthy stream must never be restarted out
                    # from under itself: skip this backend's restart
                    # entirely.
                    entry["error"] = f"drain_migrate_failed:{fail}"
                    out["ok"] = False
                    continue
                # Marked down BEFORE the process dies so the
                # supervision tick cannot race the exit into a
                # spurious lost-backend migration + supervisor kick.
                b.down = True
                try:
                    if b.proc is not None and b.proc.poll() is None:
                        b.proc.terminate()
                        try:
                            b.proc.wait(timeout=10)
                        except Exception:  # noqa: BLE001
                            b.proc.kill()
                            b.proc.wait(timeout=5)
                    b.respawner(b)
                except Exception as e:  # noqa: BLE001 - spawn failed
                    entry["error"] = f"respawn_failed: {e}"
                    out["ok"] = False
                    # Hand the corpse to the supervisor — its backoff
                    # / flap circuit decides what happens next.
                    sup = self._supervisors.get(b.name)
                    if sup is not None:
                        sup.note_exit()
                        sup.kick()
                    continue
            finally:
                b.rolling = False
            if not self._bring_up(b, "roll"):
                entry["error"] = "bring_up_failed"
                out["ok"] = False
                # The child runs but cannot join the fleet (fence
                # unreachable/refused): leave it down and let the
                # supervisor's backoff / flap circuit own it.
                sup = self._supervisors.get(b.name)
                if sup is not None:
                    sup.note_exit()
                    sup.kick()
                continue
            entry["readopted"] = self._readopt(b)
            entry["seconds"] = round(_time.monotonic() - t0, 4)
            LOG.info("rolled backend %s in %.2fs (%d drained, %d "
                     "re-adopted)", b.name, entry["seconds"],
                     len(moved), entry["readopted"])
        self._span("router.roll", t0_ns=roll_t0_ns, ok=out["ok"],
                   backends=len(out["backends"]))
        return out

    # -- aggregation ---------------------------------------------------------

    def tenants_snapshot(self) -> dict:
        """Router-level ``GET /tenants``: every tenant's row from its
        OWN backend, plus synthesized unknown rows for orphans — the
        one place a reconnecting client reads its watermark from,
        wherever the tenant lives now."""
        with self._lock:
            placement = dict(self._placement)
            orphans = {t: dict(o) for t, o in self._orphans.items()}
        rows: dict[str, dict] = {}
        backends_doc: dict[str, dict] = {}
        for b in self._backends.values():
            backends_doc[b.name] = b.snapshot()
            if b.down:
                continue
            # Probe-class timeout, not the proxy one: this aggregation
            # backs every /live tick and every reconnecting client's
            # watermark read — one slow backend must not freeze it for
            # N × http_timeout_s.
            status, doc = self._request(
                b, "/tenants",
                timeout=max(self.config.probe_timeout_s, 2.0))
            if status != 200:
                backends_doc[b.name]["unreachable"] = True
                continue
            for t, row in (doc.get("tenants") or {}).items():
                if placement.get(t) == b.name and t not in orphans:
                    row = dict(row or {})
                    row["backend"] = b.name
                    rows[t] = row
        for t, o in orphans.items():
            causes = dict(o.get("causes") or {})
            rows[t] = {
                "verdict": "unknown",
                "orphaned": True,
                "degraded": True,
                "backend": o.get("from"),
                "provenance": _prov.block(causes),
                "dominant_unknown_cause": _prov.dominant(causes),
            }
        if self.federation is not None:
            # Per-backend scrape freshness on every aggregated row:
            # the /live fleet strip and /fleet page render row AGE
            # instead of presenting a stale dead-backend row as
            # current.
            for n, m in self.federation.meta().items():
                if n in backends_doc:
                    backends_doc[n]["scrape_age_s"] = \
                        m.get("scrape_age_s")
                    backends_doc[n]["scrapes"] = m.get("scrapes")
                    backends_doc[n]["scrape_stale"] = m.get("stale")
        return {
            "router": self.name,
            "t": round(_time.time(), 3),
            "epoch": self._epoch,
            "tenant_count": len(rows),
            "tenants": rows,
            "backends": backends_doc,
            "migrations": len(self.migrations),
        }

    def health_snapshot(self) -> dict:
        """Router ``GET /healthz``: router liveness + the backend
        table (state, last-known load)."""
        with self._lock:
            n_orphans = len(self._orphans)
            n_migrating = len(self._migrating)
        return {
            "ok": True,
            "router": self.name,
            "draining": self._draining,
            "epoch": self._epoch,
            "backends": {n: b.snapshot()
                         for n, b in self._backends.items()},
            "orphaned_tenants": n_orphans,
            "migrating_tenants": n_migrating,
        }

    def live_snapshot(self) -> dict:
        """The web ``/live`` row: the service-shaped tenant table (the
        dashboard renders it unchanged) plus the backend table."""
        snap = self.tenants_snapshot()
        rows = snap["tenants"]
        return {
            "run": self.name,
            "service": True,
            "router": True,
            "t": snap["t"],
            "epoch": self._epoch,
            "draining": self._draining,
            "tenant_count": len(rows),
            "ops_observed": sum((r or {}).get("ops_observed") or 0
                                for r in rows.values()),
            "scheduler_backlog": sum(
                (b.health or {}).get("scheduler_backlog") or 0
                for b in self._backends.values() if not b.down),
            "decision_latency": {},
            "tenants": rows,
            "backends": snap["backends"],
        }

    def stats(self) -> dict:
        """Router counters for bench/tests (migration audit included;
        ``backend_loads`` feeds the advisor's rebalance rule)."""
        with self._lock:
            migrations = [dict(m) for m in self.migrations]
            orphans = {t: dict(o) for t, o in self._orphans.items()}
            placement = dict(self._placement)
        sups = {n: s.snapshot() for n, s in self._supervisors.items()}
        respawn_secs = [s["last_respawn_s"] for s in sups.values()
                        if s["last_respawn_s"] is not None]
        return {
            "placement": placement,
            "migrations": migrations,
            "orphaned": orphans,
            "epoch": self._epoch,
            # The fleet-capacity block the advisor's respawn_backend
            # rule consumes (bench embeds it): is the fleet below its
            # configured N, and is the supervision layer still
            # working on that or has it stopped (disabled / flapped
            # out)?
            "fleet": {
                "configured_backends": len(self._backends),
                "live_backends": sum(
                    1 for b in self._backends.values() if not b.down),
                "respawn_disabled": (not self.config.respawn
                                     or _supervisor.respawn_disabled()),
                "respawn_gave_up": sorted(
                    n for n, s in sups.items() if s["gave_up"]),
                "respawns": sum(s["respawns"] for s in sups.values()),
                "respawn_seconds": (max(respawn_secs)
                                    if respawn_secs else None),
                **self._fleet_stats(),
            },
            # LIVE backends only (like _maybe_rebalance): a lost
            # backend's last-good health doc is stale — feeding it to
            # the advisor would compute skew against (and point advice
            # at) a backend that no longer exists.
            "backend_loads": {
                n: {
                    "load": backend_load(b.health,
                                         self.config.lag_weight),
                    "scheduler_backlog": (b.health or {}).get(
                        "scheduler_backlog") or 0,
                    "journal_lag_ops": sum(
                        (r or {}).get("journal_lag_ops") or 0
                        for r in ((b.health or {}).get("tenants")
                                  or {}).values()),
                }
                for n, b in self._backends.items() if not b.down
            },
        }

    # -- fleet observability -------------------------------------------------

    def _alert_fleet_ctx(self) -> dict:
        """The light fleet block the alert predicates read each tick —
        capacity/respawn state + staleness, WITHOUT the per-backend
        utilization reconstruction ``_fleet_stats`` pays for (this
        runs on the probe cadence; reconstruction is page-cadence)."""
        sups = {n: s.snapshot() for n, s in self._supervisors.items()}
        out: dict = {
            "configured_backends": len(self._backends),
            "live_backends": sum(
                1 for b in self._backends.values() if not b.down),
            "respawn_disabled": (not self.config.respawn
                                 or _supervisor.respawn_disabled()),
            "respawn_gave_up": sorted(
                n for n, s in sups.items() if s["gave_up"]),
        }
        if self.federation is not None:
            out["stale_backends"] = self.federation.stale_backends(
                expected=list(self._backends))
        return out

    def _evaluate_alerts(self) -> None:
        """One alert pass over the federated totals (the `_tick`
        hook): the rule set sees the fleet as ONE system — merged
        samples, the SLO doc, capacity/respawn state, and the
        change-point sentinel's live p99 series."""
        eng = self.alert_engine
        if eng is None:
            return
        from ..telemetry import alerts as _alerts

        merged = (self.federation.merged()
                  if self.federation is not None else [])
        sentinel: list = []
        if self._sentinel is not None:
            tail = _alerts.decision_tail(merged)
            if tail is not None and tail[1] is not None:
                self._sentinel.observe("fleet:p99_decision_latency_s",
                                       tail[1], lower_is_better=True)
            sentinel = self._sentinel.active()
        eng.evaluate({
            "samples": merged,
            "slo": self._slo_doc,
            "fleet": self._alert_fleet_ctx(),
            "sentinel": sentinel,
        })

    def alerts_snapshot(self) -> dict:
        """The router ``GET /alerts`` document ({"enabled": False}
        without an alert config)."""
        if self.alert_engine is None:
            return {"enabled": False, "router": self.name}
        return {"router": self.name, **self.alert_engine.snapshot()}

    def _fleet_stats(self) -> dict:
        """The federated slice of ``stats()['fleet']`` — what bench
        embeds and the advisor's slo_burn / backend_underutilized /
        scrape_stale rules consume. Empty when federation is off."""
        fed = self.federation
        if fed is None:
            return {}
        expected = list(self._backends)
        util: dict[str, dict] = {}
        for n in fed.backends():
            u = fed.utilization(n)
            if u is not None:
                util[n] = {
                    "utilization_pct": u.get("utilization_pct"),
                    "source": u.get("source"),
                }
        vals = [u["utilization_pct"] for u in util.values()
                if isinstance(u.get("utilization_pct"),
                              (int, float))]
        lat = fed.histogram_stats("decision_latency_seconds")
        return {
            "federation": fed.meta(expected=expected),
            "stale_backends": sorted(
                fed.stale_backends(expected=expected)),
            "utilization": util,
            "min_backend_utilization_pct": (round(min(vals), 2)
                                            if vals else None),
            "p99_decision_latency_s": ((lat or {}).get("p99_s")),
            "slo": self._slo_doc,
        }

    def _state_timeline(self, limit: int = 500) -> list[dict]:
        """The raw ``router_state.jsonl`` event stream (placement
        flips, orphans, lost/respawned backends, epoch headers) for
        the /fleet timeline — newest ``limit`` records, torn tail
        skipped. Empty without ``state_path``."""
        path = self.config.state_path
        if not path:
            return []
        out: list[dict] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail / mid-write line
                    if isinstance(rec, dict):
                        out.append(rec)
        except OSError:
            return []
        return out[-limit:]

    def fleet_snapshot(self) -> dict:
        """The web ``/fleet`` document: every backend's state +
        scrape freshness + utilization, the router-state timeline,
        and the current SLO burn rates — the fleet as ONE system."""
        with self._lock:
            placement = dict(self._placement)
            orphans = sorted(self._orphans)
        fed = self.federation
        meta = fed.meta(expected=list(self._backends)) \
            if fed is not None else {}
        backends: dict[str, dict] = {}
        for n, b in self._backends.items():
            row = b.snapshot()
            m = meta.get(n)
            if m:
                row["scrape_age_s"] = m.get("scrape_age_s")
                row["scrapes"] = m.get("scrapes")
                row["scrape_failures"] = m.get("scrape_failures")
                row["scrape_stale"] = m.get("stale")
            if fed is not None:
                row["utilization"] = fed.utilization(n)
            row["tenants"] = sorted(t for t, bn in placement.items()
                                    if bn == n)
            backends[n] = row
        timeline = self._state_timeline()
        if self.alert_engine is not None:
            # Alert transitions join the placement/respawn event
            # stream: one timeline answers "what fired while that
            # backend was being respawned?".
            timeline = sorted(
                timeline + self.alert_engine.timeline_rows(),
                key=lambda r: (r.get("t") or 0))
        doc: dict = {
            "router": self.name,
            "t": round(_time.time(), 3),
            "epoch": self._epoch,
            "draining": self._draining,
            "backends": backends,
            "orphaned": orphans,
            "migrations": len(self.migrations),
            "timeline": timeline,
        }
        if fed is not None:
            doc["decision_latency"] = fed.histogram_stats(
                "decision_latency_seconds")
            doc["slo"] = self._slo_doc
            doc["stale_backends"] = sorted(fed.stale_backends(
                expected=list(self._backends)))
        if self.alert_engine is not None:
            doc["alerts"] = {
                "firing": self.alert_engine.firing(),
                "recent": self.alert_engine.history(20),
            }
        return doc

    def metrics_text(self) -> str:
        """Router ``GET /metrics``: the router's own registry plus the
        federated per-backend + fleet-total series. The family sets
        are disjoint by construction (backends emit service/scheduler
        families, the router emits ``router_*``/``fleet_*``/``slo_*``)
        so plain concatenation is a valid exposition."""
        parts: list[str] = []
        if self.metrics is not None:
            from ..telemetry import export as _export

            parts.append(_export.prometheus_text(self.metrics))
        if self.federation is not None:
            parts.append(self.federation.prometheus_text())
        return "\n".join(p for p in parts if p)

    # -- drain / shutdown ----------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> dict:
        """Drain every live backend, merge the per-tenant results
        (orphans fold unknown with their causes), stop supervision and
        reap spawned children. Idempotent."""
        with self._lock:
            if self._finished is not None:
                return self._finished
            self._draining = True
        timeout = timeout if timeout is not None \
            else self.config.drain_timeout_s
        self._stop.set()
        # Let an in-flight supervision tick (and its migrations)
        # finish before draining the backends: a /drain racing a
        # mid-tick adopt would 503 it and spuriously orphan a tenant
        # whose migration had every right to complete.
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=min(timeout, 60.0))
        results: dict[str, dict] = {}
        per_backend: dict[str, dict] = {}
        p99s: list[float] = []
        with self._lock:
            placement = dict(self._placement)
            orphans = {t: dict(o) for t, o in self._orphans.items()}
        for b in self._backends.values():
            if b.down:
                per_backend[b.name] = {"error": "lost"}
                continue
            status, doc = self._request(b, "/drain", data=b"",
                                        timeout=timeout)
            if status != 200:
                per_backend[b.name] = {
                    "error": f"drain_{status}_"
                             f"{doc.get('error') or 'failed'}"}
                # Its tenants' verdicts are unrecoverable now.
                for t, n in placement.items():
                    if n == b.name and t not in orphans:
                        orphans[t] = {"from": b.name,
                                      "causes": {"backend_lost": 1}}
                continue
            per_backend[b.name] = {
                "valid": doc.get("valid"),
                "wall_s": doc.get("wall_s"),
                "tenant_count": doc.get("tenant_count"),
            }
            lat = doc.get("decision_latency") or {}
            if isinstance(lat.get("p99_s"), (int, float)):
                p99s.append(float(lat["p99_s"]))
            for t, r in (doc.get("tenants") or {}).items():
                if placement.get(t) == b.name and t not in orphans:
                    r = dict(r or {})
                    r["backend"] = b.name
                    results[t] = r
        for t, o in orphans.items():
            causes = dict(o.get("causes") or {})
            results[t] = {
                "valid": "unknown",
                "orphaned": True,
                "backend": o.get("from"),
                "provenance": _prov.block(causes),
                "info": "tenant orphaned by a lost backend; verdict "
                        "degraded to unknown",
            }
        # A tenant whose backend died between the last probe and this
        # drain (or whose migration the drain interrupted) has no row
        # anywhere — it must surface as an honest unknown, never
        # vanish from the results document.
        with self._lock:
            interrupted = set(self._migrating)
        for t, n in placement.items():
            if t in results:
                continue
            causes = {"migration_interrupted": 1} if t in interrupted \
                else {"backend_lost": 1}
            _prov.count_metric(self.metrics,
                               [_prov.cause(c) for c in causes],
                               tenant=t)
            results[t] = {
                "valid": "unknown",
                "backend": n,
                "provenance": _prov.block(causes),
                "info": "tenant unreachable at drain (backend lost / "
                        "migration interrupted); verdict degraded to "
                        "unknown",
            }
        from ..checker import merge_valid

        with self._lock:
            migrations = [dict(m) for m in self.migrations]
        # The federated fleet p99 is the REAL cross-process quantile
        # (bucket-merged histograms, not a max of per-backend p99s);
        # the conservative worst-backend max remains the fallback when
        # federation is off or never scraped.
        fleet_p99 = None
        if self.federation is not None:
            lat = self.federation.histogram_stats(
                "decision_latency_seconds")
            if lat and isinstance(lat.get("p99_s"), (int, float)):
                fleet_p99 = lat["p99_s"]
        fin = {
            "router": self.name,
            "tenants": results,
            "tenant_count": len(results),
            "backends": per_backend,
            "valid": merge_valid(r.get("valid")
                                 for r in results.values()),
            "p99_decision_latency_s": (
                fleet_p99 if fleet_p99 is not None
                else (max(p99s) if p99s else None)),
            "fleet_p99_decision_latency_s": fleet_p99,
            "migrations": migrations,
        }
        run_prov = _prov.block(_prov.merge_counts(
            *(((r.get("provenance") or {}).get("causes"))
              for r in results.values())))
        if run_prov is not None:
            fin["provenance"] = run_prov
        self._finished = fin
        for sup in self._supervisors.values():
            sup.close()
        if self.alert_engine is not None:
            self.alert_engine.close()
        if self._state is not None:
            self._state.close()
        self._shutdown_children()
        if self.config.register_live:
            try:
                from .. import web

                web.unregister_live_source(self.name)
                web.unregister_fleet_source(self.name)
            except Exception:  # noqa: BLE001
                pass
        return fin

    def _shutdown_children(self) -> None:
        for b in self._backends.values():
            p = b.proc
            if p is None or p.poll() is not None:
                continue
            try:
                p.terminate()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                    p.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    pass

    def close(self) -> None:
        """Stop supervision without draining (test teardown)."""
        self._stop.set()
        self._thread.join(timeout=5)
        for sup in self._supervisors.values():
            sup.close()
        if self.alert_engine is not None:
            self.alert_engine.close()
        if self._state is not None:
            self._state.close()
        self._shutdown_children()
        if self.config.register_live:
            try:
                from .. import web

                web.unregister_live_source(self.name)
                web.unregister_fleet_source(self.name)
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# Spawning real backend processes (the router CLI / bench / e2e tests).


def spawn_backends(n: int, *, journal_root: str,
                   model: str = "cas-register", engine: str = "host",
                   max_configs: int = 500_000,
                   name_prefix: str = "backend",
                   extra_args: tuple = (), env: Optional[dict] = None,
                   metrics=None, failure_threshold: int = 3,
                   cooldown_s: float = 30.0,
                   wait_ready_s: float = 120.0) -> list[Backend]:
    """Spawn N backend service processes (``python -m
    jepsen_tpu.service``), each with its own ``--journal-dir`` under
    ``journal_root``, and wait for their ``/healthz``. Each child
    binds **port 0** and reports the bound port through an atomically
    written ``--port-file`` — the old probe-a-free-port-then-bind
    dance had a TOCTOU hole (another process could take the probed
    port between probe and bind), which would crash-loop exactly the
    respawn path that needs to rebind. The returned Backends carry
    the child handles (exit detection, the ``backend.process`` chaos
    seam) and a :class:`~jepsen_tpu.service.supervisor.
    ProcessRespawner` so the router's supervision layer can respawn
    them."""
    backends: list[Backend] = []
    try:
        for i in range(n):
            name = f"{name_prefix}-{i}"
            jdir = os.path.join(journal_root, name)
            port_file = os.path.join(journal_root, f"{name}.port")
            cmd = [sys.executable, "-m", "jepsen_tpu.service",
                   "--port", "0", "--port-file", port_file,
                   "--model", model, "--engine", engine,
                   "--max-configs", str(max_configs),
                   "--journal-dir", jdir, "--name", name,
                   *extra_args]
            respawner = _supervisor.ProcessRespawner(
                cmd, port_file=port_file, env=env,
                wait_ready_s=wait_ready_s)
            os.makedirs(journal_root, exist_ok=True)
            b = Backend(name, "http://127.0.0.1:0", journal_dir=jdir,
                        metrics=metrics,
                        failure_threshold=failure_threshold,
                        cooldown_s=cooldown_s, respawner=respawner)
            respawner.spawn(b)
            backends.append(b)
        deadline = _time.monotonic() + wait_ready_s
        for b in backends:
            b.respawner.await_ready(b, deadline=deadline)
        return backends
    except BaseException:
        for b in backends:
            if b.proc is not None and b.proc.poll() is None:
                b.proc.kill()
        raise


# ---------------------------------------------------------------------------
# The router's own HTTP front door (same machinery as service/http.py).


def make_router_handler(router: Router):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            LOG.debug(fmt, *args)

        def _json(self, code: int, doc: dict) -> None:
            import math

            body = json.dumps(doc, sort_keys=True,
                              default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            ra = doc.get("retry_after_s")
            if code in (429, 503) and isinstance(ra, (int, float)):
                self.send_header("Retry-After",
                                 str(max(1, math.ceil(ra))))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = unquote(urlsplit(self.path).path)
            try:
                if path in ("/", "/tenants", "/tenants/"):
                    self._json(200, router.tenants_snapshot())
                elif path == "/healthz":
                    self._json(200, router.health_snapshot())
                elif path in ("/live", "/live/"):
                    self._json(200, router.live_snapshot())
                elif path in ("/backends", "/backends/"):
                    self._json(200, router.health_snapshot())
                elif path == "/metrics":
                    body = router.metrics_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path in ("/fleet", "/fleet/"):
                    self._json(200, router.fleet_snapshot())
                elif path in ("/alerts", "/alerts/"):
                    self._json(200, router.alerts_snapshot())
                else:
                    self._json(404, {"error": "not_found"})
            except Exception as e:  # noqa: BLE001
                LOG.warning("router error serving %s", path,
                            exc_info=True)
                self._json(500, {"error": "internal",
                                 "detail": f"{type(e).__name__}: {e}"})

        def do_POST(self):
            parts = urlsplit(self.path)
            path = unquote(parts.path)
            query = parse_qs(parts.query)
            try:
                if path.startswith("/submit/"):
                    tenant = path[len("/submit/"):].strip("/")
                    length = int(self.headers.get("Content-Length")
                                 or 0)
                    # Same bounded-memory contract as the backend's
                    # transport layer: the proxy must not buffer what
                    # the backend would refuse anyway.
                    from .http import MAX_BODY_BYTES

                    if length > MAX_BODY_BYTES:
                        self._json(413, {
                            "error": "body_too_large",
                            "tenant": tenant, "accepted": 0,
                            "max_bytes": MAX_BODY_BYTES})
                        return
                    body = self.rfile.read(length)
                    tid = self.headers.get(_trace.TRACE_HEADER)
                    trace = ((tid,
                              self.headers.get(_trace.PARENT_HEADER))
                             if tid else None)
                    status, doc = router.submit(tenant, body,
                                                trace=trace)
                    self._json(status, doc)
                elif path.startswith("/migrate/"):
                    tenant = path[len("/migrate/"):].strip("/")
                    target = (query.get("target") or [None])[0]
                    ok = router.migrate(tenant, target=target)
                    self._json(200 if ok else 409,
                               {"tenant": tenant, "migrated": ok})
                elif path in ("/roll", "/roll/"):
                    doc = router.roll()
                    self._json(200 if doc.get("ok") else 409, doc)
                elif path in ("/drain", "/drain/"):
                    self._json(200, router.drain())
                else:
                    self._json(404, {"error": "not_found"})
            except KeyError as e:
                self._json(404, {"error": "unknown_tenant",
                                 "detail": str(e)})
            except Exception as e:  # noqa: BLE001
                LOG.warning("router error serving %s", path,
                            exc_info=True)
                self._json(500, {"error": "internal",
                                 "detail": f"{type(e).__name__}: {e}"})

    return Handler


def server(router: Router, port: int = 0):
    from http.server import ThreadingHTTPServer

    return ThreadingHTTPServer(("", port), make_router_handler(router))


def serve(router: Router, port: int = 8088) -> None:
    srv = server(router, port)
    LOG.info("Router %s fronting %d backend(s) on http://0.0.0.0:%d",
             router.name, len(router._backends),
             srv.server_address[1])
    print(f"Router {router.name} fronting "
          f"{len(router._backends)} backend(s) on "
          f"http://0.0.0.0:{srv.server_address[1]} "
          "(POST /submit/<tenant>, GET /tenants, POST /drain)")
    srv.serve_forever()
