"""Bench-trajectory regression gate: machine-read the committed
``BENCH_r*.json`` / ``MULTICHIP_r*.json`` round artifacts into a
metric-by-round table and flag regressions.

Five rounds of artifacts existed before this module and NOTHING machine-
read them — the trajectory handed to round 6 was literally ``[]``, and a
round-over-round regression was something a judge discovered, not
something the bench reported. This closes the loop twice:

- ``python -m jepsen_tpu.benchcmp BENCH_r0*.json`` renders the
  trajectory, compares the newest round against its predecessor (every
  adjacent pair with ``--all``) and exits nonzero when any tracked
  metric regresses past its threshold (default 10%, ``--threshold``).
- ``bench.py`` calls :func:`vs_previous` at the end of a run to embed a
  ``vs_previous`` delta block in its own JSON line, so the regression is
  self-reported in the same artifact the driver archives.

Artifact tolerance, learned from the committed five rounds: a round file
may be the driver wrapper ``{"cmd", "n", "parsed", "rc", "tail"}`` with
``parsed`` null (r1 crashed; r5's final line outgrew the tail capture
and survives only as a HEAD-TRUNCATED fragment — recovered by clipping
to the first complete ``"key":`` boundary), a bare bench JSON line, or a
multichip wrapper ``{"n_devices", "ok", ...}``. Metrics missing from a
round simply leave a hole in the table; they never crash the gate.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Optional

# Metric catalogue: (name, dotted path into the bench JSON, direction).
# direction "lower" = seconds-like (regression when it grows), "higher"
# = throughput/scale-like (regression when it shrinks), "info" = shown
# in the table but never gated (budget wall, validity echoes).
METRICS: list[tuple[str, str, str]] = [
    ("value_s", "value", "lower"),
    ("invalid_s", "invalid_s", "lower"),
    ("fresh_history_s", "fresh_history_s", "lower"),
    ("headroom_10x_s", "headroom_10x.value_s", "lower"),
    ("interpreter_ops_per_s", "interpreter_ops_per_s", "higher"),
    ("interpreter_100w_ops_per_s", "interpreter_100w_ops_per_s",
     "higher"),
    ("batch_replay_100_s", "batch_replay_100.value_s", "lower"),
    ("batch_replay_large_s", "batch_replay_large.value_s", "lower"),
    ("smoke_8x10k_s", "batch_replay_large.smoke_8x10k.value_s", "lower"),
    ("elle_txn_s", "elle_txn.value_s", "lower"),
    ("big_scc_4096_s", "elle_txn.big_scc_4096.value_s", "lower"),
    # Batched Elle SCC/closure engine (ISSUE 19): co-batched
    # throughput across size buckets, and the speedup over the serial
    # per-graph engine baseline sampled in-leg (info: the pin lives in
    # the leg's own error field).
    ("elle_txns_per_s", "elle_scc_batched.elle_txns_per_s", "higher"),
    ("elle_batch_speedup_x", "elle_scc_batched.elle_batch_speedup_x",
     "info"),
    # Trace ingestion (ISSUE 20): raw etcd recording → adapter →
    # pairing → segmented WGL; the verdict/unmapped pins live in the
    # leg's own error field.
    ("ingest_ops_per_s", "ingest_etcd_10k.ingest_ops_per_s", "higher"),
    ("ingest_etcd_10k_s", "ingest_etcd_10k.value_s", "lower"),
    ("mutex_5k_s", "mutex_5k.value_s", "lower"),
    ("device_kernel_s", "device_kernel_s", "lower"),
    ("per_level_ms", "per_level_ms", "lower"),
    ("device_util", "device_util", "higher"),
    ("hbm_copy_gbs", "hbm_copy_gbs", "higher"),
    ("max_verified_ops", "max_verified_ops.ops", "higher"),
    ("max_verified_ops_per_s", "max_verified_ops.ops_per_s", "higher"),
    ("max_verified_ops_device", "max_verified_ops_device.ops", "higher"),
    ("max_verified_ops_device_sharded",
     "max_verified_ops_device_sharded.ops", "higher"),
    ("smoke_8x10k_decided",
     "batch_replay_large.smoke_8x10k.decided", "higher"),
    # Device-saturation observability (ISSUE 7): mean device
    # utilization of the smoke leg's escalation schedule, reconstructed
    # from stamped batch-chunk events (telemetry.utilization) — the
    # ROADMAP "first metric to watch" leg, now watched for EFFICIENCY
    # and not just decided>=1. Shrinking = the ladder idles the mesh.
    ("smoke_8x10k_utilization_pct",
     "batch_replay_large.smoke_8x10k.utilization_pct", "higher"),
    ("bench_wall_s", "bench_wall_s", "info"),
    ("multichip_ok", "multichip_ok", "higher"),
    # Owner-partitioned frontier exchange (ISSUE 4): the analytic
    # per-device per-level exchange bytes of the sharded search on the
    # multichip mesh — seconds-like direction (more interconnect bytes
    # per level is a regression); the drop factor vs the replicated
    # all_gather model is scale-like (it should ride mesh size).
    ("multichip_exchange_bytes_per_level",
     "exchange_bytes_per_level.alltoall", "lower"),
    ("multichip_exchange_drop_x", "exchange_drop_x", "higher"),
    # Online linearizability monitor (ISSUE 5): history ops observed
    # before the first invalid segment's verdict lands on the
    # seeded-invalid stream, and the end-to-end cost of deciding WHILE
    # streaming vs post-hoc — both regressions when they grow.
    ("online_ops_to_detection", "online_10k.ops_to_detection", "lower"),
    ("online_overhead_pct", "online_10k.online_overhead_pct", "lower"),
    # Decision-latency tracing (ISSUE 6): the p99 invoke→watermark-
    # covered lag of the online monitor's seeded-invalid 10k-op stream
    # — THE serving-stack signal ROADMAP item 3 benches against. Growth
    # = the scheduler/pipeline got slower at covering ops; lower only.
    ("online_p99_decision_latency_s",
     "online_10k.p99_decision_latency_s", "lower"),
    # Multi-tenant checking service (ISSUE 8): sustained throughput of
    # N concurrent tenant streams through the shared co-batching
    # scheduler, and the service-wide p99 invoke→watermark-covered lag
    # — the "heavy traffic from millions of users" serving numbers
    # ROADMAP item 3 benches. Throughput shrinking or tail latency
    # growing is a regression.
    ("service_sustained_ops_per_s",
     "service_streams.sustained_ops_per_s", "higher"),
    ("service_p99_decision_latency_s",
     "service_streams.p99_decision_latency_s", "lower"),
    # Fault-tolerant checking pipeline (ISSUE 10): the service leg now
    # ALWAYS runs with one injected transient device fault, so its
    # sustained ops/s is the RECOVERED throughput; `failovers` records
    # how many oracle rounds were demoted to host re-dispatch.
    # Direction "info": the count documents chaos coverage in the
    # trajectory — more or fewer failovers is a configuration fact,
    # not a regression.
    ("service_failovers_total", "service_streams.failovers", "info"),
    # Alerting plane (alerts PR): how long the armed journal fault
    # took to flip `journal_errors` to firing (growing = the watchdog
    # reacts slower), and what the rule catalogue's evaluation cost
    # against the service leg's wall clock (growing = the always-on
    # plane stopped being negligible; the bench gates it under 2%).
    ("alert_detection_seconds",
     "service_streams.alert_detection_seconds", "lower"),
    ("alert_eval_overhead_pct",
     "service_streams.alert_eval_overhead_pct", "lower"),
    # Horizontal service resilience (router PR): 2 backend processes ×
    # 4 tenants behind the tenant router with one injected kill-9
    # mid-run — the sustained throughput is the RECOVERED-after-
    # migration number (shrinking = the outage window or the proxy
    # overhead grew), and `router_migration_seconds` prices the
    # journal-backed migration itself (checkpoint handover + adopt
    # replay + placement flip; growing = recovery got slower).
    ("router_sustained_ops_per_s",
     "service_router.sustained_ops_per_s", "higher"),
    ("router_migration_seconds",
     "service_router.migration_seconds", "lower"),
    # Self-healing fleet (supervision PR): the leg's kill now runs a
    # FULL kill→respawn→re-adopt cycle; this prices the repair half
    # (spawn → /healthz on the replacement child; growing = recovery
    # to N capacity got slower).
    ("router_respawn_seconds",
     "service_router.respawn_seconds", "lower"),
    # Fleet observability (federation PR): the REAL cross-process p99
    # from the router's bucket-merged federated histograms (growing =
    # the fleet's decision tail got slower — this is the quantile the
    # SLO monitor burns against, not a max of per-backend p99s), and
    # the coldest backend's busy share over the bench window
    # (shrinking = placement is leaving more paid-for capacity idle).
    ("fleet_p99_decision_latency_s",
     "service_router.fleet_p99_decision_latency_s", "lower"),
    ("fleet_min_backend_utilization_pct",
     "service_router.fleet_min_backend_utilization_pct", "higher"),
    # Offline decrease-and-conquer (segment planner PR): end-to-end
    # plan+decide throughput over a recorded ≥1M-op keyed history
    # through the co-batching scheduler (shrinking = the planner or
    # the ready-take pipeline got slower). `speedup_vs_serial` is
    # "info": it divides by a sample-measured single-driver rate whose
    # superlinear cost makes the ratio a machine-dependent lower
    # bound — the scale pin asserts it in tests, the table shows it.
    ("offline_segmented_ops_per_s",
     "offline_segmented.ops_per_s", "higher"),
    ("offline_segmented_speedup_vs_serial",
     "offline_segmented.speedup_vs_serial", "info"),
]

DEFAULT_THRESHOLD = 0.10


def _dig(d: Any, path: str) -> Optional[float]:
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool):
        return float(cur)
    if isinstance(cur, (int, float)):
        return float(cur)
    return None


def _parse_json_line(line: str) -> Optional[dict]:
    line = line.strip()
    if not line.startswith("{") or not line.endswith("}"):
        return None
    try:
        d = json.loads(line)
        return d if isinstance(d, dict) else None
    except ValueError:
        return None


def _recover_fragment(text: str) -> Optional[dict]:
    """Recover a dict from a HEAD-TRUNCATED JSON line (a tail capture
    that cut the front off): clip forward to the first complete
    ``, "key":`` boundary and re-open the object there. Loses the
    severed leading keys, keeps everything after — r5's final line
    yields 20+ of its metrics this way."""
    if not text.rstrip().endswith("}"):
        return None
    for m in re.finditer(r', "', text):
        candidate = '{"' + text[m.end():]
        try:
            d = json.loads(candidate)
            if isinstance(d, dict) and d:
                return d
        except ValueError:
            continue
    return None


def _last_bench_line(text: str) -> Optional[dict]:
    """The newest parseable bench JSON line in a blob of output (the
    documented last-parseable-line contract), falling back to fragment
    recovery on the final line."""
    best = None
    for line in text.splitlines():
        d = _parse_json_line(line)
        if d is not None and ("metric" in d or "bench_wall_s" in d):
            best = d
    if best is not None:
        return best
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if lines:
        rec = _recover_fragment(lines[-1])
        if rec is not None and ("bench_wall_s" in rec or "metric" in rec):
            rec["recovered_fragment"] = True
            return rec
    return None


def round_label(path: str) -> str:
    m = re.search(r"_r(\d+)", os.path.basename(path))
    if m:
        return f"r{int(m.group(1)):02d}"
    return os.path.splitext(os.path.basename(path))[0]


def round_sort_key(path: str) -> tuple:
    """NUMERIC ordering key for round artifacts — the label string is
    only 2-padded, so sorting by it (or by raw path) misplaces r100
    vs r99; every 'newest round' lookup must sort with this."""
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return ((int(m.group(1)) if m else 10 ** 9),
            os.path.basename(path))


def load_round(path: str) -> dict:
    """One artifact -> {"label", "path", "data", "kind"}; ``data`` is
    the flat bench dict (possibly recovered), ``{}`` when nothing in the
    file parses (the gate shows the hole instead of crashing)."""
    with open(path) as f:
        raw = json.load(f)
    label = round_label(path)
    kind = "bench"
    data: dict = {}
    if isinstance(raw, dict) and "n_devices" in raw:
        kind = "multichip"
        data = {"multichip_ok": bool(raw.get("ok")),
                "n_devices": raw.get("n_devices")}
        inner = raw.get("parsed")
        if isinstance(inner, dict):
            data.update(inner)
        elif isinstance(raw.get("tail"), str):
            # dryrun_multichip prints one machine-readable JSON line
            # (exchange byte model, mode agreement) amid the backend's
            # log noise — the newest one wins.
            for line in raw["tail"].splitlines():
                d = _parse_json_line(line)
                if d is not None and ("multichip" in d
                                      or "exchange_bytes_per_level" in d):
                    data.update(d)
    elif isinstance(raw, dict) and ("parsed" in raw or "tail" in raw):
        inner = raw.get("parsed")
        if isinstance(inner, dict):
            data = dict(inner)
        elif isinstance(raw.get("tail"), str):
            data = _last_bench_line(raw["tail"]) or {}
        if raw.get("rc") not in (0, None):
            data.setdefault("driver_rc", raw["rc"])
    elif isinstance(raw, dict):
        data = raw
    return {"label": label, "path": path, "data": data, "kind": kind}


def extract(data: dict) -> dict:
    """Flatten one round's data into the metric catalogue's values."""
    return {name: _dig(data, path) for name, path, _dir in METRICS
            if _dig(data, path) is not None}


def _merge_rounds(rounds: list[dict]) -> list[dict]:
    """Merge same-label artifacts (BENCH + MULTICHIP of one round) into
    one column, in NUMERIC round order (the 2-padded label sorts r100
    before r99 lexically — the gate would compare the newest pair
    backwards)."""
    by_label: dict[str, dict] = {}
    for r in rounds:
        tgt = by_label.setdefault(
            r["label"], {"label": r["label"], "metrics": {},
                         "paths": []})
        tgt["paths"].append(r["path"])
        tgt["metrics"].update(extract(r["data"]))
    return sorted(by_label.values(),
                  key=lambda m: round_sort_key(m["paths"][0]))


def deltas(prev: dict, cur: dict,
           threshold: float = DEFAULT_THRESHOLD,
           metrics: Optional[list] = None) -> dict:
    """Metric-wise delta block between two rounds' extracted metrics:
    ``{metric: {prev, cur, delta_pct, regression}}``. ``delta_pct`` is
    signed (cur vs prev); regression is direction-aware and gated at
    ``threshold`` (fraction). ``metrics`` defaults to the bench
    catalogue; the cross-run ledger (``jepsen_tpu.telemetry.ledger``)
    reuses this machinery with its own catalogue."""
    out: dict = {}
    for name, _path, direction in (metrics if metrics is not None
                                   else METRICS):
        p, c = prev.get(name), cur.get(name)
        if p is None or c is None:
            continue
        d: dict = {"prev": p, "cur": c}
        if p != 0:
            pct = (c - p) / abs(p) * 100.0
            d["delta_pct"] = round(pct, 1)
            if direction == "lower":
                d["regression"] = pct > threshold * 100.0
            elif direction == "higher":
                d["regression"] = pct < -threshold * 100.0
            else:
                d["regression"] = False
        else:
            d["regression"] = direction == "higher" and c < p
        out[name] = d
    return out


def regressions(delta_block: dict) -> list[str]:
    return sorted(k for k, v in delta_block.items()
                  if v.get("regression"))


def vs_previous(current: dict, artifact_glob: str = "BENCH_r*.json",
                root: Optional[str] = None,
                threshold: float = DEFAULT_THRESHOLD) -> Optional[dict]:
    """Delta block of a just-measured bench dict vs the NEWEST committed
    round artifact — what bench.py embeds as ``vs_previous`` so a
    regression is self-reported inside the new round's own JSON line.
    None when no prior artifact exists or none parses."""
    root = root or os.path.dirname(os.path.abspath(__file__)) + "/.."
    # Numeric round order — lexical path (or 2-padded label) order
    # misplaces r9 vs r10 (and r99 vs r100).
    paths = sorted(glob.glob(os.path.join(root, artifact_glob)),
                   key=round_sort_key)
    if not paths:
        return None
    prev = load_round(paths[-1])
    pm = extract(prev["data"])
    if not pm:
        return None
    block = deltas(pm, extract(current), threshold=threshold)
    if not block:
        return None
    return {
        "round": prev["label"],
        "path": os.path.basename(prev["path"]),
        "threshold_pct": round(threshold * 100.0, 1),
        "deltas": block,
        "regressions": regressions(block),
    }


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "—"
    if v == int(v) and abs(v) < 1e12:
        return str(int(v))
    return f"{v:.4g}"


def render_table(merged: list[dict],
                 metrics: Optional[list] = None) -> str:
    """Metric-by-round text table (metrics as rows, rounds as
    columns). ``metrics`` defaults to the bench catalogue (the ledger
    passes its own)."""
    labels = [m["label"] for m in merged]
    rows = []
    for name, _path, direction in (metrics if metrics is not None
                                   else METRICS):
        vals = [m["metrics"].get(name) for m in merged]
        if all(v is None for v in vals):
            continue
        arrow = {"lower": "↓", "higher": "↑", "info": " "}[direction]
        rows.append([f"{name} {arrow}"] + [_fmt(v) for v in vals])
    widths = [max(len(r[i]) for r in rows + [["metric"] + labels])
              for i in range(len(labels) + 1)]
    lines = ["  ".join(s.ljust(w) for s, w in
                       zip(["metric"] + labels, widths))]
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(s.ljust(w) for s, w in zip(r, widths)))
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.benchcmp",
        description="Render the bench-round trajectory and gate on "
                    "regressions.")
    p.add_argument("artifacts", nargs="*",
                   help="BENCH_r*.json / MULTICHIP_r*.json round files")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help="regression threshold as a fraction "
                        "(default 0.10 = 10%%)")
    p.add_argument("--all", action="store_true",
                   help="gate every adjacent round pair, not just the "
                        "newest")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the table + comparisons as JSON")
    ns = p.parse_args(argv)

    try:
        rounds = [load_round(a) for a in ns.artifacts]
    except (OSError, ValueError) as e:
        print(f"benchcmp: cannot read artifacts: {e}", file=sys.stderr)
        return 2
    merged = _merge_rounds(rounds)
    if len(merged) < 2:
        # A fresh repo (or a CI invocation before the second committed
        # round) has nothing to gate: that is a clean no-op, not a
        # failure — exit 0 so pipelines can call benchcmp
        # unconditionally.
        print(f"benchcmp: nothing to compare — {len(merged)} round(s) "
              "given, need at least 2 committed rounds")
        if merged:
            print(render_table(merged))
        return 0

    comparisons = []
    for prev, cur in zip(merged, merged[1:]):
        block = deltas(prev["metrics"], cur["metrics"],
                       threshold=ns.threshold)
        comparisons.append({
            "from": prev["label"], "to": cur["label"],
            "deltas": block, "regressions": regressions(block)})
    gated = comparisons if ns.all else comparisons[-1:]
    flagged = [c for c in gated if c["regressions"]]

    if ns.as_json:
        print(json.dumps({
            "rounds": [{"label": m["label"], "metrics": m["metrics"]}
                       for m in merged],
            "comparisons": comparisons,
            "threshold": ns.threshold,
            "flagged": [{k: c[k] for k in ("from", "to", "regressions")}
                        for c in flagged],
        }, indent=1, sort_keys=True))
    else:
        print(render_table(merged))
        for c in comparisons:
            marks = []
            for name in sorted(c["deltas"]):
                d = c["deltas"][name]
                if "delta_pct" not in d:
                    continue
                flag = " ** REGRESSION" if d["regression"] else ""
                if d["regression"] or abs(d["delta_pct"]) >= 5:
                    marks.append(
                        f"  {name}: {_fmt(d['prev'])} -> "
                        f"{_fmt(d['cur'])} ({d['delta_pct']:+.1f}%)"
                        f"{flag}")
            if marks:
                print(f"\n{c['from']} -> {c['to']}:")
                print("\n".join(marks))
        if flagged:
            names = {n for c in flagged for n in c["regressions"]}
            print(f"\nREGRESSIONS past {ns.threshold * 100:.0f}%: "
                  + ", ".join(sorted(names)))
        else:
            print(f"\nno regressions past {ns.threshold * 100:.0f}% "
                  f"({'all pairs' if ns.all else 'newest round'})")
    return 1 if flagged else 0


if __name__ == "__main__":
    sys.exit(main())
