"""Device-saturation observability: per-device busy timelines, idle-gap
attribution, and the occupancy Gantt.

ROADMAP item 1 wants "the segment scheduler to saturate all devices
instead of one" and item 3 wants sustained multi-stream throughput — but
until now nothing measured saturation: the roofline profiler (PR 3) says
*how well a chunk used the chip while it ran*, and `/live` (PR 6) shows
queue depths, yet no view existed of *which device was busy when* or
*why a device sat idle while work was queued*. This module closes that
gap host-side, from the timed chunk events the drivers already emit:

- ``wgl_chunk`` (single-device driver), ``wgl_batch_chunk`` (batched
  escalation — covers the ``n_devices`` dp-mesh devices), and
  ``wgl_sharded_chunk`` (frontier-sharded — covers ``n_shards``
  devices), each carrying wall-clock ``t0``/``t1`` stamps and a
  ``stage`` (compile vs execute);
- ``wgl_host_stack`` events (batch.py's next-bucket table assembly);
- the ``online_backlog`` timeline (the ``online_scheduler_backlog``
  gauge, stamped per transition by the scheduler).

:func:`reconstruct` merges each device's execute-stage chunk intervals
into busy spans, computes per-device ``device_utilization_pct{device}``
(also set as a labeled gauge on the registry), a makespan /
critical-path summary, and classifies every idle gap into EXACTLY one
of four classes, in priority order:

1. **compiling** — a compile-stage chunk on this device overlaps the
   gap (the wall is jit trace/lower/compile cost, the chip is idle);
2. **host-stacking** — a ``wgl_host_stack`` interval overlaps the gap
   (the next bucket's static tables were being assembled on the host);
3. **starved** — the scheduler backlog was > 0 during the gap but
   nothing was dispatched to this device — the exact signal ROADMAP
   item 1 needs;
4. **no-work** — the backlog was empty (or no scheduler ran): there was
   genuinely nothing to run.

The semantics are pinned closed-form by tests/test_utilization.py
(known chunk stamps → known utilization % and gap classes), and the
``/utilization`` web page renders :func:`render_gantt`'s SVG occupancy
chart (no plotting dependency). See docs/profiling.md ("Utilization &
ledger").

Off path: this module is only imported behind a telemetry registry
that actually recorded chunk events (``profile._attribute_utilization``
checks first) — with telemetry disabled it is never imported, which
tests/test_telemetry.py pins with an import guard.
"""

from __future__ import annotations

import html as _html
from typing import Iterable, Optional

GAP_CLASSES = ("no-work", "starved", "host-stacking", "compiling")

# Chunk-event families and how many devices each one covers.
CHUNK_EVENTS = ("wgl_chunk", "wgl_batch_chunk", "wgl_sharded_chunk")

_EPS = 1e-9  # overlap/length tolerance for float stamps


def _devices_of(ev: dict) -> int:
    """How many mesh devices one chunk event kept busy: the sharded
    kernel runs on every shard, the batched kernel on the dp mesh, the
    single driver on one device. Events predating the field count 1."""
    name = ev.get("name")
    if name == "wgl_sharded_chunk":
        return max(int(ev.get("n_shards") or 1), 1)
    if name == "wgl_batch_chunk":
        return max(int(ev.get("n_devices") or 1), 1)
    return 1


def _stamped(ev: dict) -> Optional[tuple[float, float]]:
    """(t0, t1) wall-clock interval of a stamped event; None for
    recordings predating the stamps (duration-only events cannot be
    placed on a timeline)."""
    t0, t1 = ev.get("t0"), ev.get("t1")
    if t0 is None or t1 is None:
        return None
    t0, t1 = float(t0), float(t1)
    if t1 < t0:
        t0, t1 = t1, t0
    return (t0, t1)


def _merge(intervals: Iterable[tuple[float, float]]
           ) -> list[tuple[float, float]]:
    """Sorted union of intervals (touching/overlapping spans fuse)."""
    out: list[list[float]] = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1] + _EPS:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _overlaps(intervals: list[tuple[float, float]],
              g0: float, g1: float) -> bool:
    return any(min(b, g1) - max(a, g0) > _EPS for a, b in intervals)


def _gaps(busy: list[tuple[float, float]], w0: float, w1: float
          ) -> list[tuple[float, float]]:
    """Complement of the busy union within the [w0, w1] window."""
    out = []
    cur = w0
    for a, b in busy:
        if a - cur > _EPS:
            out.append((cur, a))
        cur = max(cur, b)
    if w1 - cur > _EPS:
        out.append((cur, w1))
    return out


def _backlog_during(timeline: list[tuple[float, float]],
                    g0: float, g1: float) -> float:
    """Max scheduler backlog over [g0, g1]: the value holding at g0
    (last transition at or before it) plus any transition inside the
    gap. Empty timeline → 0 (no scheduler ran: no work was queued)."""
    if not timeline:
        return 0.0
    best = 0.0
    holding = None
    for t, v in timeline:  # sorted by t
        if t <= g0 + _EPS:
            holding = v
        elif t < g1 - _EPS:
            best = max(best, v)
        else:
            break
    if holding is not None:
        best = max(best, holding)
    return best


def _classify(g0: float, g1: float,
              compiling: list[tuple[float, float]],
              stacking: list[tuple[float, float]],
              backlog: list[tuple[float, float]]) -> str:
    """One class per gap, in priority order (see module docstring)."""
    if _overlaps(compiling, g0, g1):
        return "compiling"
    if _overlaps(stacking, g0, g1):
        return "host-stacking"
    if _backlog_during(backlog, g0, g1) > 0:
        return "starved"
    return "no-work"


def _bound(rows: list, cap: int, elide_key: str, out: dict) -> list:
    """Head+tail bound on a per-device list so profile.json stays
    small; the elided count is recorded, never silently dropped."""
    if len(rows) <= cap:
        return rows
    head = rows[: cap // 2]
    tail = rows[-(cap - len(head)):]
    out[elide_key] = len(rows) - len(head) - len(tail)
    return head + tail


def reconstruct(registry, max_intervals: int = 200,
                max_gaps: int = 200) -> Optional[dict]:
    """Rebuild per-device busy timelines + idle-gap attribution from a
    run's registry. Returns None when no stamped chunk events exist
    (telemetry-off runs never get here; pre-stamp recordings have no
    timeline to rebuild). Also sets the ``device_utilization_pct
    {device}`` gauge per device on the registry."""
    busy: dict[int, list[tuple[float, float]]] = {}
    compiling: dict[int, list[tuple[float, float]]] = {}
    chunks_per_dev: dict[int, int] = {}
    stacking: list[tuple[float, float]] = []
    backlog: list[tuple[float, float]] = []
    w0, w1 = None, None
    for ev in registry.events():
        name = ev.get("name")
        if name == "wgl_host_stack":
            iv = _stamped(ev)
            if iv is not None:
                stacking.append(iv)
            continue
        if name == "online_backlog":
            t = ev.get("t")
            if t is not None:
                backlog.append((float(t), float(ev.get("backlog") or 0)))
            continue
        if name not in CHUNK_EVENTS:
            continue
        iv = _stamped(ev)
        if iv is None:
            continue
        w0 = iv[0] if w0 is None else min(w0, iv[0])
        w1 = iv[1] if w1 is None else max(w1, iv[1])
        target = compiling if ev.get("stage") == "compile" else busy
        for d in range(_devices_of(ev)):
            target.setdefault(d, []).append(iv)
            if target is busy:
                chunks_per_dev[d] = chunks_per_dev.get(d, 0) + 1
    if w0 is None:
        return None
    backlog.sort()
    stacking = _merge(stacking)
    makespan = max(w1 - w0, _EPS)
    n_devices = max(len(busy) or 1, len(compiling) or 1)

    devices = []
    union_any: list[tuple[float, float]] = []
    per_dev_busy: dict[int, list[tuple[float, float]]] = {}
    gap_s: dict[str, float] = {c: 0.0 for c in GAP_CLASSES}
    util_by_dev: dict[str, float] = {}
    for d in range(n_devices):
        merged = _merge(busy.get(d, ()))
        per_dev_busy[d] = merged
        union_any.extend(merged)
        busy_s = sum(b - a for a, b in merged)
        util = round(busy_s / makespan * 100.0, 2)
        util_by_dev[str(d)] = util
        comp_d = _merge(compiling.get(d, ()))
        gaps = []
        dev_gap_s: dict[str, float] = {}
        for g0, g1 in _gaps(merged, w0, w1):
            cls = _classify(g0, g1, comp_d, stacking, backlog)
            gaps.append({"t0_s": round(g0 - w0, 6),
                         "t1_s": round(g1 - w0, 6),
                         "wall_s": round(g1 - g0, 6), "class": cls})
            dev_gap_s[cls] = dev_gap_s.get(cls, 0.0) + (g1 - g0)
            gap_s[cls] += g1 - g0
        row: dict = {
            "device": d,
            "chunks": chunks_per_dev.get(d, 0),
            "busy_s": round(busy_s, 6),
            "utilization_pct": util,
            "gap_s": {c: round(v, 6) for c, v in sorted(dev_gap_s.items())},
        }
        row["intervals"] = _bound(
            [[round(a - w0, 6), round(b - w0, 6)] for a, b in merged],
            max_intervals, "intervals_elided", row)
        row["gaps"] = _bound(gaps, max_gaps, "gaps_elided", row)
        devices.append(row)

    busy_any = _merge(union_any)
    busy_any_s = sum(b - a for a, b in busy_any)
    # busy_all: time EVERY device was busy (intersection) — with the
    # per-device unions in hand, sweep the union's spans against each.
    busy_all_s = 0.0
    for a, b in busy_any:
        seg = [(a, b)]
        for d in range(n_devices):
            nxt = []
            for s0, s1 in seg:
                for x0, x1 in per_dev_busy[d]:
                    lo, hi = max(s0, x0), min(s1, x1)
                    if hi - lo > _EPS:
                        nxt.append((lo, hi))
            seg = nxt
            if not seg:
                break
        busy_all_s += sum(s1 - s0 for s0, s1 in seg)

    idle_total = sum(gap_s.values())
    utils = list(util_by_dev.values())
    summary: dict = {
        "n_devices": n_devices,
        "makespan_s": round(makespan, 6),
        "device_utilization_pct": util_by_dev,
        "mean_utilization_pct": round(sum(utils) / len(utils), 2),
        "min_utilization_pct": min(utils),
        "max_utilization_pct": max(utils),
        "busy_any_s": round(busy_any_s, 6),
        "busy_all_s": round(busy_all_s, 6),
        # Critical path: the fraction of the makespan during which at
        # least one device was busy — the ceiling any scheduler
        # rebalancing could reach without shortening the serial chain.
        "critical_path_pct": round(busy_any_s / makespan * 100.0, 2),
        "idle_s_total": round(idle_total, 6),
        "gap_attribution_s": {c: round(v, 6)
                              for c, v in sorted(gap_s.items()) if v > 0},
    }
    if idle_total > _EPS:
        summary["gap_attribution_share"] = {
            c: round(v / idle_total, 4)
            for c, v in sorted(gap_s.items()) if v > 0}
    try:
        g = registry.gauge(
            "device_utilization_pct",
            "Per-device busy share of the run makespan, reconstructed "
            "from timed chunk events", labelnames=("device",))
        for d, pct in util_by_dev.items():
            g.labels(device=d).set(pct)
    except Exception:  # noqa: BLE001 - a read-only registry still reports
        pass
    return {
        "window": {"t0": round(w0, 6), "t1": round(w1, 6),
                   "makespan_s": round(makespan, 6)},
        "devices": devices,
        "summary": summary,
    }


# ---------------------------------------------------------------------------
# SVG occupancy Gantt (no plotting dependency — hand-rolled like
# checker/linear_viz.py)

_C_BUSY = "#78a878"
_C_GAP = {"no-work": "#d8d8d8", "starved": "#c24f4f",
          "host-stacking": "#d99a3d", "compiling": "#7d7dc2"}


def render_gantt(util: dict, width: int = 960) -> str:
    """One SVG lane per device: busy spans in green, idle gaps colored
    by class — the ``/utilization`` page's chart. ``util`` is
    :func:`reconstruct`'s output (or the block stored in
    profile.json)."""
    devices = util.get("devices") or []
    makespan = float((util.get("window") or {}).get("makespan_s")
                     or (util.get("summary") or {}).get("makespan_s")
                     or 1.0)
    x0, lane_h, pad = 70, 24, 14
    plot_w = max(width - x0 - 20, 10)
    scale = plot_w / max(makespan, _EPS)
    height = 40 + lane_h * max(len(devices), 1) + 46

    def x(t: float) -> float:
        return x0 + t * scale

    s = util.get("summary") or {}
    svg = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{height}" font-family="monospace" font-size="11">',
        f'<text x="8" y="16" font-size="13">device occupancy — mean '
        f'{_html.escape(str(s.get("mean_utilization_pct", "?")))}% over '
        f'{_html.escape(str(round(makespan, 3)))}s makespan, critical '
        f'path {_html.escape(str(s.get("critical_path_pct", "?")))}%'
        f'</text>',
    ]
    for li, dev in enumerate(devices):
        y = 30 + li * lane_h
        svg.append(f'<text x="8" y="{y + 14}">dev '
                   f'{_html.escape(str(dev.get("device")))} '
                   f'{_html.escape(str(dev.get("utilization_pct")))}%'
                   f'</text>')
        for g in dev.get("gaps") or []:
            gx0, gx1 = x(g["t0_s"]), x(g["t1_s"])
            color = _C_GAP.get(g.get("class"), "#eee")
            svg.append(
                f'<rect x="{gx0:.1f}" y="{y + 2}" '
                f'width="{max(gx1 - gx0, 1):.1f}" height="{lane_h - 8}" '
                f'fill="{color}" fill-opacity="0.85">'
                f'<title>{_html.escape(str(g.get("class")))} '
                f'{g["wall_s"]}s</title></rect>')
        for a, b in dev.get("intervals") or []:
            bx0, bx1 = x(a), x(b)
            svg.append(
                f'<rect x="{bx0:.1f}" y="{y + 2}" '
                f'width="{max(bx1 - bx0, 1):.1f}" height="{lane_h - 8}" '
                f'rx="2" fill="{_C_BUSY}">'
                f'<title>busy {round(b - a, 4)}s</title></rect>')
    ly = 30 + lane_h * max(len(devices), 1) + 16
    lx = x0
    for color, name in [(_C_BUSY, "busy")] + [
            (_C_GAP[c], c) for c in GAP_CLASSES]:
        svg.append(f'<rect x="{lx}" y="{ly}" width="12" height="12" '
                   f'rx="2" fill="{color}"/>')
        svg.append(f'<text x="{lx + 16}" y="{ly + 10}">{name}</text>')
        lx += 30 + 8 * len(name)
    svg.append("</svg>")
    return "\n".join(svg)
