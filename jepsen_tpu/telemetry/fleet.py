"""Fleet-wide metrics federation, scrape staleness and SLO burn rates.

The router fronts N backend processes (live migration, respawn
supervision, epoch fencing — the PR-14/15 fleet), but every registry is
per-process: nothing could answer "is every backend saturated?" or give
a *real* fleet p99. This module makes the fleet observable as ONE
system.

Federation model
----------------
Each backend serves its live registry over ``GET /metrics`` (Prometheus
text exposition, for humans and external scrapers) and
``GET /metrics.json`` (:func:`scrape_payload` — the samples, help
strings and the bounded event ring). The router scrapes the JSON form
on its probe cadence: re-parsing our own text exposition would discard
the event ring the utilization reconstruction needs, and histograms
would arrive cumulated. :class:`FleetFederation` REPLACES each
backend's snapshot wholesale on every successful scrape — it never
accumulates across scrapes, so a respawned backend's fresh (lower)
counters simply replace the dead generation's: no double-count across
generations, by construction.

Merge rules (:func:`merge_samples`)
-----------------------------------
Every family is re-labeled into per-backend children
(``name{...,backend="b0"}``) plus ONE cross-backend total per original
labelset:

- counters and gauges: totals sum across backends (a gauge total is the
  fleet-wide level, e.g. ``service_tenants`` = tenants anywhere);
- histograms: per-bucket counts merge (``count``/``sum`` add), so the
  fleet p99 is a real quantile of the merged distribution — NOT an
  average of per-backend averages. Histogram children whose bucket
  bounds differ across backends keep their per-backend children but get
  no total: merging mismatched buckets would fabricate a distribution.

Staleness
---------
A scrape failure keeps the last snapshot but lets its age grow
(``fleet_scrape_age_seconds{backend}``, ``fleet_scrape_failures_total``)
— a dead or mid-respawn backend reads as *stale*, never as
silently-zero. ``fleet_backends_stale`` counts backends whose age
passed the threshold (or that were expected but never scraped).

SLO burn rates (:class:`SloMonitor`)
------------------------------------
Two fleet SLOs computed from the federated totals over a fast and a
slow window (the multiwindow burn-rate alerting shape): availability
(rejects vs. attempts) and decision latency (share of ops decided
slower than the target). ``burn rate = bad-fraction / error budget`` —
1.0 means the budget burns exactly at the sustainable rate; the advisor
thresholds live in :mod:`jepsen_tpu.advisor` (``slo_burn``).

Everything here is pure over ``Registry.collect()``-shaped sample
lists; tests/test_fleet.py pins the merge/staleness/burn semantics
closed-form, without processes.
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Any, Iterable, Optional, Sequence

from . import export as _export
from .registry import bucket_quantile

# Tail of the backend event ring shipped per scrape: bounds the payload
# while keeping the recent window the utilization Gantt renders.
MAX_SCRAPE_EVENTS = 20_000

# A backend whose last successful scrape is older than this reads as
# stale (the router default: a handful of probe intervals).
SCRAPE_STALE_AFTER_S = 5.0

# SLO defaults: 99.9% of submits accepted; 99% of accepted ops decided
# within 30 s (the decision-latency bucket bound right above the online
# monitor's worst healthy tail).
SLO_AVAILABILITY_TARGET = 0.999
SLO_LATENCY_TARGET_S = 30.0
SLO_LATENCY_RATIO = 0.99
SLO_FAST_WINDOW_S = 60.0
SLO_SLOW_WINDOW_S = 600.0


def scrape_payload(registry, *, service: Optional[str] = None,
                   max_events: int = MAX_SCRAPE_EVENTS) -> dict:
    """The backend side of one federation scrape: every metric sample,
    the help strings (so the router's merged exposition keeps them) and
    the tail of the bounded event ring (the chunk/backlog events the
    fleet utilization view reconstructs from)."""
    with registry._lock:
        helps = {n: m.help for n, m in registry._metrics.items()
                 if m.help}
    events = registry.events()
    if max_events is not None and len(events) > max_events:
        events = events[-max_events:]
    return {
        "v": 1,
        "service": service,
        "t": round(_time.time(), 3),
        "samples": registry.collect(),
        "helps": helps,
        "events": events,
    }


def _bounds_counts(buckets: dict) -> tuple[list[float], list[int]]:
    """Split a sample's ``buckets`` dict into ascending finite bounds +
    counts (with the ``+Inf`` count appended last) — the
    :func:`bucket_quantile` calling convention."""
    finite = sorted((float(k), int(v)) for k, v in buckets.items()
                    if k != "+Inf")
    bounds = [b for b, _ in finite]
    counts = [c for _, c in finite]
    counts.append(int(buckets.get("+Inf", 0)))
    return bounds, counts


def stats_from_sample(sample: dict,
                      quantiles: Sequence[float] = (0.5, 0.9, 0.99)
                      ) -> dict:
    """``Histogram.stats()``-shaped summary of one histogram sample
    (works on merged fleet totals just as well as raw children)."""
    bounds, counts = _bounds_counts(sample.get("buckets") or {})
    out: dict = {"count": int(sample.get("count") or 0),
                 "sum_s": round(float(sample.get("sum") or 0.0), 6)}
    for q in quantiles:
        v = bucket_quantile(bounds, counts, q)
        out[f"p{int(round(q * 100))}_s"] = (
            round(v, 6) if v is not None else None)
    return out


def merge_samples(per_backend: dict[str, list[dict]]) -> list[dict]:
    """Federate per-backend sample lists into one fleet view: every
    sample re-labeled with ``backend=<name>``, plus one cross-backend
    total per (family, original labelset) — see the module docstring
    for the per-type merge rules. Output is sorted by (name, labels)
    like ``Registry.collect()``."""
    children: list[dict] = []
    totals: dict[tuple, Optional[dict]] = {}
    for b in sorted(per_backend):
        for s in per_backend[b]:
            labels = dict(s.get("labels") or {})
            child = dict(s)
            child["labels"] = {**labels, "backend": b}
            children.append(child)
            key = (s.get("name"), tuple(sorted(labels.items())))
            tot = totals.get(key)
            if s.get("type") == "histogram":
                sb = s.get("buckets") or {}
                if key not in totals:
                    totals[key] = {
                        "name": s.get("name"), "type": "histogram",
                        "labels": labels, "count": 0, "sum": 0.0,
                        "buckets": {k: 0 for k in sb},
                    }
                    tot = totals[key]
                elif tot is not None and set(tot["buckets"]) != set(sb):
                    # Mismatched bucket bounds: merging would fabricate
                    # a distribution — keep children, drop the total.
                    totals[key] = None
                    continue
                if tot is None:
                    continue
                tot["count"] += int(s.get("count") or 0)
                tot["sum"] += float(s.get("sum") or 0.0)
                for k, v in sb.items():
                    tot["buckets"][k] += int(v)
            else:
                if key not in totals:
                    totals[key] = {
                        "name": s.get("name"), "type": s.get("type"),
                        "labels": labels, "value": 0.0,
                    }
                    tot = totals[key]
                if tot is not None:
                    tot["value"] += float(s.get("value") or 0.0)
    out = children + [t for t in totals.values() if t is not None]
    out.sort(key=lambda s: (s.get("name") or "",
                            tuple(sorted((s.get("labels") or {}).items()))))
    return out


def prometheus_text_for(samples: Iterable[dict],
                        helps: Optional[dict] = None) -> str:
    """Prometheus text exposition of a sample list (the federated
    ``GET /metrics`` body — :func:`export.prometheus_text` is the same
    renderer, but bound to a live :class:`Registry`)."""
    helps = helps or {}
    by_name: dict[str, list[dict]] = {}
    kinds: dict[str, str] = {}
    for s in samples:
        by_name.setdefault(s["name"], []).append(s)
        kinds.setdefault(s["name"], s.get("type") or "untyped")
    lines: list[str] = []
    for name in sorted(by_name):
        kind = kinds[name]
        if helps.get(name):
            lines.append(f"# HELP {name} {helps[name]}")
        lines.append(f"# TYPE {name} {kind}")
        for s in by_name[name]:
            labels = s.get("labels") or {}
            if kind == "histogram":
                cum = 0
                bounds, counts = _bounds_counts(s.get("buckets") or {})
                for le, c in zip([*map(str, bounds), "+Inf"], counts):
                    cum += c
                    lines.append(
                        f"{name}_bucket"
                        f"{_export._label_str(labels, {'le': le})} {cum}")
                lines.append(f"{name}_sum{_export._label_str(labels)} "
                             f"{_export._fmt(s.get('sum') or 0.0)}")
                lines.append(f"{name}_count{_export._label_str(labels)} "
                             f"{int(s.get('count') or 0)}")
            else:
                lines.append(f"{name}{_export._label_str(labels)} "
                             f"{_export._fmt(s.get('value') or 0.0)}")
    return "\n".join(lines) + ("\n" if lines else "")


def backlog_occupancy(events: Iterable[dict],
                      *, until: Optional[float] = None) -> Optional[dict]:
    """Backend-busy share from the ``online_backlog`` gauge timeline:
    the fraction of the observed window during which the scheduler held
    undecided segments. The fallback saturation proxy when a backend
    ran no device kernels (host engine) and so emitted no stamped chunk
    events for the PR-7 busy-span reconstruction."""
    pts = sorted(
        (float(e["t"]), float(e.get("backlog") or 0))
        for e in events
        if e.get("name") == "online_backlog" and e.get("t") is not None)
    if not pts:
        return None
    w0 = pts[0][0]
    w1 = max(until if until is not None else pts[-1][0], pts[-1][0])
    if w1 <= w0:
        return None
    intervals: list[list[float]] = []
    for i, (t, v) in enumerate(pts):
        if v <= 0:
            continue
        t1 = pts[i + 1][0] if i + 1 < len(pts) else w1
        if intervals and t <= intervals[-1][1]:
            intervals[-1][1] = max(intervals[-1][1], t1)
        else:
            intervals.append([t, t1])
    busy = sum(b - a for a, b in intervals)
    makespan = w1 - w0
    return {
        "utilization_pct": round(busy / makespan * 100.0, 2),
        "window": {"t0": round(w0, 6), "t1": round(w1, 6),
                   "makespan_s": round(makespan, 6)},
        "intervals": [[round(a - w0, 6), round(b - w0, 6)]
                      for a, b in intervals],
    }


class _ScrapedRegistry:
    """Read-only shim over one scraped event ring, shaped just enough
    for ``utilization.reconstruct`` (which only reads ``events()`` and
    tolerates a registry that refuses writes)."""

    def __init__(self, events: list[dict]):
        self._events = list(events)

    def events(self, name: Optional[str] = None) -> list[dict]:
        if name is None:
            return list(self._events)
        return [e for e in self._events if e.get("name") == name]

    def gauge(self, *_a, **_k):  # pragma: no cover - exercised via reconstruct
        raise RuntimeError("scraped snapshot is read-only")


class FleetFederation:
    """The router-side scrape store: one replace-on-scrape snapshot per
    backend, merged on demand (see the module docstring for the
    semantics this class pins)."""

    def __init__(self, metrics=None, *,
                 stale_after_s: float = SCRAPE_STALE_AFTER_S):
        self.metrics = metrics
        self.stale_after_s = float(stale_after_s)
        self._lock = threading.Lock()
        self._snaps: dict[str, dict] = {}
        self._failures: dict[str, int] = {}
        if metrics is not None:
            self._g_age = metrics.gauge(
                "fleet_scrape_age_seconds",
                "Seconds since each backend's last successful metrics "
                "scrape (a dead/respawning backend's age grows while "
                "its last snapshot is kept — stale, never silently "
                "zero)", labelnames=("backend",))
            self._c_scrapes = metrics.counter(
                "fleet_scrapes_total",
                "Successful federation scrapes per backend",
                labelnames=("backend",))
            self._c_fail = metrics.counter(
                "fleet_scrape_failures_total",
                "Failed federation scrapes per backend (the snapshot "
                "is kept and ages)", labelnames=("backend",))
            self._g_stale = metrics.gauge(
                "fleet_backends_stale",
                "Backends whose scrape age passed the staleness "
                "threshold (or that were expected but never scraped)")

    # -- the scrape side -----------------------------------------------------

    def record_scrape(self, backend: str, payload: dict,
                      *, now: Optional[float] = None) -> None:
        """REPLACE ``backend``'s snapshot (generation-replace: a
        respawned backend's fresh counters supersede the dead
        generation's — no cross-generation double count)."""
        now = _time.time() if now is None else float(now)
        snap = {
            "samples": list(payload.get("samples") or ()),
            "helps": dict(payload.get("helps") or {}),
            "events": list(payload.get("events") or ()),
            "service": payload.get("service"),
            "at": now,
        }
        with self._lock:
            prev = self._snaps.get(backend)
            snap["scrapes"] = (prev["scrapes"] + 1) if prev else 1
            self._snaps[backend] = snap
        if self.metrics is not None:
            self._c_scrapes.labels(backend=backend).inc()
            self._g_age.labels(backend=backend).set(0.0)

    def record_failure(self, backend: str) -> None:
        with self._lock:
            self._failures[backend] = self._failures.get(backend, 0) + 1
        if self.metrics is not None:
            self._c_fail.labels(backend=backend).inc()

    def forget(self, backend: str) -> None:
        with self._lock:
            self._snaps.pop(backend, None)
            self._failures.pop(backend, None)

    # -- staleness -----------------------------------------------------------

    def ages(self, *, now: Optional[float] = None) -> dict[str, float]:
        """Scrape age per backend (also refreshes the
        ``fleet_scrape_age_seconds`` gauges)."""
        now = _time.time() if now is None else float(now)
        with self._lock:
            ages = {b: max(now - s["at"], 0.0)
                    for b, s in self._snaps.items()}
        if self.metrics is not None:
            for b, a in ages.items():
                self._g_age.labels(backend=b).set(round(a, 3))
        return ages

    def stale_backends(self, expected: Optional[Iterable[str]] = None,
                       *, now: Optional[float] = None) -> list[str]:
        """Backends whose snapshot aged past the threshold, plus any
        ``expected`` name never scraped at all.

        When ``expected`` is given it is the CURRENT config: a
        snapshot held for a backend no longer listed is decommissioned
        — once its age passes the threshold it is expired (forgotten)
        rather than reported, so removing a backend from config can't
        pin the staleness signal (and its alert) forever. Until expiry
        the snapshot still merges (a just-removed backend's counters
        drain out after ``stale_after_s``, not instantly)."""
        expected_set = set(expected) if expected is not None else None
        ages = self.ages(now=now)
        if expected_set is not None:
            for b, a in ages.items():
                if b not in expected_set and a > self.stale_after_s:
                    self.forget(b)
                    if self.metrics is not None:
                        self._g_age.labels(backend=b).set(0.0)
            ages = {b: a for b, a in ages.items() if b in expected_set}
        stale = {b for b, a in ages.items() if a > self.stale_after_s}
        stale.update(b for b in (expected_set or ()) if b not in ages)
        out = sorted(stale)
        if self.metrics is not None:
            self._g_stale.set(len(out))
        return out

    # -- the merged view -----------------------------------------------------

    def backends(self) -> list[str]:
        with self._lock:
            return sorted(self._snaps)

    def merged(self) -> list[dict]:
        with self._lock:
            per = {b: s["samples"] for b, s in self._snaps.items()}
        return merge_samples(per)

    def helps(self) -> dict[str, str]:
        out: dict[str, str] = {}
        with self._lock:
            for b in sorted(self._snaps):
                for n, h in self._snaps[b]["helps"].items():
                    out.setdefault(n, h)
        return out

    def prometheus_text(self) -> str:
        return prometheus_text_for(self.merged(), self.helps())

    def fleet_histogram(self, name: str,
                        labels: Optional[dict] = None) -> Optional[dict]:
        """The cross-backend TOTAL sample of one histogram family (the
        merged distribution; ``labels`` selects a labeled child's
        total, default the aggregate/unlabeled one)."""
        want = dict(labels or {})
        for s in self.merged():
            if (s.get("name") == name and s.get("type") == "histogram"
                    and s.get("labels") == want):
                return s
        return None

    def histogram_stats(self, name: str, labels: Optional[dict] = None,
                        quantiles: Sequence[float] = (0.5, 0.9, 0.99)
                        ) -> Optional[dict]:
        s = self.fleet_histogram(name, labels)
        return None if s is None else stats_from_sample(s, quantiles)

    # -- per-backend introspection (the /fleet page + bench block) -----------

    def meta(self, *, now: Optional[float] = None,
             expected: Optional[Iterable[str]] = None) -> dict[str, dict]:
        """Per-backend scrape bookkeeping: last-scrape stamp/age,
        scrape + failure counts, staleness. With ``expected`` (the
        current config), a held snapshot for an unlisted backend is
        flagged ``decommissioned`` — it merges until
        :meth:`stale_backends` expires it, but no longer counts
        against fleet health."""
        now = _time.time() if now is None else float(now)
        expected_set = set(expected) if expected is not None else None
        with self._lock:
            snaps = dict(self._snaps)
            failures = dict(self._failures)
        out: dict[str, dict] = {}
        for b in sorted(set(snaps) | set(failures)):
            s = snaps.get(b)
            row: dict = {
                "scrapes": s["scrapes"] if s else 0,
                "scrape_failures": failures.get(b, 0),
            }
            if expected_set is not None and b not in expected_set:
                row["decommissioned"] = True
            if s is not None:
                age = max(now - s["at"], 0.0)
                row["scraped_at"] = round(s["at"], 3)
                row["scrape_age_s"] = round(age, 3)
                row["stale"] = age > self.stale_after_s
                if s.get("service"):
                    row["service"] = s["service"]
            else:
                row["stale"] = True
            out[b] = row
        return out

    def events(self, backend: str) -> list[dict]:
        with self._lock:
            s = self._snaps.get(backend)
            return list(s["events"]) if s else []

    def utilization(self, backend: str) -> Optional[dict]:
        """This backend's saturation view from its scraped event ring:
        the PR-7 chunk-based busy-span reconstruction when the backend
        ran device kernels, else the ``online_backlog`` occupancy
        proxy. None when the snapshot carries neither."""
        evs = self.events(backend)
        if not evs:
            return None
        from . import utilization as _util

        util = _util.reconstruct(_ScrapedRegistry(evs))
        if util is not None:
            summ = util.get("summary") or {}
            return {
                "source": "chunks",
                "utilization_pct": summ.get("mean_utilization_pct"),
                "window": util.get("window"),
                "summary": summ,
                "devices": util.get("devices"),
            }
        occ = backlog_occupancy(evs)
        if occ is not None:
            return {"source": "backlog", **occ}
        return None


class SloMonitor:
    """Fleet SLO burn rates over the federated totals (see the module
    docstring). ``observe`` is called once per scrape sweep with the
    merged sample list; it keeps a bounded history of cumulative
    totals and computes windowed deltas — counter resets from a
    backend-generation replace clamp to zero rather than going
    negative."""

    def __init__(self, metrics=None, *,
                 availability_target: float = SLO_AVAILABILITY_TARGET,
                 latency_target_s: float = SLO_LATENCY_TARGET_S,
                 latency_ratio: float = SLO_LATENCY_RATIO,
                 fast_window_s: float = SLO_FAST_WINDOW_S,
                 slow_window_s: float = SLO_SLOW_WINDOW_S,
                 latency_family: str = "decision_latency_seconds",
                 rejects_family: str = "service_rejects_total"):
        if not 0.0 < availability_target < 1.0:
            raise ValueError("availability_target must be in (0, 1)")
        if not 0.0 < latency_ratio < 1.0:
            raise ValueError("latency_ratio must be in (0, 1)")
        self.availability_target = float(availability_target)
        self.latency_target_s = float(latency_target_s)
        self.latency_ratio = float(latency_ratio)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.latency_family = latency_family
        self.rejects_family = rejects_family
        self._points: deque = deque()
        self._lock = threading.Lock()
        self.metrics = metrics
        if metrics is not None:
            self._g_avail = metrics.gauge(
                "slo_availability_burn_rate",
                "Fleet availability error-budget burn rate per window "
                "(1.0 = budget burning at exactly the sustainable "
                "rate)", labelnames=("window",))
            self._g_lat = metrics.gauge(
                "slo_latency_burn_rate",
                "Fleet decision-latency error-budget burn rate per "
                "window (share of ops slower than the target vs. the "
                "allowed share)", labelnames=("window",))

    def _totals(self, merged: list[dict]) -> tuple[int, int, float]:
        """(decided ops, decided slower than target, rejected ops)
        from the fleet totals — samples WITHOUT a ``backend`` label,
        so per-backend children are never double-counted."""
        decided = slow = 0
        rejects = 0.0
        for s in merged:
            labels = s.get("labels") or {}
            if "backend" in labels:
                continue
            if (s.get("name") == self.latency_family
                    and s.get("type") == "histogram" and not labels):
                decided = int(s.get("count") or 0)
                within = sum(
                    int(v) for k, v in (s.get("buckets") or {}).items()
                    if k != "+Inf"
                    and float(k) <= self.latency_target_s)
                slow = max(decided - within, 0)
            elif (s.get("name") == self.rejects_family
                    and s.get("type") == "counter"):
                rejects += float(s.get("value") or 0.0)
        return decided, slow, rejects

    def observe(self, merged: list[dict],
                *, now: Optional[float] = None) -> dict:
        now = _time.time() if now is None else float(now)
        decided, slow, rejects = self._totals(merged)
        with self._lock:
            self._points.append((now, decided, slow, rejects))
            while (self._points
                   and self._points[0][0] < now - self.slow_window_s):
                self._points.popleft()
            points = list(self._points)
        windows: dict[str, dict] = {}
        for wname, ws in (("fast", self.fast_window_s),
                          ("slow", self.slow_window_s)):
            base = None
            for p in points:
                if p[0] >= now - ws:
                    base = p
                    break
            if base is None:
                base = points[0]
            d_dec = max(decided - base[1], 0)
            d_slow = max(slow - base[2], 0)
            d_rej = max(rejects - base[3], 0.0)
            attempts = d_dec + d_rej
            avail_bad = (d_rej / attempts) if attempts > 0 else 0.0
            avail_burn = avail_bad / (1.0 - self.availability_target)
            lat_bad = (d_slow / d_dec) if d_dec > 0 else 0.0
            lat_burn = lat_bad / (1.0 - self.latency_ratio)
            if self.metrics is not None:
                self._g_avail.labels(window=wname).set(
                    round(avail_burn, 4))
                self._g_lat.labels(window=wname).set(round(lat_burn, 4))
            windows[wname] = {
                "window_s": ws,
                "availability_burn_rate": round(avail_burn, 4),
                "latency_burn_rate": round(lat_burn, 4),
                "attempts": attempts,
                "rejected": d_rej,
                "decided": d_dec,
                "slow": d_slow,
            }
        return {
            "availability_target": self.availability_target,
            "latency_target_s": self.latency_target_s,
            "latency_ratio": self.latency_ratio,
            "windows": windows,
        }
