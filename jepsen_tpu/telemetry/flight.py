"""Run flight recorder: a bounded ring of recent telemetry events plus
phase deadlines that flushes a post-mortem ``flightrecord.json`` when
something goes wrong — an exception, a phase overshooting its deadline,
or the whole run breaching its wall budget.

Round 5's bench blew its own 740 s budget (``bench_wall_s`` 855.7) and
the only trail was the final number: nothing recorded *which* leg ate
the overrun. The recorder closes that gap the way an aircraft FDR does —
it is always cheap to feed (a deque append per note, a couple of
timestamps per phase) and only writes anything when a crash/overrun
makes the tail of the record interesting. The JSON names the offending
phase explicitly: the first phase that overshot its own deadline, else
the phase during which the budget ran out, else the still-open phase at
flush time, else the longest phase.

Disabled is free: :func:`phase` with a ``None`` recorder returns one
shared no-op context manager (module singleton — zero per-call
allocations), so instrumented code needs no guards of its own.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time as _time
from collections import deque
from typing import Any, Optional

# Shared no-op context manager for the disabled path: nullcontext is
# stateless, so ONE instance serves every `with` — no per-call object.
_NOOP_CM = contextlib.nullcontext()


def phase(recorder: Optional["FlightRecorder"], name: str,
          deadline_s: Optional[float] = None):
    """``with flight.phase(rec, "analyze"):`` — no-op when rec is None
    (the zero-overhead disabled path; always the same object)."""
    if recorder is None:
        return _NOOP_CM
    return recorder.phase(name, deadline_s=deadline_s)


class FlightRecorder:
    """Bounded event ring + phase ledger with deadlines and a run budget.

    ``budget_s``: overall wall budget; :meth:`breached` and the
    ``budget_breach`` flush reason key off it. ``max_events`` bounds the
    note ring (oldest notes fall off). All methods are thread-safe —
    bench legs and checker threads feed one recorder.
    """

    def __init__(self, budget_s: Optional[float] = None,
                 max_events: int = 512, max_phases: int = 4096):
        self.budget_s = budget_s
        self._t0 = _time.monotonic()
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max_events)
        # Bounded like the note ring: the online scheduler enters three
        # ledger phases per decide round, so a long monitored stream
        # would otherwise grow the ledger (and every flightrecord.json
        # flush) without limit. Post-mortems want the RECENT window
        # anyway; a phase dict evicted while still open is mutated
        # harmlessly by its context manager.
        self._phases: deque = deque(maxlen=max_phases)
        self._open: list[dict] = []  # stack of phases in flight
        self._seq: Optional[dict] = None  # current begin()-phase

    # -- feeding ----------------------------------------------------------

    def elapsed(self) -> float:
        return _time.monotonic() - self._t0

    def note(self, name: str, **fields: Any) -> None:
        """Append one event to the ring (bounded; oldest drop off)."""
        with self._lock:
            self._events.append(
                {"t": round(self.elapsed(), 3), "name": name, **fields})

    @contextlib.contextmanager
    def phase(self, name: str, deadline_s: Optional[float] = None):
        ph = {"phase": name, "start_s": round(self.elapsed(), 3)}
        if deadline_s is not None:
            ph["deadline_s"] = round(float(deadline_s), 3)
        with self._lock:
            self._phases.append(ph)
            self._open.append(ph)
        try:
            yield ph
        except BaseException as e:
            ph["error"] = f"{type(e).__name__}: {e}"
            raise
        finally:
            end = self.elapsed()
            with self._lock:
                ph["end_s"] = round(end, 3)
                ph["wall_s"] = round(end - ph["start_s"], 3)
                if deadline_s is not None and ph["wall_s"] > deadline_s:
                    ph["overshoot_s"] = round(ph["wall_s"] - deadline_s, 3)
                if ph in self._open:
                    self._open.remove(ph)

    # Sequential phase API for linear flows (bench.py's legs): begin()
    # closes the previous begin()-phase and opens the next, so a
    # straight-line script needs one call per leg instead of a nested
    # context manager per block.

    def begin(self, name: str, deadline_s: Optional[float] = None) -> None:
        now = self.elapsed()
        ph = {"phase": name, "start_s": round(now, 3)}
        if deadline_s is not None:
            ph["deadline_s"] = round(float(deadline_s), 3)
        with self._lock:
            self._end_locked(now)
            self._phases.append(ph)
            self._open.append(ph)
            self._seq = ph

    def end(self) -> None:
        with self._lock:
            self._end_locked(self.elapsed())

    def _end_locked(self, end: float) -> None:
        """Close the current begin()-phase; caller holds the lock."""
        ph = self._seq
        if ph is None:
            return
        ph["end_s"] = round(end, 3)
        ph["wall_s"] = round(end - ph["start_s"], 3)
        if ph.get("deadline_s") is not None \
                and ph["wall_s"] > ph["deadline_s"]:
            ph["overshoot_s"] = round(ph["wall_s"] - ph["deadline_s"], 3)
        if ph in self._open:
            self._open.remove(ph)
        self._seq = None

    # -- diagnosis --------------------------------------------------------

    def breached(self) -> bool:
        return self.budget_s is not None and self.elapsed() > self.budget_s

    def offending_phase(self) -> Optional[str]:
        """The phase to blame, in order of specificity: first deadline
        overshoot; else the phase running when the budget ran out; else
        the phase still open now; else the longest completed phase."""
        with self._lock:
            phases = list(self._phases)
            open_ = list(self._open)
        for ph in phases:
            if "overshoot_s" in ph or "error" in ph:
                return ph["phase"]
        if self.budget_s is not None:
            for ph in phases:
                end = ph.get("end_s", self.elapsed())
                if ph["start_s"] <= self.budget_s < end:
                    return ph["phase"]
        if open_:
            return open_[-1]["phase"]
        done = [p for p in phases if "wall_s" in p]
        if done:
            return max(done, key=lambda p: p["wall_s"])["phase"]
        return None

    # -- flushing ---------------------------------------------------------

    def snapshot(self, reason: Optional[str] = None,
                 registry=None, extra: Optional[dict] = None) -> dict:
        """The full record as a dict (what :meth:`flush` writes).
        ``registry``: a telemetry Registry whose newest events are
        appended as ``registry_tail`` (the last 100 — the minutes before
        the crash, FDR-style)."""
        if reason is None:
            reason = "budget_breach" if self.breached() else "manual"
        with self._lock:
            phases = [dict(p) for p in self._phases]
            events = list(self._events)
        out = {
            "reason": reason,
            "elapsed_s": round(self.elapsed(), 3),
            "budget_s": self.budget_s,
            "budget_breached": self.breached(),
            "offending_phase": self.offending_phase(),
            "phases": phases,
            "events": events,
        }
        if registry is not None:
            try:
                out["registry_tail"] = registry.events()[-100:]
            except Exception:  # diagnostics never mask the flush
                pass
        if extra:
            out.update(extra)
        return out

    def flush(self, path, reason: Optional[str] = None, registry=None,
              extra: Optional[dict] = None) -> str:
        """Atomically write the record to ``path`` (tmp + rename) and
        return the path. Never raises — a broken post-mortem writer must
        not add its own crash to the incident."""
        try:
            snap = self.snapshot(reason=reason, registry=registry,
                                 extra=extra)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=1, sort_keys=True, default=str)
            os.replace(tmp, path)
        except Exception:
            pass
        return str(path)


def store_flight_record(test: dict, recorder: FlightRecorder,
                        reason: Optional[str] = None,
                        registry=None) -> Optional[str]:
    """Flush ``flightrecord.json`` into the test's store directory
    (next to metrics.jsonl); None when the test has no store."""
    if not (test.get("name") and test.get("start-time")) or test.get(
            "no-store?"):
        return None
    from .. import store

    p = store.path_mk(test, "flightrecord.json")
    return recorder.flush(p, reason=reason, registry=registry)
