"""Thread-safe metrics registry: counters, gauges and histograms with
labels, plus a bounded append-only *event* stream for per-level series
(BFS frontier sizes and the like) that don't fit the scalar model.

The shape follows the Prometheus client-library data model (the same one
the reference's dgraph suite feeds through OpenCensus) without the
dependency: a :class:`Registry` owns named metrics, a metric owns one
child per label-value tuple, children hold the numbers. Everything is
lock-protected and cheap enough to sit on the interpreter's completion
path; the WGL kernel itself never sees any of this — device-side stats
ride the kernel's returned stats rows (``ops/wgl.py``) and are folded in
host-side, so telemetry off ⇒ the jit'd program is bit-identical.
"""

from __future__ import annotations

import bisect
import threading
import time as _time
from collections import deque
from typing import Any, Iterable, Optional, Sequence

# Latency-ish default buckets (seconds), 0.5 ms .. 10 s.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Decision-latency buckets (seconds) for the online monitor's
# invoke→watermark-covered lag: the DEFAULT_BUCKETS top out at 10 s,
# but a backlogged scheduler (or a device compile mid-stream) can hold
# an op undecided for minutes — with everything past 10 s lumped into
# +Inf, p99 estimation saturates at the last finite bound and a 30 s
# stall reads exactly like a 30 min one. Extended tail fixes that.
DECISION_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0,
)


def bucket_quantile(bounds: Sequence[float], counts: Sequence[int],
                    q: float) -> Optional[float]:
    """Prometheus-style ``histogram_quantile`` over PER-BUCKET (non-
    cumulative) counts: find the bucket the q-rank falls in and
    interpolate linearly inside it (lower edge = previous bound, 0 for
    the first). ``counts`` may carry one extra trailing +Inf bucket;
    ranks landing there clamp to the highest finite bound (the honest
    answer a bucketed histogram can give). None when empty."""
    counts = list(counts)
    total = sum(counts)
    if total <= 0:
        return None
    rank = max(q, 0.0) * total
    cum = 0
    lo = 0.0
    for i, b in enumerate(bounds):
        c = counts[i] if i < len(counts) else 0
        cum += c
        if cum >= rank:
            if c <= 0:
                return float(b)
            frac = (rank - (cum - c)) / c
            return lo + (float(b) - lo) * frac
        lo = float(b)
    return float(bounds[-1])  # +Inf bucket: clamp to last finite bound


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def max(self, value: float) -> None:
        """Ratchet: keep the largest value seen (frontier peaks)."""
        with self._lock:
            if value > self.value:
                self.value = float(value)


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, lock: threading.Lock, buckets: Sequence[float]):
        self._lock = lock
        self.buckets = tuple(buckets)  # upper bounds, ascending, no +Inf
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            counts = list(self.counts)
        return bucket_quantile(self.buckets, counts, q)


class Metric:
    """One named metric; holds a child per label-value tuple.

    ``aggregate=True`` on a *labeled* metric additionally keeps one
    unlabeled child (stored under the empty label tuple, so it exports
    as the plain ``name`` series next to the ``name{label=...}``
    family) that the metric-level ``inc``/``set``/``observe`` methods
    operate on — the "keep the unlabeled total for existing
    dashboards" pattern the per-tenant service metrics use
    (``online_scheduler_backlog`` et al.)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (), aggregate: bool = False):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.aggregate = bool(aggregate)
        self._lock = threading.Lock()
        self._children: dict[tuple, Any] = {}
        if not self.labelnames:
            self._default = self.labels()
        elif self.aggregate:
            # The empty key sorts (and exports) first; zip(labelnames,
            # ()) renders it with labels {} — i.e. the unlabeled total.
            self._default = self._children.setdefault(
                (), self._make_child())

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **labelvalues: Any):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make_child())
        return child

    def samples(self) -> list[dict]:
        with self._lock:
            items = sorted(self._children.items())
        out = []
        for key, child in items:
            s: dict = {
                "name": self.name,
                "type": self.kind,
                "labels": dict(zip(self.labelnames, key)),
            }
            if isinstance(child, _HistogramChild):
                with child._lock:
                    s["count"] = child.count
                    s["sum"] = child.sum
                    s["buckets"] = dict(
                        zip([*map(str, child.buckets), "+Inf"],
                            list(child.counts)))
            else:
                s["value"] = child.value
            out.append(s)
        return out


class Counter(Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    @property
    def value(self) -> float:
        return self._default.value


class Gauge(Metric):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(self._lock)

    def set(self, value: float) -> None:
        self._default.set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default.dec(amount)

    def max(self, value: float) -> None:
        self._default.max(value)

    @property
    def value(self) -> float:
        return self._default.value


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 aggregate: bool = False):
        b = tuple(sorted(float(x) for x in buckets if x != float("inf")))
        if not b:
            raise ValueError("histogram needs at least one finite bucket")
        self.buckets = b
        super().__init__(name, help, labelnames, aggregate=aggregate)

    def _make_child(self):
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, value: float) -> None:
        self._default.observe(value)

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile of the (unlabeled) default child."""
        return self._default.quantile(q)

    def stats(self, quantiles: Sequence[float] = (0.5, 0.9, 0.99),
              labels: Optional[dict] = None) -> dict:
        """Count/sum plus interpolated quantiles of the default child —
        the ``{"count", "sum_s", "p50_s", ...}`` summary block
        online.json and the bench legs embed. ``labels`` selects a
        specific labeled child instead (the service's per-tenant
        decision-latency summaries)."""
        child = self._default if labels is None else self.labels(**labels)
        with child._lock:
            counts = list(child.counts)
            out: dict = {"count": child.count,
                         "sum_s": round(child.sum, 6)}
        for q in quantiles:
            v = bucket_quantile(self.buckets, counts, q)
            out[f"p{int(round(q * 100))}_s"] = (
                round(v, 6) if v is not None else None)
        return out


class Registry:
    """Named-metric registry + bounded event stream.

    Register-or-get semantics: asking twice for the same name returns the
    same metric; asking with a different type/labelset raises (a silent
    second registration would split the series)."""

    def __init__(self, max_events: int = 100_000):
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}
        self._events: deque = deque(maxlen=max_events)
        # name -> newest event with that name (may outlive its ring
        # slot): last_event() must stay O(1) — the web /live poll reads
        # it per refresh while holding the same lock every hot-path
        # metric call takes, so a 100k-deque reverse scan per poll
        # would stall the instrumented paths.
        self._last_by_name: dict[str, dict] = {}
        self.created_at = _time.time()

    def _get_or_make(self, cls, name, help, labelnames, aggregate=False,
                     **extra) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames,
                                              aggregate=aggregate, **extra)
                return m
        if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name} already registered as {m.kind} with "
                f"labels {m.labelnames}")
        if aggregate and not m.aggregate:
            # A labeled metric registered WITHOUT the unlabeled total
            # cannot grow one later — the already-exported series would
            # silently change shape mid-run.
            raise ValueError(
                f"metric {name} already registered without an "
                "aggregate child")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = (),
                aggregate: bool = False) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames,
                                 aggregate=aggregate)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = (),
              aggregate: bool = False) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames,
                                 aggregate=aggregate)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  aggregate: bool = False) -> Histogram:
        m = self._get_or_make(Histogram, name, help, labelnames,
                              aggregate=aggregate, buckets=buckets)
        want = tuple(sorted(float(x) for x in buckets
                            if x != float("inf")))
        if m.buckets != want:
            raise ValueError(
                f"metric {name} already registered with buckets "
                f"{m.buckets}")
        return m

    def event(self, name: str, **fields: Any) -> None:
        """Append one point to the event stream (per-BFS-level frontier
        rows etc.). Bounded: oldest points fall off past ``max_events``.
        Locked against :meth:`events` — iterating a deque while another
        thread appends raises."""
        with self._lock:
            ev = {"name": name, **fields}
            self._events.append(ev)
            self._last_by_name[name] = ev

    def events(self, name: Optional[str] = None) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        if name is None:
            return evs
        return [e for e in evs if e.get("name") == name]

    def last_event(self, name: str) -> Optional[dict]:
        """Newest event with this name, or None — O(1) via the
        per-name index (a live dashboard polls this every second while
        the hot paths contend for the same lock; the indexed entry may
        outlive its bounded ring slot, which is fine for "newest")."""
        with self._lock:
            e = self._last_by_name.get(name)
            return dict(e) if e is not None else None

    def collect(self) -> list[dict]:
        """Samples of every metric, sorted by (name, labels)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: list[dict] = []
        for _name, m in metrics:
            out.extend(m.samples())
        return out

    def summary(self) -> dict:
        """Flat ``name{labels}`` -> value dict (histograms fold to
        count/sum) — what bench.py embeds in its JSON line."""
        out: dict = {}
        for s in self.collect():
            labels = s.get("labels") or {}
            key = s["name"]
            if labels:
                inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                key = f"{key}{{{inner}}}"
            if s["type"] == "histogram":
                out[key] = {"count": s["count"], "sum": round(s["sum"], 6)}
            else:
                v = s["value"]
                out[key] = int(v) if float(v).is_integer() else round(v, 6)
        return out


def timed_phase(registry: Optional[Registry], phase: str, recorder=None):
    """Context manager recording wall seconds of a run phase into
    ``run_phase_seconds{phase=...}`` (no-op when registry is None).
    ``recorder``: an optional ``flight.FlightRecorder`` — the same phase
    is entered in its ledger, so a crashed run's flightrecord.json names
    the lifecycle phase that died."""
    from contextlib import contextmanager

    from . import flight as _flight

    @contextmanager
    def _cm():
        t0 = _time.perf_counter()
        try:
            with _flight.phase(recorder, phase):
                yield
        finally:
            if registry is not None:
                registry.gauge(
                    "run_phase_seconds",
                    "Wall seconds per test-lifecycle phase",
                    labelnames=("phase",),
                ).labels(phase=phase).set(_time.perf_counter() - t0)

    return _cm()
