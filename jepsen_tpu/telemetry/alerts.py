"""Alerting & watchdog plane: a live rule engine over the samples the
observability stack already exports, with a durable alert lifecycle and
a change-point regression sentinel.

Everything the stack can *measure* — federated fleet samples, SLO burn
gauges, verdict provenance, the cross-run ledger — was consumed
passively (the advisor is a post-hoc CLI, ``ledger --check`` runs
between bench rounds). This module is the online consumer: a pure
rule-evaluation engine (:class:`AlertRule` = name + severity +
closed-form predicate over a context snapshot + ``for_s`` hold) driving
a typed lifecycle state machine

    inactive -> pending -> firing -> resolved -> (inactive)

with a monotone per-alert generation counter (a re-fire after resolve
gets a new generation; history keeps every transition), persisted as an
append-only ``alerts.jsonl`` under the same
:class:`service.journal.ConsistentLines` torn-final-line discipline the
tenant journal and ``router_state.jsonl`` share — a kill-9'd router
restarted over the same file replays to the same firing set.

Evaluation is driven by the hosts' EXISTING cadences (the service's
pump sweep, the router's probe tick — no new threads), the rules are
closed-form over a context dict so tests pin them synthetically, and
the advisor imports its overlapping predicates FROM here
(:func:`slo_hot_windows`, :func:`stale_backend_list`,
:func:`respawn_capacity_deficit`, :func:`tail_is_pathological`,
:func:`journal_gap_count`) so there is exactly one definition of
"when" for each shared condition.

The context dict (any key may be absent — every predicate degrades to
"not firing" on missing input, never raises):

- ``samples``  — a ``Registry.collect()`` / ``fleet.merged()`` list;
- ``slo``      — a ``fleet.SloMonitor.observe()`` document;
- ``fleet``    — the router's fleet-stats block (``stale_backends``,
  ``configured_backends`` / ``live_backends``, respawn state);
- ``health``   — a service ``health_snapshot()`` document;
- ``sentinel`` — active :class:`RegressionSentinel` findings;
- ``now``      — the evaluation wall-clock stamp.

Off is the default and costs nothing: hosts only import this module
when an alert config is present (pinned by a poisoned-import test, the
same convention the telemetry/utilization layers follow).
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import json
import logging
import math
import os
import sys
import time as _time
from typing import Any, Callable, Optional

LOG = logging.getLogger("jepsen.alerts")

SEVERITIES = ("high", "medium", "info")
STATES = ("inactive", "pending", "firing", "resolved")

# ---------------------------------------------------------------------------
# Thresholds — the ONE source both the alert catalogue and the advisor
# read (jepsen_tpu/advisor.py re-exports these under its historic
# names; tests/test_alerts.py pins the identity).

# SLO burn-rate alert thresholds (the classic multiwindow pair): a
# fast-window burn this hot exhausts the error budget in hours; a
# slow-window burn this hot is a sustained leak. Gauges come from
# telemetry.fleet.SloMonitor via the router's federated scrape.
SLO_FAST_BURN_THRESHOLD = 14.0
SLO_SLOW_BURN_THRESHOLD = 6.0
# p99/p50 decision-latency ratio past which the tail is pathological.
TAIL_RATIO_THRESHOLD = 20.0
# journal_lag_ops past which a crash would cost a resubmission storm.
JOURNAL_LAG_ALERT_OPS = 10_000
# online_watermark_stall_seconds past which coverage is wedged (the
# gauge itself already holds 0 for stall_after_s before climbing).
WATERMARK_STALL_ALERT_S = 10.0
# Hosts evaluate at most this often on their own cadence.
ALERT_EVAL_INTERVAL_S = 1.0
# A sentinel finding keeps its perf_regression alert firing this long.
REGRESSION_ACTIVE_S = 600.0

# ---------------------------------------------------------------------------
# Shared closed-form predicates (advisor.py imports these).


def slo_hot_windows(slo: Optional[dict]) -> dict:
    """``{"<window>_<kind>": {burn_rate, threshold}}`` for every SLO
    window burning past its multiwindow threshold — the advisor's
    ``slo_burn`` rule and the ``slo_burn`` alert share this exactly."""
    windows = (slo or {}).get("windows") or {}
    hot: dict = {}
    for wname, thresh in (("fast", SLO_FAST_BURN_THRESHOLD),
                          ("slow", SLO_SLOW_BURN_THRESHOLD)):
        w = windows.get(wname) or {}
        for kind in ("availability", "latency"):
            burn = w.get(f"{kind}_burn_rate")
            if isinstance(burn, (int, float)) and burn > thresh:
                hot[f"{wname}_{kind}"] = {"burn_rate": burn,
                                          "threshold": thresh}
    return hot


def stale_backend_list(fleet: Optional[dict]) -> list:
    """Backends whose federation scrape is past the staleness horizon,
    from a router fleet-stats block."""
    if not isinstance(fleet, dict):
        return []
    return sorted(fleet.get("stale_backends") or [])


def respawn_capacity_deficit(fleet: Optional[dict]) -> Optional[dict]:
    """Evidence dict when the fleet runs below its configured backend
    count AND the self-healing layer is out of play (respawn disabled,
    or the flap circuit gave up) — None while the supervisor is still
    on it, exactly the advisor's ``respawn_backend`` gate."""
    fleet = fleet if isinstance(fleet, dict) else {}
    conf = fleet.get("configured_backends")
    live = fleet.get("live_backends")
    if not isinstance(conf, int) or not isinstance(live, int) \
            or live >= conf:
        return None
    disabled = bool(fleet.get("respawn_disabled"))
    gave_up = list(fleet.get("respawn_gave_up") or [])
    if not disabled and not gave_up:
        return None
    return {"configured_backends": conf, "live_backends": live,
            "respawn_disabled": disabled, "respawn_gave_up": gave_up}


def tail_is_pathological(p50: Any, p99: Any) -> bool:
    """p99/p50 past TAIL_RATIO_THRESHOLD — the advisor's
    ``latency_tail`` rule and the ``latency_tail`` alert share this."""
    return (isinstance(p50, (int, float)) and isinstance(
        p99, (int, float)) and p50 > 0
        and p99 / p50 > TAIL_RATIO_THRESHOLD)


def journal_gap_count(causes: Optional[dict]) -> int:
    """``journal_gap`` occurrences in a provenance cause-count map."""
    if not isinstance(causes, dict):
        return 0
    n = causes.get("journal_gap")
    return int(n) if isinstance(n, (int, float)) else 0


# ---------------------------------------------------------------------------
# Sample helpers (predicates over a collect()/merged() list).


def sample_children(samples: Any, name: str) -> list[dict]:
    if not isinstance(samples, list):
        return []
    return [s for s in samples
            if isinstance(s, dict) and s.get("name") == name]


def decision_tail(samples: Any) -> Optional[tuple]:
    """(p50, p99) off the unlabeled ``decision_latency_seconds``
    histogram total, or None without one."""
    from .registry import bucket_quantile

    for s in sample_children(samples, "decision_latency_seconds"):
        if (s.get("labels") or {}) != {} or s.get("type") != "histogram":
            continue
        buckets = s.get("buckets") or {}
        try:
            items = sorted(((float(k), int(v))
                            for k, v in buckets.items()
                            if k != "+Inf"), key=lambda kv: kv[0])
        except (TypeError, ValueError):
            return None
        if not items or not s.get("count"):
            return None
        bounds = [k for k, _ in items]
        counts = [v for _, v in items]
        counts.append(int(s["count"]) - sum(counts))  # the +Inf tail
        return (bucket_quantile(bounds, counts, 0.5),
                bucket_quantile(bounds, counts, 0.99))
    return None


# ---------------------------------------------------------------------------
# Rule predicates (each: ctx -> evidence dict when firing, else None).


def _pred_slo_burn(ctx: dict) -> Optional[dict]:
    hot = slo_hot_windows(ctx.get("slo"))
    return {"hot_windows": hot} if hot else None


def _pred_scrape_stale(ctx: dict) -> Optional[dict]:
    stale = stale_backend_list(ctx.get("fleet"))
    return {"stale_backends": stale} if stale else None


def _pred_respawn_gave_up(ctx: dict) -> Optional[dict]:
    return respawn_capacity_deficit(ctx.get("fleet"))


def _pred_journal_errors(ctx: dict) -> Optional[dict]:
    bad: dict = {}
    health = ctx.get("health") or {}
    for tenant, row in sorted((health.get("tenants") or {}).items()):
        if not isinstance(row, dict):
            continue
        fails = row.get("journal_append_failures")
        if isinstance(fails, (int, float)) and fails > 0:
            bad.setdefault(tenant, {})["append_failures"] = int(fails)
        lag = row.get("journal_lag_ops")
        if isinstance(lag, (int, float)) and lag > JOURNAL_LAG_ALERT_OPS:
            bad.setdefault(tenant, {})["journal_lag_ops"] = lag
    for s in sample_children(ctx.get("samples"), "journal_lag_ops"):
        v = s.get("value")
        tenant = (s.get("labels") or {}).get("tenant")
        if tenant and isinstance(v, (int, float)) \
                and v > JOURNAL_LAG_ALERT_OPS:
            bad.setdefault(tenant, {})["journal_lag_ops"] = v
    return {"tenants": bad} if bad else None


def _pred_watermark_stall(ctx: dict) -> Optional[dict]:
    stalls = {}
    for s in sample_children(ctx.get("samples"),
                             "online_watermark_stall_seconds"):
        v = s.get("value")
        if isinstance(v, (int, float)) and v > WATERMARK_STALL_ALERT_S:
            key = ",".join(f"{k}={v2}" for k, v2 in sorted(
                (s.get("labels") or {}).items())) or "total"
            stalls[key] = v
    return {"stall_seconds": stalls} if stalls else None


def _pred_circuit_open(ctx: dict) -> Optional[dict]:
    opened = {}
    for s in sample_children(ctx.get("samples"), "circuit_state"):
        v = s.get("value")
        dev = (s.get("labels") or {}).get("device")
        if dev and isinstance(v, (int, float)) and v >= 2:
            opened[dev] = v
    return {"open_circuits": opened} if opened else None


def _pred_unattributed(ctx: dict) -> Optional[dict]:
    n = 0
    for s in sample_children(ctx.get("samples"), "verdict_causes_total"):
        if (s.get("labels") or {}).get("code") == "unattributed":
            v = s.get("value")
            if isinstance(v, (int, float)):
                n += int(v)
    prov = (ctx.get("health") or {}).get("provenance")
    if isinstance(prov, dict):
        n += int(prov.get("unattributed") or 0)
    return {"unattributed": n} if n else None


def _pred_latency_tail(ctx: dict) -> Optional[dict]:
    tail = decision_tail(ctx.get("samples"))
    if tail is None:
        return None
    p50, p99 = tail
    if p50 is None or p99 is None or not tail_is_pathological(p50, p99):
        return None
    return {"p50_s": p50, "p99_s": p99, "ratio": round(p99 / p50, 1)}


def _pred_perf_regression(ctx: dict) -> Optional[dict]:
    findings = [f for f in (ctx.get("sentinel") or [])
                if isinstance(f, dict)]
    return {"findings": findings} if findings else None


# ---------------------------------------------------------------------------
# The rule type + built-in catalogue.


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One closed-form alert: ``predicate(ctx)`` returns an evidence
    dict while the condition holds, else None. ``for_s`` is the
    pending hold before firing; ``resolve_for_s`` the clean hold
    before a firing alert resolves (hysteresis). ``expected_causes``
    names the provenance codes this condition legitimately rides with
    (the chaos matrix's vocabulary) and ``kill_switch`` the env var
    that silences the subsystem the alert watches."""

    name: str
    severity: str
    predicate: Callable[[dict], Optional[dict]]
    for_s: float = 0.0
    resolve_for_s: float = 0.0
    summary: str = ""
    expected_causes: frozenset = frozenset()
    kill_switch: Optional[str] = None

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}; "
                             f"known: {SEVERITIES}")
        if self.for_s < 0 or self.resolve_for_s < 0:
            raise ValueError("for_s / resolve_for_s must be >= 0")

    def describe(self) -> dict:
        return {"name": self.name, "severity": self.severity,
                "for_s": self.for_s,
                "resolve_for_s": self.resolve_for_s,
                "summary": self.summary,
                "expected_causes": sorted(self.expected_causes),
                "kill_switch": self.kill_switch}


def catalogue() -> list[AlertRule]:
    """The built-in rule set, covering every live signal the repo
    already exports (docs/alerts.md tabulates it)."""
    return [
        AlertRule(
            "slo_burn", "high", _pred_slo_burn,
            summary="fleet SLO error budget burning past the "
                    "fast/slow multiwindow thresholds"),
        AlertRule(
            "scrape_stale", "medium", _pred_scrape_stale,
            summary="federation scrapes stale — fleet totals "
                    "partially frozen"),
        AlertRule(
            "respawn_gave_up", "high", _pred_respawn_gave_up,
            kill_switch="JEPSEN_NO_RESPAWN",
            expected_causes=frozenset({"backend_lost",
                                       "migration_interrupted"}),
            summary="fleet below configured capacity and respawn "
                    "will not restore it"),
        AlertRule(
            "journal_errors", "high", _pred_journal_errors,
            expected_causes=frozenset({"journal_gap"}),
            summary="journal appends failing or journal lag past its "
                    "ceiling — a crash now costs a resubmission storm"),
        AlertRule(
            "watermark_stall", "medium", _pred_watermark_stall,
            summary="decided watermark frozen with ops still flowing"),
        AlertRule(
            "circuit_open", "medium", _pred_circuit_open,
            kill_switch="JEPSEN_NO_FAILOVER",
            expected_causes=frozenset({"failover_exhausted",
                                       "round_failed"}),
            summary="a device-path circuit breaker is open — rounds "
                    "are failing over to host re-dispatch"),
        AlertRule(
            "latency_tail", "medium", _pred_latency_tail,
            for_s=ALERT_EVAL_INTERVAL_S * 2,
            summary="decision-latency tail pathological "
                    "(p99/p50 past threshold)"),
        AlertRule(
            "perf_regression", "medium", _pred_perf_regression,
            summary="change-point sentinel detected a sustained "
                    "mean shift in a watched perf series"),
        # The canary: the provenance contract says every degradation
        # carries a typed cause — this alert firing is itself a bug
        # (the chaos matrix's invariant, promoted to production).
        AlertRule(
            "unattributed_causes", "high", _pred_unattributed,
            summary="a verdict degraded with no typed cause — the "
                    "provenance taxonomy leaked (must never fire)"),
    ]


# Per chaos seam (testing/chaos.py POINTS): the ONLY alerts an
# injected fault there may raise — bench.py and tests/test_alerts.py
# assert fired-alerts ⊆ this set for the armed seam, and that clean
# runs raise none. The canary appears in NO set.
_FLEET_ALERTS = frozenset({"scrape_stale", "slo_burn",
                           "respawn_gave_up", "latency_tail",
                           "perf_regression"})
EXPECTED_ALERTS: dict[str, frozenset] = {
    # perf_regression rides every seam: a fault-induced throughput /
    # latency shift IS a change-point, and the sentinel is allowed to
    # say so alongside the fault's own typed alert.
    "service.pump": frozenset({"slo_burn", "watermark_stall",
                               "latency_tail", "perf_regression"}),
    "scheduler.worker": frozenset({"slo_burn", "watermark_stall",
                                   "latency_tail", "perf_regression"}),
    "device.dispatch": frozenset({"circuit_open", "slo_burn",
                                  "latency_tail", "perf_regression"}),
    "host.stack": frozenset({"circuit_open", "slo_burn",
                             "latency_tail", "perf_regression"}),
    "journal.fsync": frozenset({"journal_errors", "perf_regression"}),
    # A parse fault costs exactly the lines it hit — a typed
    # ingest_unmapped_op verdict cause, not an operational page.
    "ingest.parse": frozenset({"perf_regression"}),
    "router.probe": _FLEET_ALERTS,
    "backend.process": _FLEET_ALERTS,
    "router.crash": _FLEET_ALERTS,
}


# ---------------------------------------------------------------------------
# Change-point regression sentinel (CUSUM, closed form, no deps).


class Cusum:
    """Streaming two-sided CUSUM mean-shift detector.

    The first ``min_n`` samples calibrate a reference mean/σ
    (Welford); afterwards each sample's standardized deviation
    ``z = (x - μ) / σ`` drives the classic recursions

        g⁺ = max(0, g⁺ + z − k)        g⁻ = max(0, g⁻ − z − k)

    and :meth:`update` returns ``"up"`` / ``"down"`` when either sum
    crosses ``h`` (≈ k=0.5, h=5 detects a 1σ sustained shift within a
    handful of samples while a white-noise walk stays below h with
    drift −k). On detection the detector re-anchors on the new level
    (recalibrates), so a later shift back fires again."""

    def __init__(self, k: float = 0.5, h: float = 5.0,
                 min_n: int = 8):
        if min_n < 2:
            raise ValueError("min_n must be >= 2")
        self.k, self.h, self.min_n = float(k), float(h), int(min_n)
        self._reset()

    def _reset(self) -> None:
        self.n = 0
        self.mean = 0.0
        self._m2 = 0.0
        self.sigma = 0.0
        self.gp = 0.0
        self.gn = 0.0

    def update(self, x: float) -> Optional[str]:
        x = float(x)
        if not math.isfinite(x):
            return None
        if self.n < self.min_n:
            # Calibration window (Welford).
            self.n += 1
            d = x - self.mean
            self.mean += d / self.n
            self._m2 += d * (x - self.mean)
            if self.n == self.min_n:
                var = self._m2 / (self.n - 1)
                # σ floor: a dead-flat reference window must still
                # standardize finitely (any real change then fires).
                self.sigma = max(math.sqrt(max(var, 0.0)),
                                 abs(self.mean) * 1e-3, 1e-9)
            return None
        z = (x - self.mean) / self.sigma
        self.gp = max(0.0, self.gp + z - self.k)
        self.gn = max(0.0, self.gn - z - self.k)
        shift = "up" if self.gp > self.h else \
            "down" if self.gn > self.h else None
        if shift is not None:
            self._reset()  # re-anchor on the new level
        return shift


class RegressionSentinel:
    """Per-series change-point watch: one :class:`Cusum` per named
    series (ledger ``(kind, workload, engine, metric)`` keys, live
    ``sustained ops/s`` / p99 windows). :meth:`observe` feeds one
    sample and returns a finding dict when a shift lands in the
    series' regression direction; :meth:`active` lists findings still
    inside ``REGRESSION_ACTIVE_S`` — the ``perf_regression`` alert's
    context input."""

    def __init__(self, k: float = 0.5, h: float = 5.0, min_n: int = 8,
                 history_limit: int = 64):
        self._mk = lambda: Cusum(k=k, h=h, min_n=min_n)
        self._detectors: dict[str, Cusum] = {}
        self._findings: collections.deque = collections.deque(
            maxlen=history_limit)

    def observe(self, series: str, value: Any, *,
                lower_is_better: bool = False,
                t: Optional[float] = None) -> Optional[dict]:
        if not isinstance(value, (int, float)) \
                or not math.isfinite(float(value)):
            return None
        det = self._detectors.setdefault(series, self._mk())
        baseline = det.mean if det.n >= det.min_n else None
        shift = det.update(float(value))
        if shift is None:
            return None
        regression = (shift == "up") if lower_is_better \
            else (shift == "down")
        finding = {"series": series, "shift": shift,
                   "value": float(value), "baseline": baseline,
                   "regression": regression,
                   "t": float(t) if t is not None else _time.time()}
        if regression:
            self._findings.append(finding)
        return finding

    def observe_ledger(self, records: list, *,
                       now: Optional[float] = None) -> list[dict]:
        """Feed a loaded ledger's gated metric series through the
        per-(kind, workload, engine, metric) detectors; returns the
        regression findings raised."""
        from . import ledger as _ledger

        out = []
        for rec in records:
            if not isinstance(rec, dict):
                continue
            gkey = _ledger.group_key(rec)
            for name, key, direction in _ledger.LEDGER_METRICS:
                if direction == "info":
                    continue
                v = rec.get(key)
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool):
                    continue
                series = "/".join(str(k) for k in gkey) + ":" + name
                f = self.observe(series, v,
                                 lower_is_better=(direction == "lower"),
                                 t=now if now is not None
                                 else rec.get("ts"))
                if f is not None and f["regression"]:
                    out.append(f)
        return out

    def active(self, now: Optional[float] = None,
               within_s: float = REGRESSION_ACTIVE_S) -> list[dict]:
        now = _time.time() if now is None else now
        return [f for f in self._findings
                if now - f["t"] <= within_s]


# ---------------------------------------------------------------------------
# Webhook / ndjson sink (service/client.py's bounded-backoff idiom:
# emit() NEVER raises, zero-progress attempts back off exponentially
# and give up after max_retries).


class AlertSink:
    """Fan one transition record out to an HTTP webhook (``http(s)://``
    target — one JSON POST per record) or an ndjson file (any other
    target)."""

    def __init__(self, target: str, *, max_retries: int = 3,
                 base_backoff_s: float = 0.05, max_backoff_s: float = 2.0,
                 timeout_s: float = 5.0, sleep=_time.sleep):
        self.target = target
        self.is_http = target.startswith(("http://", "https://"))
        self.max_retries = max_retries
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.timeout_s = timeout_s
        self.sleep = sleep
        self.emitted = 0
        self.failures = 0

    def emit(self, record: dict) -> dict:
        if not self.is_http:
            try:
                d = os.path.dirname(self.target)
                if d:
                    os.makedirs(d, exist_ok=True)
                with open(self.target, "a", encoding="utf-8") as f:
                    f.write(json.dumps(record, sort_keys=True,
                                       default=str) + "\n")
                self.emitted += 1
                return {"ok": True, "status": 200, "attempts": 1}
            except OSError as e:
                self.failures += 1
                return {"ok": False, "status": 0, "attempts": 1,
                        "error": str(e)}
        import urllib.error
        import urllib.request

        body = json.dumps(record, sort_keys=True,
                          default=str).encode("utf-8")
        consec = 0
        while True:
            try:
                req = urllib.request.Request(
                    self.target, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as r:
                    self.emitted += 1
                    return {"ok": True, "status": r.status,
                            "attempts": consec + 1}
            except urllib.error.HTTPError as e:
                status, retryable = e.code, e.code in (429, 503)
            except (urllib.error.URLError, OSError, TimeoutError):
                status, retryable = 0, True
            consec += 1
            if not retryable or consec >= self.max_retries:
                self.failures += 1
                return {"ok": False, "status": status,
                        "attempts": consec}
            self.sleep(min(self.base_backoff_s * (2 ** (consec - 1)),
                           self.max_backoff_s))


# ---------------------------------------------------------------------------
# The lifecycle engine + durable alerts.jsonl.


class _RuleState:
    __slots__ = ("state", "since", "clear_since", "generation",
                 "evidence")

    def __init__(self):
        self.state = "inactive"
        self.since: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.generation = 0
        self.evidence: Optional[dict] = None


class AlertEngine:
    """Evaluates a rule set over context snapshots on the host's
    cadence, maintains the typed per-rule lifecycle, appends every
    transition to a durable ``alerts.jsonl`` (ConsistentLines
    discipline: reopening truncates a torn tail, replay restores the
    firing set and the monotone generation counters), exports
    ``alerts_total{rule,severity}`` / ``alerts_firing{rule}``, and
    fans transitions out to an optional :class:`AlertSink`."""

    def __init__(self, rules: Optional[list] = None, *,
                 metrics=None, path: Optional[str] = None,
                 sink: Optional[AlertSink] = None, source: str = "",
                 history_limit: int = 512, now=_time.time):
        self.rules = list(rules) if rules is not None else catalogue()
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.metrics = metrics
        self.sink = sink
        self.source = source
        self.path = path
        self.now = now
        self.eval_seconds = 0.0
        self.evaluations = 0
        self.append_failures = 0
        self.replayed = 0
        self.replay_torn = False
        self._history: collections.deque = collections.deque(
            maxlen=history_limit)
        self._state: dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}
        self._f = None
        if path:
            self._open_journal(path)

    # -- durability ----------------------------------------------------------

    def _open_journal(self, path: str) -> None:
        """Replay the consistent prefix (restoring firing states and
        generation counters), truncate any torn tail, reopen for
        line-buffered append — the TenantJournal reopen discipline."""
        from ..service.journal import ConsistentLines

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        consistent = 0
        if os.path.exists(path):
            lines = ConsistentLines(path)
            for rec in lines:
                self._restore(rec)
                self.replayed += 1
            self.replay_torn = lines.torn
            consistent = lines.consistent_bytes
            if lines.torn:
                try:
                    with open(path, "r+b") as tf:
                        tf.truncate(consistent)
                except OSError:
                    LOG.warning("could not truncate torn tail of %s",
                                path, exc_info=True)
        self._f = open(path, "a", buffering=1, encoding="utf-8")

    def _restore(self, rec: dict) -> None:
        rule = rec.get("rule")
        st = self._state.get(rule)
        if st is None:
            return  # a rule removed from the catalogue: history only
        state = rec.get("state")
        if state not in STATES:
            return
        gen = rec.get("generation")
        if isinstance(gen, int):
            st.generation = max(st.generation, gen)
        st.state = "inactive" if state == "resolved" else state
        st.since = rec.get("t") if isinstance(
            rec.get("t"), (int, float)) else None
        st.clear_since = None
        st.evidence = rec.get("evidence") \
            if isinstance(rec.get("evidence"), dict) else None
        self._history.append(dict(rec))

    def _append(self, rec: dict) -> None:
        if self._f is None:
            return
        try:
            self._f.write(json.dumps(rec, sort_keys=True,
                                     default=str) + "\n")
        except (OSError, ValueError):
            self.append_failures += 1
            if self.append_failures == 1:
                LOG.warning("alerts.jsonl append failing (%s); alert "
                            "durability lost, evaluation continues",
                            self.path, exc_info=True)

    # -- evaluation ----------------------------------------------------------

    def _record(self, rule: AlertRule, state: str, now: float,
                st: _RuleState) -> dict:
        rec = {"t": now, "rule": rule.name, "severity": rule.severity,
               "state": state, "generation": st.generation,
               "evidence": st.evidence, "source": self.source}
        self._history.append(rec)
        self._append(rec)
        if self.metrics is not None:
            firing = self.metrics.gauge(
                "alerts_firing",
                "Alert rules currently firing (1 per firing rule; "
                "the unlabeled total is the firing count)",
                labelnames=("rule",), aggregate=True)
            if state == "firing":
                total = self.metrics.counter(
                    "alerts_total",
                    "Alert firing transitions, by rule and severity",
                    labelnames=("rule", "severity"), aggregate=True)
                total.labels(rule=rule.name,
                             severity=rule.severity).inc()
                total.inc()  # the unlabeled all-rules child
                firing.labels(rule=rule.name).set(1)
            elif state in ("resolved", "inactive"):
                firing.labels(rule=rule.name).set(0)
            if state in ("firing", "resolved", "inactive"):
                firing.set(len(self.firing()))
        if self.sink is not None:
            try:
                self.sink.emit(rec)
            except Exception:  # noqa: BLE001 - sink must never bite
                LOG.warning("alert sink raised", exc_info=True)
        return rec

    def evaluate(self, ctx: dict, now: Optional[float] = None) -> list:
        """One pass over every rule; returns the transition records
        emitted (possibly empty). Never raises out of a predicate —
        a broken rule reads as not-firing."""
        t0 = _time.perf_counter()
        now = self.now() if now is None else now
        ctx = dict(ctx or {})
        ctx.setdefault("now", now)
        out = []
        for rule in self.rules:
            try:
                ev = rule.predicate(ctx)
            except Exception:  # noqa: BLE001
                LOG.warning("alert predicate %s raised", rule.name,
                            exc_info=True)
                ev = None
            st = self._state[rule.name]
            if ev:
                st.clear_since = None
                st.evidence = ev
                if st.state == "inactive":
                    st.since = now
                    if rule.for_s > 0:
                        st.state = "pending"
                        out.append(self._record(rule, "pending", now,
                                                st))
                    else:
                        st.state = "firing"
                        st.generation += 1
                        out.append(self._record(rule, "firing", now,
                                                st))
                elif st.state == "pending" \
                        and now - (st.since or now) >= rule.for_s:
                    st.state = "firing"
                    st.generation += 1
                    st.since = now
                    out.append(self._record(rule, "firing", now, st))
            else:
                if st.state == "pending":
                    st.state = "inactive"
                    st.since = None
                    out.append(self._record(rule, "inactive", now, st))
                elif st.state == "firing":
                    if rule.resolve_for_s > 0:
                        if st.clear_since is None:
                            st.clear_since = now
                        if now - st.clear_since < rule.resolve_for_s:
                            continue
                    st.state = "inactive"
                    st.since = None
                    st.clear_since = None
                    out.append(self._record(rule, "resolved", now, st))
        self.eval_seconds += _time.perf_counter() - t0
        self.evaluations += 1
        return out

    # -- views ---------------------------------------------------------------

    def firing(self) -> dict:
        """rule -> {severity, since, generation, evidence} for every
        currently-firing rule (the restart-replay pin's subject)."""
        out = {}
        for rule in self.rules:
            st = self._state[rule.name]
            if st.state == "firing":
                out[rule.name] = {"severity": rule.severity,
                                  "since": st.since,
                                  "generation": st.generation,
                                  "evidence": st.evidence}
        return out

    def fired_rules(self) -> set:
        """Every rule that has fired at least once this process
        generation (history + replay) — the chaos matrix's subject."""
        return {rec["rule"] for rec in self._history
                if rec.get("state") == "firing"}

    def history(self, limit: int = 40) -> list[dict]:
        return list(self._history)[-limit:]

    def timeline_rows(self, limit: int = 40) -> list[dict]:
        """Alert transitions shaped for the /fleet timeline join
        (kind="alert" next to place/respawn/epoch rows)."""
        return [{"kind": "alert", "t": rec.get("t"),
                 "rule": rec.get("rule"), "state": rec.get("state"),
                 "severity": rec.get("severity"),
                 "generation": rec.get("generation")}
                for rec in self.history(limit)]

    def snapshot(self) -> dict:
        """The ``GET /alerts`` document."""
        return {"enabled": True, "source": self.source,
                "path": self.path,
                "rules": [r.describe() for r in self.rules],
                "firing": self.firing(),
                "recent": self.history(),
                "evaluations": self.evaluations,
                "eval_seconds": round(self.eval_seconds, 6),
                "append_failures": self.append_failures,
                "replayed": self.replayed,
                "replay_torn": self.replay_torn}

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


# ---------------------------------------------------------------------------
# Offline emitters (the `ledger --check --alerts` pipeline) + replay.


def replay(path: str) -> dict:
    """Fold an ``alerts.jsonl`` consistent prefix into
    ``{"records", "firing", "torn"}`` without constructing an engine —
    the CLI's and the ledger emitter's shared reader."""
    from ..service.journal import ConsistentLines

    records: list[dict] = []
    last: dict[str, dict] = {}
    torn = False
    if os.path.exists(path):
        lines = ConsistentLines(path)
        for rec in lines:
            records.append(rec)
            if rec.get("rule"):
                last[rec["rule"]] = rec
        torn = lines.torn
    firing = {r: {"severity": rec.get("severity"),
                  "since": rec.get("t"),
                  "generation": rec.get("generation"),
                  "evidence": rec.get("evidence")}
              for r, rec in sorted(last.items())
              if rec.get("state") in ("firing", "pending")
              and rec.get("state") == "firing"}
    return {"records": records, "firing": firing, "torn": torn}


def append_finding(path: str, evidence: dict, *,
                   rule: str = "perf_regression",
                   severity: str = "medium", source: str = "ledger",
                   now: Optional[float] = None) -> Optional[dict]:
    """Append one firing record for an offline finding (the
    ``ledger --check --alerts`` seam), continuing the file's monotone
    generation counter. Never raises; returns the record or None."""
    try:
        folded = replay(path)
        gen = max((r.get("generation") or 0
                   for r in folded["records"]
                   if r.get("rule") == rule), default=0) + 1
        rec = {"t": _time.time() if now is None else now, "rule": rule,
               "severity": severity, "state": "firing",
               "generation": gen, "evidence": evidence,
               "source": source}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
        return rec
    except OSError:
        LOG.warning("could not append alert finding to %s", path,
                    exc_info=True)
        return None


# ---------------------------------------------------------------------------
# CLI: tail / replay an alerts.jsonl.


def _render_record(rec: dict) -> str:
    t = rec.get("t")
    stamp = _time.strftime("%H:%M:%S", _time.localtime(t)) \
        if isinstance(t, (int, float)) else "?"
    return (f"{stamp}  {rec.get('state', '?'):8s} "
            f"[{rec.get('severity', '?')}] {rec.get('rule', '?')}"
            f"  gen={rec.get('generation')}"
            + (f"  source={rec['source']}" if rec.get("source") else ""))


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.alerts",
        description="Replay or tail a durable alerts.jsonl (the "
                    "alert plane's transition journal).")
    p.add_argument("path", help="alerts.jsonl to read")
    p.add_argument("--firing", action="store_true",
                   help="print only the restored firing set")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--follow", action="store_true",
                   help="keep polling for appended records (Ctrl-C "
                        "to stop)")
    p.add_argument("--interval", type=float, default=1.0,
                   help="--follow poll interval seconds")
    ns = p.parse_args(argv)

    if not os.path.exists(ns.path):
        print(f"alerts: no such file {ns.path!r}", file=sys.stderr)
        return 2
    folded = replay(ns.path)
    if ns.as_json:
        doc = {"firing": folded["firing"], "torn": folded["torn"]}
        if not ns.firing:
            doc["records"] = folded["records"]
        print(json.dumps(doc, indent=1, sort_keys=True, default=str))
    elif ns.firing:
        if not folded["firing"]:
            print("no alerts firing")
        for rule, row in folded["firing"].items():
            print(f"FIRING [{row['severity']}] {rule} "
                  f"gen={row['generation']} since={row['since']}")
    else:
        for rec in folded["records"]:
            print(_render_record(rec))
        print(f"-- {len(folded['records'])} transition(s), "
              f"{len(folded['firing'])} firing"
              + (", torn tail dropped" if folded["torn"] else ""))
    if ns.follow:
        seen = len(folded["records"])
        try:
            while True:
                _time.sleep(ns.interval)
                folded = replay(ns.path)
                for rec in folded["records"][seen:]:
                    print(_render_record(rec), flush=True)
                seen = max(seen, len(folded["records"]))
        except KeyboardInterrupt:
            pass
    return 1 if folded["firing"] and ns.firing else 0


if __name__ == "__main__":
    sys.exit(main())
