"""Framework-wide telemetry: metrics registry, sinks, and heartbeats.

The reference exports OpenCensus spans from ONE suite (dgraph's
trace.clj) and nothing from the checker side; this package instruments
the whole stack — WGL kernel chunks (per-level frontier sizes, dedup
ratios, capacity escalations, compile-vs-execute split), the
frontier-sharded search (per-device config counts, all_gather bytes),
the interpreter/client path (op latency histograms by ``f`` and
``type``), and ``core.run`` phase timings — and writes ``metrics.jsonl``
+ ``metrics.prom`` into the run's ``store/`` directory next to
``spans.jsonl``, with ``jepsen_tpu.web``'s ``/metrics`` page rendering
them per run.

Gating seam: everything hangs off ``test["telemetry?"]`` (the
``--telemetry`` CLI flag). :func:`of_test` returns the test's registry —
creating and caching it under ``test["telemetry-registry"]`` — or None
when telemetry is off, and every instrumentation site guards on that
None, so a disabled run takes zero extra allocations; the jit'd WGL
kernel in particular is only built with its stats carry when a registry
is actually injected (``metrics=`` on the driver entry points). See
docs/telemetry.md.
"""

from __future__ import annotations

from typing import Optional

from . import flight, profile  # noqa: F401
from .export import (  # noqa: F401
    export_jsonl,
    export_prometheus,
    jsonl_lines,
    prometheus_text,
    store_metrics,
)
from .flight import FlightRecorder, store_flight_record  # noqa: F401
from .heartbeat import Heartbeat  # noqa: F401
from .profile import (  # noqa: F401
    attribute,
    memory_watermarks,
    store_profile,
    trace_capture,
)
from .registry import (  # noqa: F401
    DECISION_LATENCY_BUCKETS,
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    bucket_quantile,
    timed_phase,
)


import threading as _threading

_of_test_lock = _threading.Lock()


def enabled(test: Optional[dict]) -> bool:
    """Is telemetry requested on this test map?"""
    return bool(test and test.get("telemetry?"))


def of_test(test: Optional[dict]) -> Optional[Registry]:
    """The test's registry, created on first ask — or None when telemetry
    is off (callers guard their instrumentation on that None). Creation
    is locked: composed checkers ask from parallel threads, and a racy
    double-create would silently split the series."""
    if not enabled(test):
        return None
    reg = test.get("telemetry-registry")
    if reg is None:
        with _of_test_lock:
            reg = test.get("telemetry-registry")
            if reg is None:
                reg = test["telemetry-registry"] = Registry()
    return reg


__all__ = [
    "Counter",
    "DECISION_LATENCY_BUCKETS",
    "DEFAULT_BUCKETS",
    "FlightRecorder",
    "bucket_quantile",
    "Gauge",
    "Heartbeat",
    "Histogram",
    "Registry",
    "attribute",
    "enabled",
    "export_jsonl",
    "export_prometheus",
    "flight",
    "jsonl_lines",
    "memory_watermarks",
    "of_test",
    "profile",
    "prometheus_text",
    "store_flight_record",
    "store_metrics",
    "store_profile",
    "timed_phase",
    "trace_capture",
]
