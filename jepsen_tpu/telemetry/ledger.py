"""Cross-run perf ledger: every ``core.run`` and every ``bench.py`` leg
appends ONE compact JSON line to ``store/ledger.jsonl``, and
``python -m jepsen_tpu.ledger`` renders the direction-aware trend —
so a regression is caught *between* the five-per-epoch committed
``BENCH_r*.json`` rounds, not only when a judge diffs them.

The committed-round gate (``jepsen_tpu.benchcmp``) compares bench
artifacts; this ledger compares *runs*: local test runs, CI bench legs,
ad-hoc ``core.run`` invocations — anything that executed on this store.
A record carries run identity (workload, engine/exchange mode), scale
(ops), verdict, and the observability stack's headline numbers
(checker seconds, p99 decision latency, mean device utilization, idle
gap-attribution shares — see ``telemetry.utilization``):

```json
{"ts": 1754300000.0, "kind": "run", "run": "cas-register/2026...",
 "workload": "cas-register", "engine": "native", "ops": 10000,
 "verdict": "True", "checker_seconds": 0.041,
 "p99_decision_latency_s": 0.18, "utilization_pct": 81.3,
 "gap_share": {"compiling": 0.7, "no-work": 0.3}}
```

Trend + gate semantics REUSE benchcmp's machinery: records group by
``(kind, workload, engine)`` (only like runs compare), the table is
``benchcmp.render_table`` over :data:`LEDGER_METRICS` (same
direction-aware arrows), and ``--check`` runs ``benchcmp.deltas`` on
each group's newest record vs its predecessor, exiting nonzero past
the threshold — suitable as a post-bench CI step. See
docs/profiling.md ("Utilization & ledger").

Appends are append-only, best-effort (a ledger write never sinks a
run) and one-line JSON, so concurrent writers interleave whole
records; unparseable lines are skipped on load.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time as _time
from pathlib import Path
from typing import Any, Optional

LEDGER_BASENAME = "ledger.jsonl"

# Metric catalogue: (name, key, direction) — flat keys into a ledger
# record; same direction semantics as benchcmp.METRICS ("lower" =
# seconds-like, "higher" = throughput/utilization-like, "info" = shown
# but never gated).
LEDGER_METRICS: list[tuple[str, str, str]] = [
    ("value_s", "value_s", "lower"),
    ("checker_seconds", "checker_seconds", "lower"),
    ("p99_decision_latency_s", "p99_decision_latency_s", "lower"),
    ("utilization_pct", "utilization_pct", "higher"),
    ("ops_per_s", "ops_per_s", "higher"),
    # Self-healing fleet: spawn → /healthz on the replacement child
    # after the router bench leg's injected kill-9.
    ("respawn_seconds", "respawn_seconds", "lower"),
    # Fleet federation: the bucket-merged cross-process p99 and the
    # coldest backend's busy share (telemetry/fleet.py).
    ("fleet_p99_decision_latency_s",
     "fleet_p99_decision_latency_s", "lower"),
    ("fleet_min_backend_utilization_pct",
     "fleet_min_backend_utilization_pct", "higher"),
    # Offline decrease-and-conquer: the segment planner's one-pass
    # cut cost over the recorded history (growing = planning stopped
    # being negligible next to deciding) and the end-to-end advantage
    # over the single-driver serial search ("info": the serial rate is
    # sample-measured and superlinear in history length, so the ratio
    # is a machine-dependent lower bound — gated in tests, not here).
    ("plan_seconds", "plan_seconds", "lower"),
    ("speedup_vs_serial", "speedup_vs_serial", "info"),
    # Alerting plane (telemetry/alerts.py): how long the armed
    # journal-fault took to flip `journal_errors` to firing, and what
    # rule evaluation cost against the service leg's wall clock —
    # both growing means the watchdog got slower or heavier.
    ("alert_detection_seconds", "alert_detection_seconds", "lower"),
    ("alert_eval_overhead_pct", "alert_eval_overhead_pct", "lower"),
    # Trace ingestion (jepsen_tpu.ingest): raw-recording parse+check
    # throughput of the adapter front door.
    ("ingest_ops_per_s", "ingest_ops_per_s", "higher"),
    ("ops", "ops", "info"),
]

DEFAULT_THRESHOLD = 0.10


def default_path(root: Optional[Any] = None) -> Path:
    """``<store root>/ledger.jsonl``; ``JEPSEN_LEDGER_PATH`` overrides
    everything (CI can point every writer at one file)."""
    env = os.environ.get("JEPSEN_LEDGER_PATH")
    if env:
        return Path(env)
    if root is None:
        from .. import store

        root = store.BASE_DIR
    return Path(root) / LEDGER_BASENAME


def append(record: dict, path: Optional[Any] = None) -> Optional[str]:
    """Append one record (``ts`` stamped if absent). Never raises —
    the ledger is an observability artifact, not a run dependency."""
    try:
        p = Path(path) if path is not None else default_path()
        rec = dict(record)
        rec.setdefault("ts", round(_time.time(), 3))
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "a") as f:
            f.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
        return str(p)
    except Exception:  # noqa: BLE001
        return None


def load(path: Optional[Any] = None) -> list[dict]:
    """All parseable records, in file (= time) order."""
    p = Path(path) if path is not None else default_path()
    out: list[dict] = []
    try:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if isinstance(d, dict):
                    out.append(d)
    except OSError:
        return []
    return out


# ---------------------------------------------------------------------------
# Record builders


def _walk_results(results: Any, found: dict) -> None:
    if not isinstance(results, dict):
        return
    for k, v in results.items():
        if k in ("backend", "exchange", "n_shards") and not isinstance(
                v, (dict, list)):
            found.setdefault(k, v)
        elif isinstance(v, dict):
            _walk_results(v, found)


def _stored_utilization_summary(test: dict) -> Optional[dict]:
    """A --profile run's core.run already reconstructed utilization
    into profile.json moments before the ledger append — read the
    summary back instead of re-running the full event-ring scan (and
    re-setting gauges after the metric sinks were exported)."""
    if not test.get("profile?"):
        return None
    if not (test.get("name") and test.get("start-time")) or test.get(
            "no-store?"):
        return None
    try:
        from .. import store

        doc = json.loads(store.path(test, "profile.json").read_text())
        return (doc.get("attribution") or {}).get(
            "utilization", {}).get("summary")
    except Exception:  # noqa: BLE001 - fall back to recomputing
        return None


def record_of_run(test: dict) -> dict:
    """One compact ledger record from a finished (or crashed)
    ``core.run`` test map: identity, scale, verdict, checker seconds,
    online p99 decision latency, and the utilization summary when the
    run's registry recorded stamped chunk events. The utilization
    module is only imported when those events exist (the telemetry-off
    pin in tests/test_telemetry.py)."""
    results = test.get("results") or {}
    found: dict = {}
    _walk_results(results, found)
    h = test.get("history")
    rec: dict = {
        "kind": "run",
        "run": f"{test.get('name')}/{test.get('start-time')}",
        "workload": test.get("name"),
        "engine": found.get("backend") or "host",
        "verdict": str(results.get("valid")),
    }
    if found.get("exchange"):
        rec["exchange"] = found["exchange"]
    if found.get("n_shards"):
        rec["n_shards"] = found["n_shards"]
    try:
        rec["ops"] = len(h) if h is not None else None
    except TypeError:
        rec["ops"] = None
    reg = test.get("telemetry-registry")
    if reg is not None:
        try:
            s = reg.summary()
            cs = []
            for k, v in s.items():
                if not k.startswith("checker_seconds"):
                    continue
                # checker_seconds is a histogram: summary() folds it to
                # {count, sum} — the per-run total IS the sum.
                if isinstance(v, dict):
                    v = v.get("sum")
                if isinstance(v, (int, float)):
                    cs.append(float(v))
            if cs:
                rec["checker_seconds"] = round(sum(cs), 6)
        except Exception:  # noqa: BLE001 - record what we can
            pass
        try:
            u_summary = _stored_utilization_summary(test)
            if u_summary is None:
                from .profile import _attribute_utilization

                u = _attribute_utilization(reg)
                u_summary = u["summary"] if u is not None else None
            if u_summary is not None:
                rec["utilization_pct"] = \
                    u_summary["mean_utilization_pct"]
                if u_summary.get("gap_attribution_share"):
                    rec["gap_share"] = \
                        u_summary["gap_attribution_share"]
        except Exception:  # noqa: BLE001
            pass
    onl = test.get("online-results") or {}
    lat = onl.get("decision_latency") or {}
    if lat.get("p99_s") is not None:
        rec["p99_decision_latency_s"] = lat["p99_s"]
    return rec


# bench.py leg catalogue: (leg name, dotted path into the bench dict or
# None for top level, engine, {ledger key: source key}).
_BENCH_LEGS: list[tuple[str, Optional[str], str, dict]] = [
    ("headline", None, "native",
     {"value_s": "value", "ops_per_s": "ops_per_s"}),
    ("invalid_refutation", None, "native", {"value_s": "invalid_s"}),
    ("interpreter", None, "host",
     {"ops_per_s": "interpreter_ops_per_s"}),
    ("online_10k", "online_10k", "host",
     {"value_s": "online_s",
      "p99_decision_latency_s": "p99_decision_latency_s",
      "ops": "n_ops", "verdict": "valid"}),
    ("service_streams", "service_streams", "host",
     {"value_s": "wall_s", "ops_per_s": "sustained_ops_per_s",
      "p99_decision_latency_s": "p99_decision_latency_s",
      "ops": "n_ops_total", "verdict": "valid_all",
      # Alerting plane: detection latency of the armed journal fault
      # and the rule-evaluation overhead share of the leg's wall.
      "alert_detection_seconds": "alert_detection_seconds",
      "alert_eval_overhead_pct": "alert_eval_overhead_pct"}),
    ("service_router", "service_router", "host",
     {"value_s": "wall_s", "ops_per_s": "sustained_ops_per_s",
      "p99_decision_latency_s": "p99_decision_latency_s",
      "ops": "n_ops_total", "verdict": "valid_all",
      # Self-healing fleet: the repair half of the kill cycle.
      "respawn_seconds": "respawn_seconds",
      # Fleet federation: cross-process p99 + coldest backend busy
      # share from the router's federated scrape.
      "fleet_p99_decision_latency_s": "fleet_p99_decision_latency_s",
      "fleet_min_backend_utilization_pct":
          "fleet_min_backend_utilization_pct"}),
    ("batch_replay_100", "batch_replay_100", "device",
     {"value_s": "value_s"}),
    ("batch_replay_large", "batch_replay_large", "device",
     {"value_s": "value_s"}),
    ("smoke_8x10k", "batch_replay_large.smoke_8x10k", "device",
     {"value_s": "value_s", "utilization_pct": "utilization_pct"}),
    ("elle_txn", "elle_txn", "device",
     {"value_s": "value_s", "ops": "mops"}),
    # Batched Elle SCC/closure engine: N graphs across >=2 size
    # buckets through <= one vmapped dispatch per bucket.
    ("elle_scc_batched", "elle_scc_batched", "device",
     {"value_s": "value_s", "ops_per_s": "elle_txns_per_s",
      "ops": "n_txns", "speedup_vs_serial": "elle_batch_speedup_x"}),
    ("mutex_5k", "mutex_5k", "device", {"value_s": "value_s"}),
    ("device_kernel", None, "device",
     {"value_s": "device_kernel_s",
      "utilization_pct": "device_utilization_pct"}),
    ("max_verified_ops", "max_verified_ops", "native",
     {"ops": "ops", "value_s": "value_s", "ops_per_s": "ops_per_s"}),
    ("max_verified_ops_device", "max_verified_ops_device", "device",
     {"ops": "ops", "value_s": "value_s"}),
    ("max_verified_ops_device_sharded",
     "max_verified_ops_device_sharded", "sharded",
     {"ops": "ops", "value_s": "value_s"}),
    # Offline decrease-and-conquer: plan() → drive() over a recorded
    # ≥1M-op keyed history (segment × carried-state co-batching).
    ("offline_segmented", "offline_segmented", "auto",
     {"value_s": "decide_seconds", "ops_per_s": "ops_per_s",
      "plan_seconds": "plan_seconds",
      "speedup_vs_serial": "speedup_vs_serial",
      "utilization_pct": "utilization_pct",
      "ops": "n_ops", "verdict": "valid"}),
    # Trace ingestion: a 10k-op synthetic etcd recording through
    # adapter → pairing → classification → segmented WGL.
    ("ingest_etcd_10k", "ingest_etcd_10k", "host",
     {"value_s": "value_s", "ingest_ops_per_s": "ingest_ops_per_s",
      "ops": "ops", "verdict": "valid"}),
]


def _dig(d: Any, path: Optional[str]) -> Any:
    if path is None:
        return d
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def records_of_bench(out: dict) -> list[dict]:
    """One record per bench leg that actually produced a number —
    skipped/errored legs leave no record (their absence from the trend
    IS the signal; the bench JSON itself records the error)."""
    ts = round(_time.time(), 3)
    recs = []
    for leg, path, engine, fields in _BENCH_LEGS:
        data = _dig(out, path)
        if not isinstance(data, dict):
            continue
        rec: dict = {"ts": ts, "kind": "bench", "run": leg,
                     "workload": leg, "engine": engine}
        got_number = False
        for key, src in fields.items():
            v = data.get(src)
            if key == "verdict":
                if v is not None:
                    rec["verdict"] = str(v)
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                rec[key] = v
                got_number = True
        if got_number:
            recs.append(rec)
    return recs


# ---------------------------------------------------------------------------
# Trend + gate (reusing benchcmp's delta/threshold machinery)


def group_key(rec: dict) -> tuple:
    """Comparability key: only like runs trend against each other."""
    return (str(rec.get("kind")), str(rec.get("workload")),
            str(rec.get("engine")))


def grouped(records: list[dict]) -> dict[tuple, list[dict]]:
    out: dict[tuple, list[dict]] = {}
    for r in records:
        out.setdefault(group_key(r), []).append(r)
    return out


def _metrics_of(rec: dict) -> dict:
    return {name: float(rec[key]) for name, key, _d in LEDGER_METRICS
            if isinstance(rec.get(key), (int, float))
            and not isinstance(rec.get(key), bool)}


def _label(rec: dict, i: int) -> str:
    ts = rec.get("ts")
    try:
        return _time.strftime("%m-%d %H:%M", _time.localtime(float(ts)))
    except (TypeError, ValueError):
        return f"#{i}"


def trend(records: list[dict], threshold: float = DEFAULT_THRESHOLD,
          last: int = 8) -> list[dict]:
    """Per-group trend blocks: the newest ``last`` records as table
    columns plus the newest-vs-previous delta block (benchcmp.deltas
    over :data:`LEDGER_METRICS`)."""
    from .. import benchcmp

    out = []
    for key, recs in sorted(grouped(records).items()):
        recs = sorted(recs, key=lambda r: r.get("ts") or 0)
        window = recs[-last:]
        merged = [{"label": _label(r, i), "metrics": _metrics_of(r)}
                  for i, r in enumerate(window)]
        block: dict = {
            "key": {"kind": key[0], "workload": key[1],
                    "engine": key[2]},
            "records": len(recs),
            "columns": merged,
            "verdicts": [str(r.get("verdict")) for r in window],
        }
        if len(recs) >= 2:
            d = benchcmp.deltas(_metrics_of(recs[-2]),
                                _metrics_of(recs[-1]),
                                threshold=threshold,
                                metrics=LEDGER_METRICS)
            block["deltas"] = d
            block["regressions"] = benchcmp.regressions(d)
        out.append(block)
    return out


def check(records: list[dict],
          threshold: float = DEFAULT_THRESHOLD) -> list[dict]:
    """The ``--check`` gate: every group's newest record vs its
    previous comparable one; returns the flagged groups (empty =
    pass). Post-bench CI runs this right after the bench appended its
    leg records, so each leg gates against its own history."""
    return [b for b in trend(records, threshold=threshold)
            if b.get("regressions")]


def render(records: list[dict], threshold: float = DEFAULT_THRESHOLD,
           last: int = 8) -> str:
    from .. import benchcmp

    if not records:
        return ("ledger is empty — runs and bench legs append to "
                f"{default_path()}")
    lines = []
    for block in trend(records, threshold=threshold, last=last):
        k = block["key"]
        lines.append(f"== {k['kind']} {k['workload']} "
                     f"[engine={k['engine']}] "
                     f"({block['records']} records)")
        lines.append(benchcmp.render_table(block["columns"],
                                           metrics=LEDGER_METRICS))
        lines.append("verdicts: " + " ".join(block["verdicts"]))
        for name in sorted(block.get("deltas") or {}):
            d = block["deltas"][name]
            if "delta_pct" not in d:
                continue
            if d["regression"] or abs(d["delta_pct"]) >= 5:
                flag = " ** REGRESSION" if d["regression"] else ""
                lines.append(
                    f"  {name}: {benchcmp._fmt(d['prev'])} -> "
                    f"{benchcmp._fmt(d['cur'])} "
                    f"({d['delta_pct']:+.1f}%){flag}")
        lines.append("")
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.ledger",
        description="Render the cross-run perf ledger's trend and gate "
                    "on regressions between comparable runs.")
    p.add_argument("path", nargs="?", default=None,
                   help=f"ledger file (default {default_path()})")
    p.add_argument("--check", action="store_true",
                   help="exit nonzero when any group's newest record "
                        "regresses past the threshold vs its previous "
                        "comparable run (same workload + engine)")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help="regression threshold as a fraction "
                        "(default 0.10 = 10%%)")
    p.add_argument("--workload", default=None,
                   help="only this workload/leg")
    p.add_argument("--last", type=int, default=8,
                   help="table columns per group (default 8)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--alerts", default=None, metavar="ALERTS_JSONL",
                   help="with --check: append each flagged group as a "
                        "`perf_regression` alert record to this "
                        "alerts.jsonl (the alerting plane's durable "
                        "format — `python -m jepsen_tpu.alerts` tails "
                        "it), so offline ledger gating and the live "
                        "sentinel share one alert stream")
    ns = p.parse_args(argv)

    records = load(ns.path)
    if ns.workload:
        records = [r for r in records
                   if str(r.get("workload")) == ns.workload]
    flagged = check(records, threshold=ns.threshold) if records else []
    if ns.alerts and ns.check:
        from . import alerts as _alerts
        for b in flagged:
            _alerts.append_finding(ns.alerts, {
                "key": b["key"],
                "regressions": b["regressions"],
                "deltas": {m: b["deltas"][m]
                           for m in b["regressions"]
                           if m in (b.get("deltas") or {})},
                "threshold": ns.threshold,
            }, rule="perf_regression", severity="medium",
                source="ledger")
    if ns.as_json:
        print(json.dumps({
            "groups": trend(records, threshold=ns.threshold,
                            last=ns.last),
            "threshold": ns.threshold,
            "flagged": [b["key"] for b in flagged],
        }, indent=1, sort_keys=True, default=str))
    else:
        print(render(records, threshold=ns.threshold, last=ns.last))
        if ns.check:
            if flagged:
                names = sorted(
                    f"{b['key']['workload']}[{b['key']['engine']}]"
                    f": {', '.join(b['regressions'])}"
                    for b in flagged)
                print(f"REGRESSIONS past {ns.threshold * 100:.0f}%:")
                print("\n".join("  " + n for n in names))
            else:
                print(f"no regressions past {ns.threshold * 100:.0f}% "
                      "(newest record per comparable group)")
    return 1 if (ns.check and flagged) else 0


if __name__ == "__main__":
    sys.exit(main())
