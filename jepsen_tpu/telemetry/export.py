"""Metric sinks: JSON-lines and Prometheus text exposition, written into
the test's ``store/`` directory next to ``spans.jsonl``.

- ``metrics.jsonl`` — one JSON object per line: every metric sample
  (counters/gauges carry ``value``; histograms carry ``count``/``sum``/
  ``buckets``) followed by every event point (``"type": "event"`` — the
  per-BFS-level frontier rows the WGL driver records). This is the
  machine-readable sink bench rounds and tests consume.
- ``metrics.prom`` — Prometheus text exposition format 0.0.4 (HELP/TYPE
  headers, cumulative ``_bucket`` series with ``+Inf``, ``_sum``/
  ``_count``), scrape-able or just greppable.

Both writes are atomic (tmp + rename) so repeated exports of a growing
registry are deterministic full snapshots, mirroring
``trace.Collector.export_jsonl``.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from .registry import Registry


def _fmt(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _esc(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _label_str(labels: dict, extra: Optional[dict] = None) -> str:
    items = list(labels.items()) + list((extra or {}).items())
    if not items:
        return ""
    body = ",".join(f'{k}="{_esc(v)}"' for k, v in items)
    return "{" + body + "}"


def prometheus_text(registry: Registry) -> str:
    """Render the registry in Prometheus text exposition format."""
    lines: list[str] = []
    seen: set[str] = set()
    by_name: dict[str, list[dict]] = {}
    meta: dict[str, tuple[str, str]] = {}
    for s in registry.collect():
        by_name.setdefault(s["name"], []).append(s)
        meta.setdefault(s["name"], (s["type"], ""))
    with registry._lock:
        helps = {n: m.help for n, m in registry._metrics.items()}
    for name in sorted(by_name):
        kind, _ = meta[name]
        if name not in seen:
            seen.add(name)
            if helps.get(name):
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} {kind}")
        for s in by_name[name]:
            labels = s.get("labels") or {}
            if kind == "histogram":
                cum = 0
                for le, c in s["buckets"].items():
                    cum += c
                    lines.append(
                        f"{name}_bucket{_label_str(labels, {'le': le})} "
                        f"{cum}")
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_fmt(s['sum'])}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{s['count']}")
            else:
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_fmt(s['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def jsonl_lines(registry: Registry) -> list[str]:
    """All metric samples, then all events, one JSON object per line."""
    out = [json.dumps(s, sort_keys=True) for s in registry.collect()]
    out.extend(
        json.dumps({"type": "event", **e}, sort_keys=True)
        for e in registry.events()
    )
    return out


def _atomic_write(path, text: str) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def export_jsonl(registry: Registry, path) -> int:
    lines = jsonl_lines(registry)
    _atomic_write(path, "".join(line + "\n" for line in lines))
    return len(lines)


def export_prometheus(registry: Registry, path) -> None:
    _atomic_write(path, prometheus_text(registry))


def store_metrics(test: dict, registry: Optional[Registry] = None
                  ) -> Optional[list]:
    """Write metrics.jsonl + metrics.prom into the test's store directory
    (next to spans.jsonl); returns the paths or None when the test has no
    store or no registry."""
    reg = registry if registry is not None \
        else test.get("telemetry-registry")
    if reg is None:
        return None
    if not (test.get("name") and test.get("start-time")) or test.get(
            "no-store?"):
        return None
    from .. import store

    pj = store.path_mk(test, "metrics.jsonl")
    export_jsonl(reg, pj)
    pp = store.path_mk(test, "metrics.prom")
    export_prometheus(reg, pp)
    return [str(pj), str(pp)]
