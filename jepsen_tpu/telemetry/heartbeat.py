"""Progress heartbeat for long checks.

Knossos prints "checking... 43%" while its search grinds; the reference
surfaces nothing at all once the checker starts (failed analyses "can
take hours", checker.clj:210-213). The WGL device driver already calls a
``chunk_callback(info)`` after every kernel chunk with ``level`` /
``total_levels`` / ``F`` / ``frontier_max`` / ``count`` / ``wall_s``;
:class:`Heartbeat` is a rate-limited callback that turns those into a
periodic log line with percentage and ETA, and (optionally) mirrors them
into a telemetry registry so a live scrape sees the same numbers.
"""

from __future__ import annotations

import logging
import time as _time
from typing import Optional

LOG = logging.getLogger("jepsen.telemetry")


class Heartbeat:
    """Rate-limited progress reporter usable as a WGL ``chunk_callback``.

    ``total``: fallback level count when the info dict carries none.
    ``interval_s``: minimum seconds between log lines (0 ⇒ every chunk).
    ``registry``: optional telemetry Registry to mirror progress gauges
    into (``wgl_progress_level``, ``wgl_progress_percent``,
    ``wgl_eta_seconds``).
    """

    def __init__(self, total: Optional[int] = None,
                 interval_s: float = 10.0, label: str = "linearizability",
                 log: Optional[logging.Logger] = None, registry=None):
        self.total = total
        self.interval_s = interval_s
        self.label = label
        self.log = log or LOG
        self.registry = registry
        self._t0 = _time.monotonic()
        self._last: Optional[float] = None
        self.beats = 0

    def __call__(self, info: dict) -> None:
        now = _time.monotonic()
        # The first chunk always beats; later ones are rate-limited.
        if self.interval_s and self._last is not None \
                and now - self._last < self.interval_s:
            return
        self._last = now
        self.beats += 1
        level = int(info.get("level") or 0)
        total = int(info.get("total_levels") or self.total or 0)
        wall = float(info.get("wall_s") or (now - self._t0))
        parts = [f"level {level}"]
        pct = None
        eta = None
        if total > 0:
            pct = min(100.0, 100.0 * level / total)
            parts[0] = f"level {level}/{total}"
        if level > 0 and total > level:
            eta = wall / level * (total - level)
            parts.append(f"ETA {eta:.0f}s")
        if info.get("count") is not None:
            parts.append(f"frontier {int(info['count'])}")
        if info.get("F") is not None:
            parts.append(f"F={int(info['F'])}")
        pct_s = f" {pct:.0f}%" if pct is not None else ""
        self.log.info("checking %s...%s (%s, %.1fs elapsed)",
                      self.label, pct_s, ", ".join(parts), wall)
        if self.registry is not None:
            g = self.registry.gauge
            g("wgl_progress_level",
              "Current BFS level of the running check").set(level)
            if pct is not None:
                g("wgl_progress_percent",
                  "Progress of the running check").set(round(pct, 2))
            if eta is not None:
                g("wgl_eta_seconds",
                  "Estimated seconds to verdict at current rate").set(
                      round(eta, 1))
