"""Performance attribution: a roofline profiler over the WGL telemetry.

Round 5's verdict could say the kernel's ``device_util`` was an honest
0.119 but not *which levels* were latency- vs bandwidth-bound. This
module closes that gap host-side, from data the drivers already record
when a registry is injected:

- the stats-variant kernel's per-level ``wgl_level`` rows
  (``[level, frontier, expanded, overflow]`` — ``ops/wgl.py``),
- the per-chunk ``wgl_chunk`` events (levels run, capacity ``F``, wall,
  compile-vs-execute stage),
- the ``wgl.level_byte_floor`` byte model (a provable LOWER bound on a
  level's HBM traffic, enumerated from the kernel's static shapes).

The classification is the roofline argument in time units: at capacity
``F`` a level costs at least ``t_bw = byte_floor(F) / copy_bw`` of pure
streaming and at least ``t_lat`` of fixed overhead (dispatch + the
bitonic sort's pass latency on a mostly-empty frontier — the measured
~0.2 ms/level constant in ``wgl._levels_per_call``). Whichever bound
explains more of the measured per-level wall names the chunk:
**bandwidth-bound** (the byte floor dominates — more capacity or fewer
bytes help) or **latency-bound** (the fixed floor dominates — fewer,
fatter levels help). Compile chunks are attributed separately — their
wall is jit cost, not the chip. Without a measured copy bandwidth the
classifier falls back to frontier occupancy (a frontier filling its
capacity streams real bytes; a near-empty one pays latency).

Also here: opt-in ``jax.profiler`` trace capture + device
``memory_stats()`` watermarks (the ``--profile`` CLI flag), the
``profile.json`` store artifact the ``/profile`` web page renders, and
attribution for the batched pipeline (per-rung occupancy — why a member
escalated) and the frontier-sharded driver (mode-aware exchange bytes,
``exchange_bytes`` with the legacy ``allgather_bytes`` alias — the
interconnect's share of the level's traffic). See docs/profiling.md.
"""

from __future__ import annotations

import contextlib
import json
import os
import time as _time
from typing import Any, Callable, Optional

from .registry import Registry

# Fixed per-level latency floor (seconds): dispatch + loop overhead at
# the 2x unroll — the constant term of wgl._levels_per_call's measured
# per-level cost model.
LATENCY_FLOOR_S = 2.0e-4

# Occupancy fallback threshold for the no-measured-bandwidth case: a
# chunk whose mean frontier fills less than this fraction of F is
# latency-bound (its levels are mostly fixed overhead).
OCCUPANCY_THRESHOLD = 0.25


def _byte_floor_fn(plan, byte_floor, **floor_kw) -> Optional[Callable]:
    """Resolve the bytes-per-level model: an explicit callable wins,
    else wrap ``wgl.level_byte_floor`` over the plan. Context kwargs
    (``sharded``, ``exchange``) are forwarded to explicit callables
    that accept them; older single-argument callables keep working."""
    if byte_floor is not None:
        if not floor_kw:
            return byte_floor
        # Decide by SIGNATURE, not by catching TypeError from the call
        # — a TypeError raised inside the callable must propagate, not
        # silently re-invoke it without the context kwargs.
        import inspect

        try:
            params = inspect.signature(byte_floor).parameters
            accepts_kw = any(
                p.kind == inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            ) or all(k in params for k in floor_kw)
        except (TypeError, ValueError):  # builtins/odd callables
            accepts_kw = False
        if accepts_kw:
            return lambda F: byte_floor(F, **floor_kw)
        return byte_floor
    if plan is None:
        return None
    from ..ops import wgl

    return lambda F: wgl.level_byte_floor(plan, F, **floor_kw)


def _classify(per_level_s: float, floor_bytes: Optional[int],
              copy_bw_gbs: Optional[float], occupancy: Optional[float],
              latency_floor_s: float) -> tuple[str, Optional[float], float]:
    """(bound, util, latency_share) for one executing chunk."""
    latency_share = min(1.0, latency_floor_s / per_level_s) \
        if per_level_s > 0 else 0.0
    util = None
    if floor_bytes and copy_bw_gbs:
        t_bw = floor_bytes / (copy_bw_gbs * 1e9)
        util = min(1.0, t_bw / per_level_s) if per_level_s > 0 else 0.0
        bound = "bandwidth" if util >= latency_share else "latency"
    elif occupancy is not None:
        bound = "bandwidth" if occupancy >= OCCUPANCY_THRESHOLD \
            else "latency"
    else:
        bound = "latency" if latency_share >= 0.5 else "indeterminate"
    return bound, util, latency_share


def attribute(registry: Registry, plan=None,
              byte_floor: Optional[Callable[[int], int]] = None,
              copy_bw_gbs: Optional[float] = None,
              latency_floor_s: float = LATENCY_FLOOR_S,
              max_chunks: int = 60) -> dict:
    """Fold a run's registry into a performance-attribution map.

    Returns ``{"device": ..., "batch": ..., "sharded": ...}`` — each
    section present only when its events exist. ``plan`` (a
    ``wgl.DevicePlan``) or ``byte_floor(F) -> bytes`` enables the byte
    model; ``copy_bw_gbs`` (bench.py's measured on-device copy
    bandwidth) enables achieved-GB/s and the measured-roofline
    classification. ``max_chunks`` bounds the per-chunk list in the
    output (head + tail kept, middle elided) so bench JSON stays small.
    """
    out: dict = {}
    dev = _attribute_device(registry, plan, byte_floor, copy_bw_gbs,
                            latency_floor_s, max_chunks)
    if dev is not None:
        out["device"] = dev
    batch = _attribute_batch(registry)
    if batch is not None:
        out["batch"] = batch
    sharded = _attribute_sharded(registry, plan, byte_floor)
    if sharded is not None:
        out["sharded"] = sharded
    util = _attribute_utilization(registry)
    if util is not None:
        out["utilization"] = util
    return out


def _attribute_utilization(registry) -> Optional[dict]:
    """Per-device busy timelines + idle-gap attribution (see
    ``telemetry.utilization``). The import is gated on chunk events
    actually existing: with telemetry disabled (or nothing recorded)
    the utilization module is never imported — the off-path pin
    tests/test_telemetry.py holds."""
    if not any(registry.events(n) for n in
               ("wgl_chunk", "wgl_batch_chunk", "wgl_sharded_chunk")):
        return None
    from . import utilization

    return utilization.reconstruct(registry)


def _attribute_device(registry, plan, byte_floor, copy_bw_gbs,
                      latency_floor_s, max_chunks) -> Optional[dict]:
    chunks_ev = registry.events("wgl_chunk")
    if not chunks_ev:
        return None
    floor = _byte_floor_fn(plan, byte_floor)
    # Per-level rows grouped by capacity: escalation retries rewrite the
    # same level number at a larger F, so the (F, level-range) pair is
    # the only unambiguous join key for a chunk's levels.
    by_F: dict[int, list[dict]] = {}
    for e in registry.events("wgl_level"):
        by_F.setdefault(int(e["F"]), []).append(e)

    chunks = []
    for ev in chunks_ev:
        F = int(ev["F"])
        lvl0, lvl = int(ev["level0"]), int(ev["level"])
        wall = float(ev["wall_s"])
        levels = max(lvl - lvl0, 0)
        c: dict = {"F": F, "level0": lvl0, "level": lvl,
                   "levels": levels, "wall_s": round(wall, 4),
                   "stage": ev.get("stage", "execute")}
        rows = [e for e in by_F.get(F, ())
                if lvl0 < int(e["level"]) <= lvl]
        occ = None
        if rows:
            occ = sum(int(e["frontier"]) for e in rows) / (len(rows) * F)
            c["occupancy"] = round(occ, 4)
            c["frontier_mean"] = round(
                sum(int(e["frontier"]) for e in rows) / len(rows), 1)
            c["expanded_total"] = sum(int(e["expanded"]) for e in rows)
        if levels == 0:
            # An attempt that completed no level: an overflow awaiting
            # escalation (or an instant accept) — wall is real, but a
            # per-level rate is meaningless.
            c["bound"] = ("compile" if c["stage"] == "compile"
                          else "overflow")
            chunks.append(c)
            continue
        per_level = wall / levels
        c["per_level_ms"] = round(per_level * 1e3, 4)
        fb = int(floor(F)) if floor is not None else None
        if fb is not None:
            c["bytes_floor"] = fb * levels
            if wall > 0:
                c["achieved_gbs"] = round(fb * levels / wall / 1e9, 2)
        if c["stage"] == "compile":
            # First chunk after a fresh build: the wall is jit cost.
            c["bound"] = "compile"
        else:
            bound, util, lat = _classify(per_level, fb, copy_bw_gbs, occ,
                                         latency_floor_s)
            c["bound"] = bound
            c["latency_share"] = round(lat, 4)
            if util is not None:
                c["util"] = round(util, 4)
        chunks.append(c)

    # Rung (capacity) aggregation + run summary.
    rungs: dict[int, dict] = {}
    totals = {"wall_s": 0.0, "levels": 0, "bytes_floor": 0}
    # Executing chunks only (a compile chunk's wall conflates jit cost
    # with its levels' execution, so BOTH its wall and its bytes stay
    # out of the achieved-GB/s figure).
    exec_totals = {"wall_s": 0.0, "bytes_floor": 0}
    bound_wall: dict[str, float] = {}
    for c in chunks:
        r = rungs.setdefault(c["F"], {
            "F": c["F"], "chunks": 0, "levels": 0, "wall_s": 0.0,
            "bytes_floor": 0, "_occ": [], "_bw": {}})
        r["chunks"] += 1
        r["levels"] += c["levels"]
        r["wall_s"] += c["wall_s"]
        r["bytes_floor"] += c.get("bytes_floor") or 0
        if "occupancy" in c:
            r["_occ"].append(c["occupancy"])
        r["_bw"][c["bound"]] = r["_bw"].get(c["bound"], 0.0) + c["wall_s"]
        totals["wall_s"] += c["wall_s"]
        totals["levels"] += c["levels"]
        totals["bytes_floor"] += c.get("bytes_floor") or 0
        if c["bound"] != "compile":
            exec_totals["wall_s"] += c["wall_s"]
            exec_totals["bytes_floor"] += c.get("bytes_floor") or 0
        bound_wall[c["bound"]] = bound_wall.get(c["bound"], 0.0) \
            + c["wall_s"]
    rung_list = []
    for F in sorted(rungs):
        r = rungs[F]
        occ = r.pop("_occ")
        bw = r.pop("_bw")
        if occ:
            r["occupancy_mean"] = round(sum(occ) / len(occ), 4)
        r["wall_s"] = round(r["wall_s"], 4)
        if r["bytes_floor"] and r["wall_s"] > 0:
            r["achieved_gbs"] = round(
                r["bytes_floor"] / r["wall_s"] / 1e9, 2)
        r["bound"] = max(bw, key=bw.get)
        rung_list.append(r)

    summary: dict = {
        "levels": totals["levels"],
        "wall_s": round(totals["wall_s"], 4),
        "bound_wall_s": {b: round(w, 4)
                         for b, w in sorted(bound_wall.items())},
        "copy_bw_gbs": copy_bw_gbs,
    }
    hot = {b: w for b, w in bound_wall.items()
           if b in ("latency", "bandwidth")}
    if hot:
        summary["dominant_bound"] = max(hot, key=hot.get)
    if totals["bytes_floor"]:
        summary["bytes_floor_total"] = totals["bytes_floor"]
    if exec_totals["bytes_floor"] and exec_totals["wall_s"] > 0:
        summary["achieved_gbs"] = round(
            exec_totals["bytes_floor"] / exec_totals["wall_s"] / 1e9, 2)
        if copy_bw_gbs:
            summary["util"] = round(
                exec_totals["bytes_floor"] / exec_totals["wall_s"]
                / (copy_bw_gbs * 1e9), 4)

    if len(chunks) > max_chunks:
        head = chunks[: max_chunks // 2]
        tail = chunks[-(max_chunks - len(head)):]
        summary["chunks_elided"] = len(chunks) - len(head) - len(tail)
        chunks = head + tail
    return {"chunks": chunks, "rungs": rung_list, "summary": summary}


def _attribute_batch(registry) -> Optional[dict]:
    """Per-rung occupancy of the batched escalation pipeline: WHY a
    member escalated is visible as its rung's final occupancy (members
    still searching when the rung's ladder moved on) plus the rebatch
    events' member counts."""
    chunk_ev = registry.events("wgl_batch_chunk")
    rung_ev = registry.events("wgl_batch_rung")
    rebatch_ev = registry.events("wgl_rebatch")
    if not (chunk_ev or rung_ev):
        return None
    by_F: dict[int, list[dict]] = {}
    for e in chunk_ev:
        by_F.setdefault(int(e["F"]), []).append(e)
    rungs = []
    for e in rung_ev:
        F = int(e["F"])
        r = {k: e[k] for k in
             ("F", "members", "calls", "wall_s", "decided", "overflowed",
              "lossy") if k in e}
        evs = by_F.get(F, ())
        if evs:
            occs = [int(x["active"]) / max(int(x["batch"]), 1)
                    for x in evs]
            r["occupancy_mean"] = round(sum(occs) / len(occs), 4)
            r["occupancy_final"] = round(occs[-1], 4)
        rungs.append(r)
    if not rungs:  # chunk events only (older recordings)
        for F in sorted(by_F):
            evs = by_F[F]
            occs = [int(x["active"]) / max(int(x["batch"]), 1)
                    for x in evs]
            rungs.append({"F": F, "calls": len(evs),
                          "occupancy_mean": round(sum(occs) / len(occs), 4),
                          "occupancy_final": round(occs[-1], 4)})
    out: dict = {"rungs": rungs}
    if rebatch_ev:
        out["escalations"] = [
            {"from_F": e["from_F"], "to_F": e["to_F"],
             "members": e["members"]} for e in rebatch_ev]
    return out


def _attribute_sharded(registry, plan, byte_floor) -> Optional[dict]:
    """Interconnect share of the frontier-sharded search: the analytic
    exchange bytes (mode-aware — the hash-routed all_to_all or the
    legacy replicated all_gather) vs the per-shard compute byte floor —
    how much of the level's traffic is the exchange itself."""
    ev = registry.events("wgl_sharded_chunk")
    if not ev:
        return None
    # Exchange mode of the run (events predating the field are the
    # legacy all_gather recordings).
    mode = next((e["exchange"] for e in ev if "exchange" in e),
                "allgather")
    floor = _byte_floor_fn(plan, byte_floor, sharded=True, exchange=mode)
    ex_total = 0
    floor_total = 0
    prev_level = 0
    chunks = []
    for e in ev:
        lvl = int(e["level"])
        levels = max(lvl - prev_level, 0)
        prev_level = lvl
        c = {"level": lvl, "F": int(e["F"]),
             "n_shards": int(e["n_shards"]),
             "wall_s": e.get("wall_s")}
        # New field first; back-compat with recordings that only carry
        # the all_gather-named alias.
        ex = e.get("exchange_bytes", e.get("allgather_bytes"))
        if ex is not None:
            ex_total += int(ex)
            c["exchange_bytes"] = int(ex)
        for k in ("count_max", "count_min"):
            if k in e:
                c[k] = int(e[k])
        if floor is not None:
            fb = int(floor(int(e["F"]))) * levels
            floor_total += fb
            c["bytes_floor"] = fb
        chunks.append(c)
    if not ex_total:
        # Fall back to the run counters (older recordings carry only
        # the unlabeled all_gather total; newer ones label the
        # exchange counter by mode).
        s = registry.summary()
        ex_total = int(sum(
            v for k, v in s.items()
            if k.startswith("wgl_exchange_bytes_total"))) or \
            int(s.get("wgl_allgather_bytes_total", 0))
    out: dict = {"exchange": mode,
                 "chunks": chunks[-60:],
                 "interconnect": {"exchange_bytes_total": ex_total,
                                  # legacy alias, kept one layer deep so
                                  # pre-partitioning consumers keep
                                  # reading a number
                                  "allgather_bytes_total": ex_total}}
    if ex_total and floor_total:
        out["interconnect"]["share_of_traffic"] = round(
            ex_total / (ex_total + floor_total), 4)
        out["interconnect"]["compute_bytes_floor_total"] = floor_total
    return out


# ---------------------------------------------------------------------------
# Opt-in on-device capture (--profile): jax.profiler trace + HBM marks


@contextlib.contextmanager
def trace_capture(outdir):
    """Capture a ``jax.profiler`` trace into ``outdir`` for the body;
    yields the directory, or None when the profiler is unavailable (no
    jax, trace already running, unsupported backend). Never raises —
    profiling must not take the run down."""
    started = False
    try:
        import jax

        os.makedirs(str(outdir), exist_ok=True)
        jax.profiler.start_trace(str(outdir))
        started = True
    except Exception:
        pass
    try:
        yield str(outdir) if started else None
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass


def memory_watermarks() -> list[dict]:
    """Per-device ``memory_stats()`` snapshot (bytes_in_use /
    peak_bytes_in_use watermarks where the backend reports them); empty
    when jax or the stats are unavailable."""
    try:
        import jax

        out = []
        for d in jax.devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                pass
            if stats:
                out.append({
                    "device": str(d),
                    **{k: int(v) for k, v in sorted(stats.items())
                       if isinstance(v, (int, float))}})
        return out
    except Exception:
        return []


def store_profile(test: dict, registry: Optional[Registry] = None,
                  plan=None, copy_bw_gbs: Optional[float] = None,
                  extra: Optional[dict] = None) -> Optional[str]:
    """Write ``profile.json`` (attribution + memory watermarks) into the
    test's store directory next to metrics.jsonl; None when the test has
    no store or no registry."""
    reg = registry if registry is not None \
        else test.get("telemetry-registry")
    if reg is None:
        return None
    if not (test.get("name") and test.get("start-time")) or test.get(
            "no-store?"):
        return None
    from .. import store

    doc = {
        "generated_at": _time.time(),
        "attribution": attribute(reg, plan=plan, copy_bw_gbs=copy_bw_gbs),
        "memory_watermarks": memory_watermarks(),
    }
    if extra:
        doc.update(extra)
    path = store.path_mk(test, "profile.json")
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, default=str)
    os.replace(tmp, path)
    return str(path)
