"""Pure-functional operation-scheduling DSL.

The reference's generator system (jepsen/src/jepsen/generator.clj) models a
schedule as an immutable value with two operations::

    op(gen, test, ctx)        -> None | PENDING | (op-map, gen')
    update(gen, test, ctx, e) -> gen'

``None`` means exhausted; ``PENDING`` means "nothing to do yet, ask again";
otherwise the generator returns the next operation plus its successor state.
``update`` folds scheduler events (invocations and completions) back into
the generator (generator.clj:381-386). The *context* carries the logical
clock, the set of free worker threads, and the thread→process map
(generator.clj:433-444).

Python value types are generators too (generator.clj:525-600 extends the
protocol over maps/seqs/fns/delays):

- ``None``      — the empty generator
- ``dict``      — yields itself once, with :process/:time/:type filled from
                  the context (``fill_in_op``, generator.clj:511-523)
- ``list``/``tuple`` — a sequence of generators, run till each is exhausted;
                  updates go to the head
- callables     — called with (test, ctx) (or no args) to produce a fresh
                  generator each time; an endless stream until it returns None

All the reference combinators are provided under their reference names
(trailing underscore where Python collides): validate, friendly_exceptions,
trace, map_/f_map, filter_, on_update, on_threads/on, any_, each_thread,
reserve, clients, nemesis, mix, limit, once, log_, repeat_, process_limit,
time_limit, stagger, delay, sleep, synchronize, phases, then, until_ok,
flip_flop, concat (generator.clj:652-1428).

Randomness goes through a module RNG so the deterministic simulator
(`jepsen_tpu.generator.sim`) can pin it (the reference's
``with-fixed-rand-int``, generator/test.clj:30-47).
"""

from __future__ import annotations

import inspect
import logging
import functools
import random as _random
import threading
from typing import Any, Callable, Iterable, Optional

LOG = logging.getLogger("jepsen.generator")

from ..history import FAIL, INFO, INVOKE, NEMESIS, OK  # single source of truth

# Generator-only op types (interpreted by the scheduler, never in history).
SLEEP, LOG_TYPE = "sleep", "log"


class _Pending:
    __slots__ = ()

    def __repr__(self) -> str:
        return ":pending"


PENDING = _Pending()


# ---------------------------------------------------------------------------
# RNG indirection (pinnable for deterministic simulation)

_rng_local = threading.local()


def _rng() -> _random.Random:
    r = getattr(_rng_local, "rng", None)
    return r if r is not None else _random


class fixed_rand:
    """Context manager pinning this thread's generator RNG to a seed."""

    def __init__(self, seed: int):
        self.seed = seed

    def __enter__(self):
        self.prev = getattr(_rng_local, "rng", None)
        _rng_local.rng = _random.Random(self.seed)
        return self

    def __exit__(self, *exc):
        _rng_local.rng = self.prev
        return False


def rand_int(n: int) -> int:
    return _rng().randrange(n) if n > 0 else 0


def rand_float(x: float) -> float:
    return _rng().random() * x


def secs_to_nanos(s: float) -> int:
    return int(s * 1e9)


# ---------------------------------------------------------------------------
# Context


class Context:
    """Scheduler context: logical time (ns), free threads, thread→process.

    Threads are ints 0..concurrency-1 plus the string "nemesis"
    (generator.clj:433-444).
    """

    __slots__ = ("time", "free_threads", "workers", "_flist", "_restrict")

    def __init__(self, time: int, free_threads: frozenset, workers: dict):
        self.time = time
        self.free_threads = free_threads
        self.workers = workers
        # Lazy per-instance caches (sound: contexts are immutable).
        self._flist = None
        self._restrict = None

    def with_(self, time=None, free_threads=None, workers=None) -> "Context":
        return Context(
            self.time if time is None else time,
            self.free_threads if free_threads is None else frozenset(free_threads),
            self.workers if workers is None else workers,
        )

    def free_thread_list(self) -> tuple:
        # Deterministic order: numeric threads sorted, nemesis last.
        # Tuple, not list: the value is cached, so it must be immutable.
        # Split by type and sort without a key fn: a keyed sort over
        # ~concurrency threads ran every scheduler step and dominated
        # high-concurrency interpreter profiles.
        if self._flist is None:
            ints = []
            names = []
            for t in self.free_threads:
                (ints if type(t) is int else names).append(t)
            ints.sort()
            names.sort(key=str)
            self._flist = tuple(ints) + tuple(names)
        return self._flist

    def __repr__(self) -> str:
        return (
            f"<ctx t={self.time} free={sorted(map(str, self.free_threads))} "
            f"workers={self.workers}>"
        )


def context(test: dict) -> Context:
    """Build the initial context for a test map (generator.clj:433-444):
    threads = nemesis + concurrency ints; every thread starts free, process
    = thread."""
    threads = [NEMESIS] + list(range(test.get("concurrency", 0)))
    return Context(0, frozenset(threads), {t: t for t in threads})


def free_processes(ctx: Context) -> list:
    return [ctx.workers[t] for t in ctx.free_thread_list()]


def some_free_process(ctx: Context):
    free = ctx.free_thread_list()
    if not free:
        return None
    return ctx.workers[free[rand_int(len(free))]]


def all_processes(ctx: Context) -> list:
    return list(ctx.workers.values())


def all_threads(ctx: Context) -> list:
    return list(ctx.workers.keys())


def process_to_thread(ctx: Context, process):
    for t, p in ctx.workers.items():
        if p == process:
            return t
    return None


def thread_to_process(ctx: Context, thread):
    return ctx.workers.get(thread)


def next_process(ctx: Context, thread):
    """Process id for a thread whose process just crashed: old process +
    number of numeric processes (generator.clj:499-507). Use with the
    global context only."""
    if isinstance(thread, int):
        return ctx.workers[thread] + sum(
            1 for p in all_processes(ctx) if isinstance(p, int)
        )
    return thread


def fill_in_op(op: dict, ctx: Context):
    """Fill :time/:process/:type from context; PENDING if no process free
    (generator.clj:511-523)."""
    p = some_free_process(ctx)
    if p is None:
        return PENDING
    out = dict(op)
    # Like the reference's (nil? ...) checks: an explicit None means absent.
    if out.get("time") is None:
        out["time"] = ctx.time
    if out.get("process") is None:
        out["process"] = p
    if out.get("type") is None:
        out["type"] = INVOKE
    return out


# ---------------------------------------------------------------------------
# Protocol dispatch


class Generator:
    """Base class for combinator generators."""

    def op(self, test: dict, ctx: Context):
        raise NotImplementedError

    def update(self, test: dict, ctx: Context, event: dict):
        return self

    def __repr__(self) -> str:
        fields = ", ".join(
            f"{k}={getattr(self, k)!r}" for k in getattr(self, "__slots__", ())[:3]
        )
        return f"<{type(self).__name__} {fields}>"


def op(gen, test: dict, ctx: Context):
    """Protocol dispatch over generator-ish values (generator.clj:525-600)."""
    while True:
        if gen is None:
            return None
        if isinstance(gen, Generator):
            return gen.op(test, ctx)
        if isinstance(gen, dict):
            filled = fill_in_op(gen, ctx)
            if filled is PENDING:
                return (PENDING, gen)
            return (filled, None)
        if isinstance(gen, (list, tuple)):
            seq = list(gen)
            if not seq:
                return None
            res = op(seq[0], test, ctx)
            if res is None:
                gen = seq[1:]
                continue
            o, g1 = res
            rest = seq[1:]
            return (o, [g1] + rest if rest else g1)
        if callable(gen):
            x = _call_gen_fn(gen, test, ctx)
            if x is None:
                return None
            if type(x) is dict:
                # Fast path for the overwhelmingly common fn->op-map
                # case: skip the [x, gen] list round trip (the list
                # branch would return (filled, [None, gen]), which the
                # next call walks back to plain ``gen`` anyway).
                filled = fill_in_op(x, ctx)
                if filled is PENDING:
                    return (PENDING, [x, gen])
                return (filled, gen)
            return op([x, gen], test, ctx)
        raise TypeError(f"not a generator: {gen!r}")


def update(gen, test: dict, ctx: Context, event: dict):
    # Identity convention (throughput-critical): every combinator's
    # update returns ``self``/``gen`` UNCHANGED when the wrapped
    # generator came back identical, so a no-op update of a deep stack
    # allocates nothing. Two updates run per completed op; the wrapper
    # churn dominated interpreter throughput before this.
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.update(test, ctx, event)
    if isinstance(gen, dict):
        return gen
    if isinstance(gen, (list, tuple)):
        if not gen:
            return None
        g2 = update(gen[0], test, ctx, event)
        if g2 is gen[0]:
            return gen
        return [g2, *gen[1:]]
    if callable(gen):
        return gen
    raise TypeError(f"not a generator: {gen!r}")


# Keyed by __code__ so closure instances share one entry and the cache
# doesn't pin per-test closures (and their captured state) forever.
_ARITY_CACHE: dict = {}


def _arity(f) -> int:
    try:
        sig = inspect.signature(f)
        return len(
            [
                p
                for p in sig.parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                and p.default is p.empty
            ]
        )
    except (ValueError, TypeError):
        return 0


def _call_gen_fn(f, test, ctx):
    code = getattr(f, "__code__", None)
    if code is not None:
        nargs = _ARITY_CACHE.get(code)
        if nargs is None:
            nargs = _ARITY_CACHE[code] = _arity(f)
    else:
        nargs = _arity(f)
    return f(test, ctx) if nargs >= 2 else f()


# ---------------------------------------------------------------------------
# Validation & error wrapping


class InvalidOp(Exception):
    pass


_VALID_TYPES = {INVOKE, INFO, SLEEP, LOG_TYPE}


class Validate(Generator):
    """Checks well-formedness of emitted ops (generator.clj:602-656)."""

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        if not (isinstance(res, tuple) and len(res) == 2):
            raise InvalidOp(f"generator should return an (op, gen') pair, got {res!r}")
        o, g = res
        if o is not PENDING:
            problems = []
            if not isinstance(o, dict):
                problems.append("op should be either PENDING or a dict")
            else:
                if o.get("type") not in _VALID_TYPES:
                    problems.append(
                        f":type should be one of {sorted(_VALID_TYPES)}, got {o.get('type')!r}"
                    )
                if not isinstance(o.get("time"), (int, float)):
                    problems.append(":time should be a number")
                if o.get("process") is None:
                    problems.append("no :process")
                elif o.get("process") not in free_processes(ctx):
                    problems.append(f"process {o.get('process')!r} is not free")
            if problems:
                raise InvalidOp(
                    "generator produced an invalid op: "
                    + f"{o!r}; problems: {problems}; context: {ctx!r}"
                )
        return (o, Validate(g))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Validate(g2)


validate = Validate


class FriendlyExceptions(Generator):
    """Wraps errors from the underlying generator with the context that
    produced them (generator.clj:658-698)."""

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        try:
            res = op(self.gen, test, ctx)
        except Exception as e:
            raise RuntimeError(
                f"generator threw {type(e).__name__} when asked for an op in ctx {ctx!r}"
            ) from e
        if res is None:
            return None
        o, g = res
        return (o, FriendlyExceptions(g))

    def update(self, test, ctx, event):
        try:
            g2 = update(self.gen, test, ctx, event)
            return self if g2 is self.gen else FriendlyExceptions(g2)
        except Exception as e:
            raise RuntimeError(
                f"generator threw {type(e).__name__} when updated with {event!r}"
            ) from e


friendly_exceptions = FriendlyExceptions


class Trace(Generator):
    """Logs every op/update through this point (generator.clj:700-760)."""

    __slots__ = ("k", "gen")

    def __init__(self, k, gen):
        self.k = k
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        LOG.info("%s op -> %r", self.k, None if res is None else res[0])
        if res is None:
            return None
        return (res[0], Trace(self.k, res[1]))

    def update(self, test, ctx, event):
        LOG.info("%s update <- %r", self.k, event)
        return Trace(self.k, update(self.gen, test, ctx, event))


trace = Trace


# ---------------------------------------------------------------------------
# Transformations


class Map(Generator):
    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g = res
        return (o if o is PENDING else self.f(o), Map(self.f, g))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Map(self.f, g2)


def map_(f, gen):
    """Transform each emitted op with f (generator.clj:762-768)."""
    return Map(f, gen)


def f_map(fm: dict, gen):
    """Rename op :f fields through the map fm (generator.clj:770-776) —
    used when composing nemesis packages."""

    def transform(o):
        o = dict(o)
        o["f"] = fm.get(o.get("f"), o.get("f"))
        return o

    return Map(transform, gen)


class Filter(Generator):
    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        gen = self.gen
        while True:
            res = op(gen, test, ctx)
            if res is None:
                return None
            o, g = res
            if o is PENDING or self.f(o):
                return (o, Filter(self.f, g))
            gen = g

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Filter(self.f, g2)


def filter_(f, gen):
    """Pass only ops matching f; PENDING passes through
    (generator.clj:779-798)."""
    return Filter(f, gen)


class OnUpdate(Generator):
    """Custom update handler: f(this, test, ctx, event) -> gen'
    (generator.clj:808-823)."""

    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return (res[0], OnUpdate(self.f, res[1]))

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


on_update = OnUpdate


# ---------------------------------------------------------------------------
# Thread routing


# (pred, id(workers)) -> (workers ref, allowed thread set, restricted
# workers dict). Thread ids are fixed for a run and workers dicts are
# immutable (replaced wholesale on process bumps), so the Python-level
# pred sweep runs once per (pred, workers-generation) instead of per
# scheduler step; holding the dict ref keeps the id stable. Bounded by
# a clear-on-overflow (generations = info-op count, normally tiny).
_RESTRICT_MEMO: dict = {}
_RESTRICT_MEMO_MAX = 4096


def on_threads_context(pred: Callable[[Any], bool], ctx: Context) -> Context:
    """Restrict a context to threads satisfying pred (generator.clj:826-843).

    Memoized per (ctx, pred): a deep generator stack restricts the same
    immutable context several times per scheduler step, which dominated
    interpreter throughput before caching. The pred sweep itself is
    additionally memoized per workers-generation (see _RESTRICT_MEMO),
    so steady-state restriction is one C-level set intersection."""
    cache = ctx._restrict
    if cache is None:
        cache = ctx._restrict = {}
    try:
        hit = cache.get(pred)
    except TypeError:  # unhashable pred: build uncached
        hit = None
        cache = None
    if hit is None:
        ent = None
        key = (pred, id(ctx.workers)) if cache is not None else None
        if key is not None:
            ent = _RESTRICT_MEMO.get(key)
            if ent is not None and ent[0] is not ctx.workers:
                ent = None
        if ent is None:
            allowed = frozenset(t for t in ctx.workers if pred(t))
            rworkers = {t: p for t, p in ctx.workers.items()
                        if t in allowed}
            ent = (ctx.workers, allowed, rworkers)
            if key is not None:
                if len(_RESTRICT_MEMO) > _RESTRICT_MEMO_MAX:
                    _RESTRICT_MEMO.clear()
                _RESTRICT_MEMO[key] = ent
        _, allowed, rworkers = ent
        hit = ctx.with_(free_threads=ctx.free_threads & allowed,
                        workers=rworkers)
        if cache is not None:
            cache[pred] = hit
    return hit


class OnThreads(Generator):
    """Restrict the wrapped generator to threads satisfying pred
    (generator.clj:845-864)."""

    __slots__ = ("pred", "gen")

    def __init__(self, pred, gen):
        self.pred = pred
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, on_threads_context(self.pred, ctx))
        if res is None:
            return None
        return (res[0], OnThreads(self.pred, res[1]))

    def update(self, test, ctx, event):
        if self.pred(process_to_thread(ctx, event.get("process"))):
            g2 = update(self.gen, test,
                        on_threads_context(self.pred, ctx), event)
            return self if g2 is self.gen else OnThreads(self.pred, g2)
        return self


def rand_int_seq(seed: Optional[int] = None):
    """A reproducible infinite stream of random ints for a seed
    (generator.clj:445-452)."""
    rng = _random.Random(seed if seed is not None else rand_int(2**31))
    while True:
        yield rng.getrandbits(63)


def on_threads(pred, gen):
    return OnThreads(pred, gen)




# `on` is the reference's short alias for on-threads (generator.clj:856).
on = on_threads


def soonest_op_map(m1: Optional[dict], m2: Optional[dict]) -> Optional[dict]:
    """Pick whichever {op, ..., weight} map happens sooner; break time ties
    randomly, weighted (generator.clj:866-908)."""
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    o1, o2 = m1["op"], m2["op"]
    if o1 is PENDING:
        return m2
    if o2 is PENDING:
        return m1
    t1, t2 = o1.get("time"), o2.get("time")
    if t1 == t2:
        w1 = m1.get("weight", 1)
        w2 = m2.get("weight", 1)
        out = dict(m1 if rand_int(w1 + w2) < w1 else m2)
        out["weight"] = w1 + w2
        return out
    return m1 if t1 < t2 else m2


class Any(Generator):
    """Ops from whichever sub-generator is soonest; updates to all
    (generator.clj:910-934)."""

    __slots__ = ("gens",)

    def __init__(self, gens):
        self.gens = list(gens)

    def op(self, test, ctx):
        soonest = None
        for i, g in enumerate(self.gens):
            res = op(g, test, ctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "i": i}
                )
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return (soonest["op"], Any(gens))

    def update(self, test, ctx, event):
        gens = [update(g, test, ctx, event) for g in self.gens]
        if all(g2 is g for g2, g in zip(gens, self.gens)):
            return self
        return Any(gens)


def any_(*gens):
    if not gens:
        return None
    if len(gens) == 1:
        return gens[0]
    return Any(gens)


class EachThread(Generator):
    """An independent copy of the generator per thread; each copy sees a
    single-thread context (generator.clj:936-988)."""

    __slots__ = ("fresh", "gens")

    def __init__(self, fresh, gens=None):
        self.fresh = fresh
        self.gens = gens or {}

    def _thread_ctx(self, ctx, thread):
        return ctx.with_(
            free_threads=frozenset([thread]),
            workers={thread: ctx.workers[thread]},
        )

    def op(self, test, ctx):
        soonest = None
        for thread in ctx.free_thread_list():
            g = self.gens.get(thread, self.fresh)
            res = op(g, test, self._thread_ctx(ctx, thread))
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "thread": thread}
                )
        if soonest is not None:
            gens = dict(self.gens)
            gens[soonest["thread"]] = soonest["gen"]
            return (soonest["op"], EachThread(self.fresh, gens))
        if len(ctx.free_threads) != len(ctx.workers):
            return (PENDING, self)  # busy thread may still want ops later
        return None  # every thread exhausted

    def update(self, test, ctx, event):
        thread = process_to_thread(ctx, event.get("process"))
        if thread is None:
            return self
        g = self.gens.get(thread, self.fresh)
        tctx = ctx.with_(
            free_threads=frozenset(t for t in ctx.free_threads if t == thread),
            workers={thread: event.get("process")},
        )
        g2 = update(g, test, tctx, event)
        if g2 is g and thread in self.gens:
            return self
        gens = dict(self.gens)
        gens[thread] = g2
        return EachThread(self.fresh, gens)


each_thread = EachThread


@functools.lru_cache(maxsize=None)
def _in_set_pred(s: frozenset):
    """A stable membership predicate per thread set, so
    on_threads_context's identity-keyed memo can hit (the sets are the
    handful of reserve/group ranges a test declares, so the cache stays
    tiny)."""
    return lambda t: t in s


@functools.lru_cache(maxsize=None)
def _not_in_set_pred(s: frozenset):
    return lambda t: t not in s


class Reserve(Generator):
    """Dedicated thread ranges per generator + a default
    (generator.clj:990-1070)."""

    __slots__ = ("ranges", "all_ranges", "gens")

    def __init__(self, ranges, all_ranges, gens):
        self.ranges = ranges  # list[frozenset[int]]
        self.all_ranges = all_ranges
        self.gens = gens  # len(ranges)+1, last = default

    def op(self, test, ctx):
        soonest = None
        for i, threads in enumerate(self.ranges):
            rctx = on_threads_context(_in_set_pred(threads), ctx)
            res = op(self.gens[i], test, rctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest,
                    {"op": res[0], "gen": res[1], "weight": len(threads), "i": i},
                )
        dctx = on_threads_context(_not_in_set_pred(self.all_ranges), ctx)
        res = op(self.gens[-1], test, dctx)
        if res is not None:
            soonest = soonest_op_map(
                soonest,
                {
                    "op": res[0],
                    "gen": res[1],
                    "weight": len(dctx.workers),
                    "i": len(self.ranges),
                },
            )
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return (soonest["op"], Reserve(self.ranges, self.all_ranges, gens))

    def update(self, test, ctx, event):
        thread = process_to_thread(ctx, event.get("process"))
        i = len(self.ranges)
        for j, r in enumerate(self.ranges):
            if thread in r:
                i = j
                break
        g2 = update(self.gens[i], test, ctx, event)
        if g2 is self.gens[i]:
            return self
        gens = list(self.gens)
        gens[i] = g2
        return Reserve(self.ranges, self.all_ranges, gens)


def reserve(*args):
    """reserve(5, write_gen, 10, cas_gen, read_gen): first 5 threads get
    write_gen, next 10 cas_gen, the rest the default
    (generator.clj:1036-1070)."""
    *pairs, default = args
    assert default is not None
    assert len(pairs) % 2 == 0
    ranges, gens = [], []
    n = 0
    for i in range(0, len(pairs), 2):
        cnt, g = pairs[i], pairs[i + 1]
        ranges.append(frozenset(range(n, n + cnt)))
        gens.append(g)
        n += cnt
    all_ranges = frozenset().union(*ranges) if ranges else frozenset()
    return Reserve(ranges, all_ranges, gens + [default])


def clients(client_gen, nemesis_gen=None):
    """Route clients to client_gen (and optionally nemesis to nemesis_gen)
    (generator.clj:1073-1083)."""
    if nemesis_gen is None:
        return on_threads(lambda t: t != NEMESIS, client_gen)
    return any_(clients(client_gen), nemesis(nemesis_gen))


def nemesis(nemesis_gen, client_gen=None):
    if client_gen is None:
        return on_threads(lambda t: t == NEMESIS, nemesis_gen)
    return any_(nemesis(nemesis_gen), clients(client_gen))


class Mix(Generator):
    """Uniform random mixture; ignores updates (generator.clj:1104-1131)."""

    __slots__ = ("i", "gens")

    def __init__(self, i, gens):
        self.i = i
        self.gens = list(gens)

    def op(self, test, ctx):
        if not self.gens:
            return None
        res = op(self.gens[self.i], test, ctx)
        if res is not None:
            gens = list(self.gens)
            gens[self.i] = res[1]
            return (res[0], Mix(rand_int(len(gens)), gens))
        gens = self.gens[: self.i] + self.gens[self.i + 1 :]
        if not gens:
            return None
        return Mix(rand_int(len(gens)), gens).op(test, ctx)

    def update(self, test, ctx, event):
        return self


def mix(gens):
    gens = list(gens)
    if not gens:
        return None
    return Mix(rand_int(len(gens)), gens)


# ---------------------------------------------------------------------------
# Bounds


class Limit(Generator):
    __slots__ = ("remaining", "gen")

    def __init__(self, remaining, gen):
        self.remaining = remaining
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return (res[0], Limit(self.remaining - 1, res[1]))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Limit(self.remaining, g2)


def limit(n, gen):
    """At most n ops from gen (generator.clj:1133-1146)."""
    return Limit(n, gen)


def once(gen):
    return limit(1, gen)


def log_(msg):
    """One :log op that makes the interpreter log a message
    (generator.clj:1153-1157)."""
    return {"type": LOG_TYPE, "value": msg}


class Repeat(Generator):
    """Re-emit from the same underlying generator state forever / n times
    (generator.clj:1159-1186)."""

    __slots__ = ("remaining", "gen")

    def __init__(self, remaining, gen):
        self.remaining = remaining  # -1 = infinite
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return (res[0], Repeat(self.remaining - 1, self.gen))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Repeat(self.remaining, g2)


def repeat_(*args):
    if len(args) == 1:
        return Repeat(-1, args[0])
    n, gen = args
    assert n >= 0
    return Repeat(n, gen)


class ProcessLimit(Generator):
    """Emit ops for at most n distinct processes (generator.clj:1188-1213)."""

    __slots__ = ("n", "procs", "gen")

    def __init__(self, n, procs, gen):
        self.n = n
        self.procs = procs
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g = res
        if o is PENDING:
            return (o, ProcessLimit(self.n, self.procs, g))
        procs = self.procs | frozenset(all_processes(ctx))
        if len(procs) > self.n:
            return None
        return (o, ProcessLimit(self.n, procs, g))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else ProcessLimit(self.n, self.procs, g2)


def process_limit(n, gen):
    return ProcessLimit(n, frozenset(), gen)


class TimeLimit(Generator):
    """Emit ops for dt seconds after the first op (generator.clj:1215-1240)."""

    __slots__ = ("limit", "cutoff", "gen")

    def __init__(self, limit, cutoff, gen):
        self.limit = limit
        self.cutoff = cutoff
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g = res
        if o is PENDING:
            return (o, TimeLimit(self.limit, self.cutoff, g))
        cutoff = self.cutoff if self.cutoff is not None else o["time"] + self.limit
        if o["time"] >= cutoff:
            return None
        return (o, TimeLimit(self.limit, cutoff, g))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else TimeLimit(self.limit, self.cutoff, g2)


def time_limit(dt, gen):
    return TimeLimit(secs_to_nanos(dt), None, gen)


class Stagger(Generator):
    """Schedule ops at uniform random intervals in [0, 2*dt) — a *total*
    rate across all threads (generator.clj:1242-1281)."""

    __slots__ = ("dt", "next_time", "gen")

    def __init__(self, dt, next_time, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g = res
        if o is PENDING:
            return (o, self)
        nt = self.next_time if self.next_time is not None else ctx.time
        nt2 = nt + int(rand_float(self.dt))
        if nt <= o["time"]:
            return (o, Stagger(self.dt, nt2, g))
        o = dict(o)
        o["time"] = nt
        return (o, Stagger(self.dt, nt2, g))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Stagger(self.dt, self.next_time, g2)


def stagger(dt, gen):
    return Stagger(secs_to_nanos(2 * dt), None, gen)


class Delay(Generator):
    """Ops exactly dt apart (catching up when behind)
    (generator.clj:1318-1347)."""

    __slots__ = ("dt", "next_time", "gen")

    def __init__(self, dt, next_time, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g = res
        if o is PENDING:
            return (o, Delay(self.dt, self.next_time, g))
        nt = self.next_time if self.next_time is not None else o["time"]
        o = dict(o)
        o["time"] = max(o["time"], nt)
        return (o, Delay(self.dt, nt + self.dt, g))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Delay(self.dt, self.next_time, g2)


def delay(dt, gen):
    return Delay(secs_to_nanos(dt), None, gen)


def sleep(dt):
    """One :sleep op — the receiving worker idles dt seconds
    (generator.clj:1348-1352)."""
    return {"type": SLEEP, "value": dt}


class Cycle(Generator):
    """Cycle through a sequence of generators forever: run element i to
    exhaustion, then move to (i+1) mod n with a FRESH copy of the
    element (the reference writes this as Clojure's lazy ``(cycle
    [...])``; note ``repeat_`` is different — it re-emits from the same
    un-advanced generator, so ``repeat_([a b])`` yields only ``a``s)."""

    _FRESH = object()  # distinct from None (None = exhausted inner)

    __slots__ = ("elements", "i", "inner")

    def __init__(self, elements, i=0, inner=_FRESH):
        self.elements = tuple(elements)
        self.i = i
        self.inner = self.elements[i] if inner is Cycle._FRESH else inner

    def op(self, test, ctx):
        i, inner = self.i, self.inner
        for _ in range(len(self.elements) + 1):
            res = op(inner, test, ctx)
            if res is not None:
                o, g2 = res
                return (o, Cycle(self.elements, i, g2))
            i = (i + 1) % len(self.elements)
            inner = self.elements[i]
        return None  # every element is empty

    def update(self, test, ctx, event):
        g2 = update(self.inner, test, ctx, event)
        return self if g2 is self.inner else Cycle(self.elements, self.i, g2)


def cycle_(elements):
    """An endless loop over a sequence of generators."""
    elements = list(elements)
    if not elements:
        return None
    return Cycle(elements)


class Synchronize(Generator):
    """PENDING until every worker is free, then delegates
    (generator.clj:1354-1374)."""

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        if ctx.free_threads == frozenset(ctx.workers):
            return op(self.gen, test, ctx)
        return (PENDING, self)

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Synchronize(g2)


synchronize = Synchronize


def phases(*gens):
    """Run each generator to completion, synchronizing between
    (generator.clj:1376-1381)."""
    return [Synchronize(g) for g in gens]


def then(a, b):
    """b, then (synchronized) a — argument order matches the reference's
    threading-macro convention (generator.clj:1383-1394)."""
    return [b, Synchronize(a)]


class UntilOk(Generator):
    """Yield ops until one completes :ok (generator.clj:1396-1414)."""

    __slots__ = ("gen", "done")

    def __init__(self, gen, done=False):
        self.gen = gen
        self.done = done

    def op(self, test, ctx):
        if self.done:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return (res[0], UntilOk(res[1], self.done))

    def update(self, test, ctx, event):
        if event.get("type") == OK:
            return self if self.done else UntilOk(self.gen, True)
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else UntilOk(g2, self.done)


def until_ok(gen):
    return UntilOk(gen)


class FlipFlop(Generator):
    """Alternate between generators; stop when any is exhausted; ignore
    updates (generator.clj:1416-1428)."""

    __slots__ = ("gens", "i")

    def __init__(self, gens, i=0):
        self.gens = list(gens)
        self.i = i

    def op(self, test, ctx):
        res = op(self.gens[self.i], test, ctx)
        if res is None:
            return None
        gens = list(self.gens)
        gens[self.i] = res[1]
        return (res[0], FlipFlop(gens, (self.i + 1) % len(gens)))


def flip_flop(a, b):
    return FlipFlop([a, b])


def concat(*gens):
    """Concatenate arbitrary generators (generator.clj:755-761)."""
    return list(gens)
