"""The runtime that turns a generator into a real history.

Mirrors jepsen.generator.interpreter (jepsen/src/jepsen/generator/
interpreter.clj): a single scheduler loop plus one OS thread per worker,
coupled through size-1 queues. The scheduler:

1. polls completions FIRST (latency-sensitive: a stale completion makes
   the generator believe ops are concurrent when they're not —
   interpreter.clj:215-241);
2. otherwise evaluates the pure generator for the next op
   (interpreter.clj:244-248);
3. dispatches ops whose :time has arrived to their worker's in-queue,
   sleeps until pending ops mature, and exits when the generator is
   exhausted and all outstanding ops have completed
   (interpreter.clj:252-292).

Soundness rule: a worker that catches ANY exception from a client invoke
completes the op as ``:info`` (indeterminate — the fault may have taken
effect), and the scheduler hands that thread a fresh process id so the
next op can't be confused with the crashed one
(interpreter.clj:142-157,233-236). Nemesis crashes do NOT bump the
process (the nemesis is a singleton).

Worker kinds come from the thread id: integer threads are client workers,
the ``"nemesis"`` thread drives the test's nemesis
(interpreter.clj:33-97).
"""

from __future__ import annotations

import logging
import queue
import sys
import threading
import time as _time
from typing import Any, Optional

from .. import client as jclient
from .. import nemesis as jnemesis
from ..history import INFO, INVOKE, NEMESIS
from ..util import log_op, relative_time_nanos
from . import (
    PENDING,
    Context,
    FriendlyExceptions,
    Validate,
    context as make_context,
    next_process,
    op as gen_op,
    update as gen_update,
)

LOG = logging.getLogger("jepsen.interpreter")

# Don't sleep longer than this when the generator is :pending — it may
# become ready as completions arrive (interpreter.clj:166-170).
MAX_PENDING_INTERVAL_S = 0.001

# GIL switch interval while a run is live. The scheduler thread is the
# bottleneck and every dispatched op is tiny; the default 5 ms interval
# lets freshly-woken workers preempt the scheduler mid-step, thrashing
# the GIL at high concurrency (~+17% throughput at 100 workers with
# 20 ms measured). Process-global state: a depth counter makes
# overlapping runs save/restore it exactly once (outermost wins).
SWITCH_INTERVAL_S = 0.02
_SWITCH_LOCK = threading.Lock()
_SWITCH_DEPTH = 0
_SWITCH_SAVED = 0.0


def _switch_interval_enter() -> None:
    global _SWITCH_DEPTH, _SWITCH_SAVED
    with _SWITCH_LOCK:
        _SWITCH_DEPTH += 1
        if _SWITCH_DEPTH == 1:
            _SWITCH_SAVED = sys.getswitchinterval()
            sys.setswitchinterval(max(_SWITCH_SAVED, SWITCH_INTERVAL_S))


def _switch_interval_exit() -> None:
    global _SWITCH_DEPTH
    with _SWITCH_LOCK:
        _SWITCH_DEPTH -= 1
        if _SWITCH_DEPTH == 0:
            sys.setswitchinterval(_SWITCH_SAVED)


def goes_in_history(op: dict) -> bool:
    """:sleep and :log ops are scheduler directives, not history events
    (interpreter.clj:172-179)."""
    return op.get("type") not in ("sleep", "log")


class Worker:
    """One executor of ops (interpreter.clj:19-31)."""

    def open(self, test: dict, thread_id: Any) -> "Worker":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def close(self, test: dict) -> None:
        pass


class ClientWorker(Worker):
    """Wraps a Client; re-opens it when the worker's process changes and
    the client isn't Reusable (interpreter.clj:33-67)."""

    def __init__(self, node: Any, process: Any = None,
                 client: Optional[jclient.Client] = None):
        self.node = node
        self.process = process
        self.client = client

    def invoke(self, test, op):
        if self.process != op.get("process") and not (
            self.client is not None
            and jclient.is_reusable(self.client, test)
        ):
            # Process changed; tear down the old connection, open a fresh one.
            if self.client is not None:
                try:
                    self.client.close(test)
                except Exception:
                    LOG.warning("error closing client", exc_info=True)
                self.client = None
            try:
                self.client = jclient.validate(test["client"]).open(
                    test, self.node
                )
                self.process = op.get("process")
            except Exception:
                LOG.warning(
                    "error opening client for process %s on node %s",
                    op.get("process"), self.node, exc_info=True,
                )
                return {
                    **op,
                    "type": "fail",
                    "error": ["no-client", "cannot open client"],
                }
        return self.client.invoke(test, op)

    def close(self, test):
        if self.client is not None:
            self.client.close(test)
            self.client = None


class NemesisWorker(Worker):
    """Applies ops to the test's (already set-up) nemesis
    (interpreter.clj:69-76)."""

    def __init__(self, nemesis: jnemesis.Nemesis):
        self.nemesis = nemesis

    def invoke(self, test, op):
        return self.nemesis.invoke(test, op)


def client_nodes(test: dict) -> list:
    """Thread i's home node: round-robin over :nodes
    (interpreter.clj:83-97)."""
    nodes = test.get("nodes") or [None]
    conc = test.get("concurrency", len(nodes))
    return [nodes[i % len(nodes)] for i in range(conc)]


def make_worker(test: dict, thread_id: Any, nemesis: jnemesis.Nemesis) -> Worker:
    if thread_id == NEMESIS:
        return NemesisWorker(nemesis)
    node = client_nodes(test)[thread_id]
    return ClientWorker(node)


class _WorkerThread:
    """A worker plus its inbox and OS thread; completions land on the
    scheduler's ONE shared queue (the reference's single out
    ArrayBlockingQueue, interpreter.clj:99-164) so the scheduler blocks
    on arrivals instead of polling per-worker outboxes.

    Both queues are ``SimpleQueue`` (C-implemented — roughly half the
    per-op synchronization cost of ``queue.Queue``'s pure-Python
    lock/condition dance, measured ~1.5× interpreter throughput). The
    inbox is unbounded but holds at most one op by construction: the
    scheduler only dispatches to FREE threads."""

    def __init__(self, test: dict, thread_id: Any, worker: Worker,
                 done_q: "queue.SimpleQueue[tuple]"):
        self.thread_id = thread_id
        self.worker = worker
        self.inbox: "queue.SimpleQueue[dict]" = queue.SimpleQueue()
        self.done_q = done_q
        self.thread = threading.Thread(
            target=self._run, args=(test,),
            name=f"jepsen-worker-{thread_id}", daemon=True,
        )
        self.thread.start()

    def _run(self, test: dict) -> None:
        while True:
            op = self.inbox.get()
            typ = op.get("type")
            if typ == "exit":
                try:
                    self.worker.close(test)
                except Exception:
                    LOG.warning("error closing worker %s", self.thread_id,
                                exc_info=True)
                return
            if typ == "sleep":
                _time.sleep(op.get("value") or 0)
                self.done_q.put((self.thread_id, dict(op)))
                continue
            if typ == "log":
                LOG.info("%s", op.get("value"))
                self.done_q.put((self.thread_id, dict(op)))
                continue
            try:
                res = self.worker.invoke(test, op)
                log_op(res)
                self.done_q.put((self.thread_id, res))
            except Exception as e:  # noqa: BLE001 - soundness rule
                # Coarse-grained failure: we don't know whether the op took
                # effect. :info keeps its interval open to end-of-history
                # (interpreter.clj:142-157).
                LOG.warning("process %s %s indeterminate", op.get("process"),
                            op.get("f"), exc_info=True)
                self.done_q.put((self.thread_id, {
                    **op,
                    "type": INFO,
                    "error": f"indeterminate: {e}",
                    "exception": e,
                }))

    def send(self, op: dict) -> None:
        self.inbox.put(op)

    def join(self, timeout: Optional[float] = None) -> None:
        self.thread.join(timeout)


def run(test: dict) -> list[dict]:
    """Run the test's generator to completion against its client and
    nemesis; returns the history as a list of op dicts
    (interpreter.clj:181-310).

    Requires: test["client"] (a Client prototype), test["nemesis"] (already
    set up), test["generator"], test["concurrency"], test["nodes"].

    Optional live-run hooks (both resolved ONCE; absent keys cost one
    None check per op):

    - ``test["op-observer"]``: called with every history-bound op as it
      lands (invocations and completions) — the online monitor's tee.
      Exceptions are logged, never propagated into the run.
    - ``test["stop-event"]``: a ``threading.Event``; once set, the
      scheduler stops dispatching and returns the history accumulated so
      far (ops still in flight are abandoned to their daemon workers) —
      the online monitor's ``abort_on_violation`` seam."""
    from .. import telemetry as jtelemetry

    ctx = make_context(test)
    nemesis = test.get("nemesis") or jnemesis.noop()
    _reg = jtelemetry.of_test(test)
    _observer = test.get("op-observer")
    _stop = test.get("stop-event")
    # Op-latency histogram by (f, completion type). Metric object is
    # resolved ONCE here; the completion path below only guards on the
    # None, so a telemetry-off run allocates nothing per op.
    _lat = (_reg.histogram(
        "jepsen_op_latency_seconds",
        "Client op latency (invoke to completion) by f and type",
        labelnames=("f", "type")) if _reg is not None else None)
    threads = ctx.free_thread_list()
    done_q: "queue.SimpleQueue[tuple]" = queue.SimpleQueue()
    workers: dict[Any, _WorkerThread] = {
        t: _WorkerThread(test, t, make_worker(test, t, nemesis), done_q)
        for t in threads
    }
    gen = Validate(FriendlyExceptions(test.get("generator")))
    history: list[dict] = []
    # Ops in flight: thread id -> invoke op.
    outstanding: dict[Any, dict] = {}
    # process -> thread, maintained alongside ctx.workers: dispatch must
    # not scan every worker per op (O(concurrency) per op bites at 100+
    # workers).
    thread_of: dict[Any, Any] = {p: t for t, p in ctx.workers.items()}
    exc: Optional[BaseException] = None

    def _note(op: dict) -> None:
        history.append(op)
        if _observer is not None:
            try:
                _observer(op)
            except Exception:  # noqa: BLE001 - observers never sink runs
                LOG.warning("op-observer failed", exc_info=True)

    def take_completion(block: bool, timeout: Optional[float] = None):
        """Apply completions from the shared queue; returns whether any
        was handled (interpreter.clj:215-241). BATCH-DRAIN: after the
        first get (which may block), every already-arrived completion is
        drained non-blockingly before returning — at high concurrency
        (100 workers) completions arrive in bursts, and paying a
        generator evaluation + scheduler pass per completion was the
        gap between the 1-worker and 100-worker throughput numbers.
        Each completion still updates the generator individually (the
        generator must observe every op), in arrival order — only the
        interleaved scheduler passes are elided."""
        nonlocal ctx, gen
        handled = 0
        while True:
            try:
                if handled == 0:
                    thread, op2 = done_q.get(block=block, timeout=timeout)
                else:
                    thread, op2 = done_q.get_nowait()
            except queue.Empty:
                return handled > 0
            inv = outstanding.pop(thread, None)
            op2 = dict(op2)
            op2.pop("exception", None)
            op2["time"] = relative_time_nanos()
            if _lat is not None and inv is not None and thread != NEMESIS \
                    and goes_in_history(op2):
                _lat.labels(f=str(op2.get("f")),
                            type=str(op2.get("type"))).observe(
                                max(op2["time"] - inv.get("time",
                                                          op2["time"]),
                                    0) / 1e9)
            ctx = ctx.with_(
                time=max(ctx.time, op2["time"]),
                free_threads=ctx.free_threads | {thread},
            )
            gen = gen_update(gen, test, ctx, op2)
            # Client crash ⇒ fresh process id for this thread
            # (interpreter.clj:233-236).
            if thread != NEMESIS and op2.get("type") == INFO:
                new_workers = dict(ctx.workers)
                thread_of.pop(new_workers[thread], None)
                new_workers[thread] = next_process(ctx, thread)
                thread_of[new_workers[thread]] = thread
                ctx = ctx.with_(workers=new_workers)
            if goes_in_history(op2):
                _note(op2)
            handled += 1

    _switch_interval_enter()
    try:
        while True:
            # 0. External stop (online monitor abort): return the
            # history as recorded so far; in-flight ops are abandoned
            # to their daemon workers (the run is over).
            if _stop is not None and _stop.is_set():
                take_completion(block=False)
                break

            # 1. Completions first (drain whatever has arrived).
            if take_completion(block=False):
                continue

            # 2. Ask the generator (interpreter.clj:244-292).
            res = gen_op(gen, test, ctx)
            if res is None:
                # Exhausted: wait for stragglers, then shut workers down.
                if outstanding:
                    take_completion(block=True,
                                    timeout=MAX_PENDING_INTERVAL_S)
                    continue
                break
            op_, gen2 = res
            now = relative_time_nanos()
            if op_ is PENDING:
                # Wake on the next completion rather than spinning.
                take_completion(block=True, timeout=MAX_PENDING_INTERVAL_S)
                continue
            if op_["time"] > now:
                # Future op: sleep towards it, but wake early for
                # completions (interpreter.clj:268-275).
                take_completion(
                    block=True,
                    timeout=min((op_["time"] - now) / 1e9,
                                MAX_PENDING_INTERVAL_S),
                )
                continue
            # Dispatch. The op keeps its scheduled :time.
            op_ = dict(op_)
            op_["time"] = max(op_["time"], now) if op_["time"] >= 0 else now
            thread = thread_of.get(op_["process"])
            assert thread is not None, f"no thread for process {op_['process']}"
            workers[thread].send(dict(op_))
            outstanding[thread] = op_
            ctx = ctx.with_(
                time=max(ctx.time, op_["time"]),
                free_threads=ctx.free_threads - {thread},
            )
            gen = gen_update(gen2, test, ctx, op_)
            if goes_in_history(op_):
                _note(op_)
    except BaseException as e:  # noqa: BLE001 - propagate after cleanup
        exc = e
    finally:
        _switch_interval_exit()
        # Drain & stop workers (interpreter.clj:252-261,294-309). Workers
        # stuck in a client call are daemon threads; exit ops queue behind
        # whatever they're doing.
        for w in workers.values():
            w.inbox.put({"type": "exit"})
        for w in workers.values():
            w.join(timeout=5.0)
    if exc is not None:
        raise exc
    return history
