"""Deterministic generator simulation — no threads, no wall clock.

Mirrors jepsen/src/jepsen/generator/test.clj: plays a generator against a
synthetic completion function under a pinned RNG (seed 45100), maintaining a
sorted in-flight completion set. This is both the unit-test vehicle for the
combinators and their executable spec (SURVEY.md §4)."""

from __future__ import annotations

from typing import Callable, Optional

from . import (
    Context,
    INVOKE,
    NEMESIS,
    PENDING,
    Validate,
    context,
    fixed_rand,
    next_process,
    op as gen_op,
    process_to_thread,
    update as gen_update,
)

DEFAULT_TEST: dict = {}
RAND_SEED = 45100  # generator/test.clj:43-47
PERFECT_LATENCY = 10  # ns, generator/test.clj:124-126


def n_plus_nemesis_context(n: int) -> Context:
    return context({"concurrency": n})


def default_context() -> Context:
    return n_plus_nemesis_context(2)


def invocations(history: list[dict]) -> list[dict]:
    return [o for o in history if o.get("type") == INVOKE]


def simulate(gen, complete_fn: Callable, ctx: Optional[Context] = None,
             test: Optional[dict] = None) -> list[dict]:
    """Simulate a generator to exhaustion (generator/test.clj:49-106).

    ``complete_fn(ctx, invoke) -> completion-op`` decides each op's fate.
    Returns the full history (invocations + completions interleaved by
    time)."""
    if ctx is None:
        ctx = default_context()
    if test is None:
        test = DEFAULT_TEST
    with fixed_rand(RAND_SEED):
        ops: list[dict] = []
        in_flight: list[dict] = []  # sorted by time
        gen = Validate(gen)
        while True:
            res = gen_op(gen, test, ctx)
            if res is None:
                return ops + in_flight
            invoke, gen2 = res
            if invoke is not PENDING and (
                not in_flight or invoke["time"] <= in_flight[0]["time"]
            ):
                # Apply the invocation: advance clock, occupy the thread.
                thread = process_to_thread(ctx, invoke["process"])
                ctx = ctx.with_(
                    time=max(ctx.time, invoke["time"]),
                    free_threads=ctx.free_threads - {thread},
                )
                gen = gen_update(gen2, test, ctx, invoke)
                complete = complete_fn(ctx, invoke)
                in_flight = sorted(in_flight + [complete], key=lambda o: o["time"])
                ops.append(invoke)
            else:
                # Complete something before the next invocation can apply.
                assert in_flight, "generator pending and nothing in flight???"
                o = in_flight[0]
                thread = process_to_thread(ctx, o["process"])
                ctx = ctx.with_(
                    time=max(ctx.time, o["time"]),
                    free_threads=ctx.free_threads | {thread},
                )
                gen = gen_update(gen, test, ctx, o)
                if thread != NEMESIS and o.get("type") == "info":
                    workers = dict(ctx.workers)
                    workers[thread] = next_process(ctx, thread)
                    ctx = ctx.with_(workers=workers)
                ops.append(o)
                in_flight = in_flight[1:]


def with_nemesis(nemesis, complete_fn, test: Optional[dict] = None):
    """Wrap ``complete_fn`` so nemesis-track invocations route through a
    real :class:`jepsen_tpu.nemesis.Nemesis` instance (its completion
    keeps the op's time + PERFECT_LATENCY unless the nemesis set one) —
    lets the simulated generator drive stateful fault injectors like
    the process-pause nemesis (jepsen_tpu.nemesis.pause)."""

    def complete(ctx, op):
        if op.get("process") == NEMESIS:
            res = dict(nemesis.invoke(test or DEFAULT_TEST, op))
            if res.get("time") == op.get("time"):
                res["time"] = op["time"] + PERFECT_LATENCY
            res.setdefault("type", "info")
            return res
        return complete_fn(ctx, op)

    return complete


def quick_ops(gen, ctx=None, test=None):
    """Every op succeeds instantly with zero latency."""
    return simulate(gen, lambda ctx, o: {**o, "type": "ok"}, ctx, test)


def quick(gen, ctx=None, test=None):
    return invocations(quick_ops(gen, ctx, test))


def perfect_star(gen, ctx=None):
    """Every op succeeds in 10 ns; full history."""
    return simulate(
        gen, lambda ctx, o: {**o, "type": "ok", "time": o["time"] + PERFECT_LATENCY}, ctx
    )


def perfect(gen, ctx=None):
    return invocations(perfect_star(gen, ctx))


def perfect_info(gen, ctx=None):
    """Every op crashes (:info) in 10 ns; invocations only."""
    return invocations(
        simulate(
            gen,
            lambda ctx, o: {**o, "type": "info", "time": o["time"] + PERFECT_LATENCY},
            ctx,
        )
    )


def imperfect(gen, ctx=None):
    """Threads rotate fail -> info -> ok; full history
    (generator/test.clj:163-180)."""
    state: dict = {}
    rot = {None: "fail", "fail": "info", "info": "ok", "ok": "fail"}

    def complete(ctx, o):
        t = process_to_thread(ctx, o["process"])
        state[t] = rot[state.get(t)]
        return {**o, "type": state[t], "time": o["time"] + PERFECT_LATENCY}

    return simulate(gen, complete, ctx)
