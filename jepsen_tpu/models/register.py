"""Register models.

Equivalents of knossos ``model/register``, ``model/cas-register`` (consumed
by the reference at e.g. consul/src/jepsen/consul/register.clj:71-72 and
jepsen/src/jepsen/tests/linearizable_register.clj:22-53) and
``model/multi-register``.

Op shapes follow the reference workloads:

- read:  invoke ``{:f :read :value nil}``, ok carries the observed value.
- write: ``{:f :write :value v}``.
- cas:   ``{:f :cas :value [old new]}``.
- multi-register: ``{:f :read|:write :value {reg v}}`` (single-reg per op on
  the device path).
"""

from __future__ import annotations

from typing import Optional

from . import EncodeError, Model, UNKNOWN, ValueTable, register_model
from ..history import OK

READ, WRITE, CAS = 0, 1, 2


@register_model
class CasRegister(Model):
    """A register supporting read/write/compare-and-set."""

    name = "cas-register"
    state_width = 1
    n_opcodes = 3

    def __init__(self, init=None):
        self.init = init

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return (table.intern(self.init),)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        f = iv.f
        if f == "read":
            if iv.type != OK:
                # indeterminate read: no state change, unknown result — drop
                return None
            return (READ, table.intern(iv.value_out), 0)
        if f == "write":
            return (WRITE, table.intern(iv.value_in), 0)
        if f == "cas":
            old, new = iv.value_in
            return (CAS, table.intern(old), table.intern(new))
        raise EncodeError(f"cas-register: unknown f {f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        (v,) = state
        if opcode == READ:
            return (a1 == UNKNOWN or v == a1, state)
        if opcode == WRITE:
            return (True, (a1,))
        # CAS
        return (v == a1, (a2,) if v == a1 else state)

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        v = states[..., 0]
        is_read = opcodes == READ
        is_write = opcodes == WRITE
        is_cas = opcodes == CAS
        cas_hit = v == a1s
        ok = (
            (is_read & ((a1s == UNKNOWN) | (v == a1s)))
            | is_write
            | (is_cas & cas_hit)
        )
        v2 = jnp.where(is_write, a1s, jnp.where(is_cas & cas_hit, a2s, v))
        return ok, v2[..., None]

    def decode_state(self, state, table):
        return (table.lookup(int(state[0])),)

    def encode_state(self, decoded, table):
        return (table.intern(decoded[0]),)

    def describe_op(self, opcode, a1, a2, table):
        if opcode == READ:
            return f"read -> {table.lookup(a1)!r}"
        if opcode == WRITE:
            return f"write {table.lookup(a1)!r}"
        return f"cas {table.lookup(a1)!r} -> {table.lookup(a2)!r}"


@register_model
class Register(CasRegister):
    """Read/write register (no cas)."""

    name = "register"
    n_opcodes = 2

    def encode_op(self, iv, table):
        if iv.f == "cas":
            raise EncodeError("register: cas not supported; use cas-register")
        return super().encode_op(iv, table)


@register_model
class MultiRegister(Model):
    """A fixed set of named registers, read/written one at a time on the
    device path (ops whose value maps several registers fall back to host).

    ``init``: dict register-name -> initial value. Op values are
    ``{reg value}`` maps.
    """

    name = "multi-register"
    n_opcodes = 2

    def __init__(self, init: dict):
        if not init:
            raise ValueError("multi-register needs at least one register")
        self.init = dict(init)
        self.regs = sorted(self.init, key=repr)
        self.reg_ids = {r: i for i, r in enumerate(self.regs)}
        self.state_width = len(self.regs)

    def cache_key(self):
        return (self.name, self.state_width, self.n_opcodes)

    def cache_args(self):
        return (tuple(sorted(self.init.items(), key=repr)),)

    @classmethod
    def _from_cache_key(cls, args):
        return cls(dict(args[0]))

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return tuple(table.intern(self.init[r]) for r in self.regs)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        f = iv.f
        if f not in ("read", "write"):
            raise EncodeError(f"multi-register: unknown f {f!r}")
        value = iv.value_out if f == "read" else iv.value_in
        if f == "read" and iv.type != OK:
            return None
        if not isinstance(value, dict) or len(value) != 1:
            raise EncodeError("multi-register device path handles single-register ops")
        ((reg, v),) = value.items()
        if reg not in self.reg_ids:
            raise EncodeError(f"multi-register: unknown register {reg!r}")
        return (READ if f == "read" else WRITE, self.reg_ids[reg], table.intern(v))

    def step_scalar(self, state, opcode, a1, a2):
        cur = state[a1]
        if opcode == READ:
            return (a2 == UNKNOWN or cur == a2, state)
        new = list(state)
        new[a1] = a2
        return (True, tuple(new))

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        cur = jnp.take_along_axis(states, a1s[..., None], axis=-1)[..., 0]
        is_read = opcodes == READ
        ok = jnp.where(is_read, (a2s == UNKNOWN) | (cur == a2s), True)
        lane = jnp.arange(states.shape[-1], dtype=states.dtype)
        write_mask = (~is_read)[..., None] & (lane == a1s[..., None])
        states2 = jnp.where(write_mask, a2s[..., None], states)
        return ok, states2

    def decode_state(self, state, table):
        return tuple(table.lookup(int(x)) for x in state)

    def encode_state(self, decoded, table):
        return tuple(table.intern(v) for v in decoded)

    def describe_op(self, opcode, a1, a2, table):
        verb = "read" if opcode == READ else "write"
        return f"{verb} {self.regs[a1]!r} {table.lookup(a2)!r}"
