"""Consistency models.

The reference consumes knossos models (`model/cas-register`, `model/mutex`,
`model/register`, `model/multi-register`; jepsen/src/jepsen/checker.clj:17-23)
plus five custom CP-subsystem models in the hazelcast suite
(hazelcast/src/jepsen/hazelcast.clj:515-649). Each model here carries *two*
step implementations over one integer encoding:

- ``step_scalar(state, opcode, a1, a2) -> (ok, state')`` — plain Python on
  tuples of ints; the trusted oracle used by the host checker and the
  differential tests.
- ``step_jax(states, opcodes, a1s, a2s) -> (ok, states')`` — the same
  transition vectorized over a batch of configurations with jax.numpy; this
  is what the TPU frontier kernel jits. Written so it also works on plain
  numpy arrays.

States are fixed-width int32 lane tuples so a configuration (linearized-set,
model-state) packs into a small tensor row. Arbitrary op *values* are
interned to dense int ids by :class:`ValueTable` at encode time
(`jepsen_tpu.ops.encode`); models only ever see ints.

``UNKNOWN`` marks an unobserved value (e.g. a read whose completion never
arrived); models must treat it as "matches anything" where a comparison
against observed data is involved.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

# int32-safe sentinel: an interned value id can never equal it.
UNKNOWN = -(2**31)


class ValueTable:
    """Interns arbitrary hashable op values to dense non-negative int ids."""

    def __init__(self) -> None:
        self.ids: dict[Any, int] = {}
        self.values: list[Any] = []

    def intern(self, v: Any) -> int:
        v = _freeze(v)
        i = self.ids.get(v)
        if i is None:
            i = len(self.values)
            self.ids[v] = i
            self.values.append(v)
        return i

    def lookup(self, i: int) -> Any:
        if i == UNKNOWN:
            return None
        return self.values[i]

    def __len__(self) -> int:
        return len(self.values)


def _freeze(v: Any) -> Any:
    if isinstance(v, list):
        return tuple(_freeze(e) for e in v)
    if isinstance(v, dict):
        return tuple(sorted(((k, _freeze(x)) for k, x in v.items()), key=repr))
    if isinstance(v, set):
        return frozenset(_freeze(e) for e in v)
    return v


class EncodeError(Exception):
    """Raised when an op cannot be expressed in the model's encoding
    (the checker then falls back to a host-side rich-value model)."""


class Model:
    """Base class. Subclasses define the encoding + transition function.

    Class attributes:

    - ``name``: registry key (mirrors the knossos model fn name).
    - ``state_width``: number of int32 lanes of model state.
    - ``n_opcodes``: size of the opcode space.
    """

    name: str = "model"
    state_width: int = 1
    n_opcodes: int = 1
    device_capable: bool = True  # False => host-only model (no step_jax)

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        """Initial model state as int32 lanes; interns any initial values
        into ``table`` so ops referring to them encode consistently."""
        raise NotImplementedError

    def encode_op(self, interval, table: ValueTable) -> Optional[tuple[int, int, int]]:
        """Map a paired op (:class:`jepsen_tpu.history.Interval`) to
        ``(opcode, a1, a2)`` ints, or ``None`` to drop it as irrelevant to
        the model (e.g. an indeterminate read — it cannot change state and
        constrains nothing). ``:fail`` ops are dropped by the encoder before
        this hook. Raise :class:`EncodeError` for inexpressible ops."""
        raise NotImplementedError

    def step_scalar(
        self, state: tuple[int, ...], opcode: int, a1: int, a2: int
    ) -> tuple[bool, tuple[int, ...]]:
        raise NotImplementedError

    def step_jax(self, states, opcodes, a1s, a2s):
        """Vectorized transition. ``states``: int32 [N, state_width];
        ``opcodes``/``a1s``/``a2s``: int32 [N]. Returns (ok [N] bool,
        states' [N, state_width]). Must be jax-traceable (no Python
        branching on data)."""
        raise NotImplementedError

    # -- state portability (the online monitor's cross-segment carry) -------
    # State lanes are only meaningful relative to the ValueTable they were
    # encoded against; carrying a decided end-state across segment
    # boundaries (jepsen_tpu.online) therefore round-trips through the
    # *semantic* value domain: ``decode_state`` lifts lanes out of a table,
    # ``encode_state`` re-interns them into the next segment's table. The
    # defaults treat lanes as table-independent ints (correct for models
    # whose lanes are plain counters — Mutex, ReentrantMutex,
    # Semaphore); models with interned value ids in their lanes
    # (registers, queues, and the owner-aware mutexes, whose owner lane
    # is an interned ("process", p) id) override both.

    def decode_state(self, state: Sequence[int], table: ValueTable) -> tuple:
        """Lanes -> table-independent semantic state."""
        return tuple(int(x) for x in state)

    def encode_state(self, decoded: tuple, table: ValueTable) -> tuple[int, ...]:
        """Semantic state -> lanes relative to ``table`` (interning any
        values it introduces)."""
        return tuple(int(x) for x in decoded)

    # -- kernel-cache identity ----------------------------------------------
    # The device kernel (ops/wgl.py) compiles one XLA program per model
    # *behavior*; these hooks define the hashable identity and how to rebuild
    # an equivalent instance inside the cached kernel factory.
    def cache_key(self) -> tuple:
        return (self.name, self.state_width, self.n_opcodes)

    def cache_args(self) -> tuple:
        """Hashable constructor args that affect step_jax behavior."""
        return ()

    @classmethod
    def _from_cache_key(cls, args: tuple) -> "Model":
        return cls(*args)

    # -- description helpers -------------------------------------------------
    def describe_op(self, opcode: int, a1: int, a2: int, table: ValueTable) -> str:
        return f"op{opcode}({a1}, {a2})"

    def __repr__(self) -> str:
        return f"<model {self.name}>"


_REGISTRY: dict[str, Callable[..., Model]] = {}


def register_model(cls):
    """Class decorator: adds the model to the by-name registry used by the
    CLI / EDN-driven checker configuration."""
    _REGISTRY[cls.name] = cls
    return cls


def model_by_name(name: str, *args: Any, **kw: Any) -> Model:
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}") from None
    return cls(*args, **kw)


def known_models() -> Sequence[str]:
    return sorted(_REGISTRY)


# Import concrete models for their registration side effects.
from . import register as _register_mod  # noqa: E402,F401
from . import mutex as _mutex_mod  # noqa: E402,F401
from . import queue as _queue_mod  # noqa: E402,F401
from . import counter as _counter_mod  # noqa: E402,F401
from . import sets as _sets_mod  # noqa: E402,F401
from . import bank as _bank_mod  # noqa: E402,F401

from .register import Register, CasRegister, MultiRegister  # noqa: E402,F401
from .counter import Counter  # noqa: E402,F401
from .sets import LwSet  # noqa: E402,F401
from .bank import Bank  # noqa: E402,F401
from .mutex import (  # noqa: E402,F401
    Mutex,
    ReentrantMutex,
    OwnerAwareMutex,
    FencedMutex,
    ReentrantFencedMutex,
    Semaphore,
)
from .queue import FIFOQueue, UnorderedQueue  # noqa: E402,F401
