"""Bank model (the classic Jepsen total-balance workload).

A fixed set of named accounts; money moves but is never created or
destroyed. Ops:

- transfer: ``{:f :transfer :value {:from a :to b :amount n}}`` — ok
  iff the source balance covers it (no overdrafts), atomically moving
  ``n``.
- read: ``{:f :read :value {account balance}}`` observing ONE
  account's exact balance on the device path (the single-lane
  constraint, exactly like multi-register); a snapshot read of several
  accounts raises :class:`EncodeError` and the host fallback checks it
  against the full decoded state.

State is one raw int32 balance lane per account (no interning —
transfers are arithmetic), so the default table-independent
``decode_state``/``encode_state`` carry is already correct. The
conservation invariant needs no separate check: every expressible
transition preserves the total, so any history whose reads imply
created/destroyed money simply has no witness and refutes.
"""

from __future__ import annotations

from typing import Optional

from . import EncodeError, Model, UNKNOWN, ValueTable, register_model
from ..history import OK

READ, TRANSFER = 0, 1

_LIMIT = 2**30


def _int(v, what: str) -> int:
    if not isinstance(v, int) or isinstance(v, bool) or abs(v) >= _LIMIT:
        raise EncodeError(f"bank: {what} must be an int32-safe "
                          f"integer, got {v!r}")
    return v


@register_model
class Bank(Model):
    """Fixed accounts, overdraft-refusing transfers, raw balance lanes."""

    name = "bank"
    n_opcodes = 2

    def __init__(self, init: dict):
        if not init:
            raise ValueError("bank needs at least one account")
        self.init = {a: _int(b, f"balance[{a!r}]")
                     for a, b in init.items()}
        self.accounts = sorted(self.init, key=repr)
        self.acct_ids = {a: i for i, a in enumerate(self.accounts)}
        self.state_width = len(self.accounts)

    def cache_key(self):
        return (self.name, self.state_width, self.n_opcodes)

    def cache_args(self):
        return (tuple(sorted(self.init.items(), key=repr)),)

    @classmethod
    def _from_cache_key(cls, args):
        return cls(dict(args[0]))

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return tuple(self.init[a] for a in self.accounts)

    def _acct(self, a) -> int:
        i = self.acct_ids.get(a)
        if i is None:
            raise EncodeError(f"bank: unknown account {a!r}")
        return i

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        f = iv.f
        W = self.state_width
        if f == "transfer":
            v = iv.value_in or {}
            src = self._acct(v.get("from"))
            dst = self._acct(v.get("to"))
            return (TRANSFER, src * W + dst, _int(v.get("amount"), "amount"))
        if f == "read":
            if iv.type != OK:
                return None  # indeterminate read constrains nothing
            v = iv.value_out
            if not isinstance(v, dict) or len(v) != 1:
                raise EncodeError(
                    "bank device path handles single-account reads; "
                    "snapshot reads fall back to host")
            ((a, b),) = v.items()
            return (READ, self._acct(a),
                    UNKNOWN if b is None else _int(b, "balance"))
        raise EncodeError(f"bank: unknown f {f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        W = self.state_width
        if opcode == READ:
            return (a2 == UNKNOWN or state[a1] == a2, state)
        src, dst = divmod(a1, W)
        if state[src] < a2:
            return (False, state)
        new = list(state)
        new[src] -= a2
        new[dst] += a2
        return (True, tuple(new))

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        W = states.shape[-1]
        is_read = opcodes == READ
        # Reads: a1 = account lane, a2 = expected balance.
        cur = jnp.take_along_axis(
            states, (a1s % W)[..., None], axis=-1)[..., 0]
        read_ok = (a2s == UNKNOWN) | (cur == a2s)
        # Transfers: a1 = src*W + dst, a2 = amount.
        src = a1s // W
        dst = a1s % W
        bal_src = jnp.take_along_axis(states, src[..., None], axis=-1)[..., 0]
        xfer_ok = bal_src >= a2s
        lane = jnp.arange(W, dtype=states.dtype)
        move = (~is_read & xfer_ok)[..., None]
        delta = jnp.where(lane == src[..., None], -a2s[..., None], 0) \
            + jnp.where(lane == dst[..., None], a2s[..., None], 0)
        states2 = jnp.where(move, states + delta, states)
        ok = jnp.where(is_read, read_ok, xfer_ok)
        return ok, states2

    def describe_op(self, opcode, a1, a2, table):
        W = self.state_width
        if opcode == READ:
            return (f"read {self.accounts[a1]!r} -> "
                    f"{None if a2 == UNKNOWN else a2}")
        src, dst = divmod(a1, W)
        return (f"transfer {self.accounts[src]!r} -> "
                f"{self.accounts[dst]!r} amount {a2}")
