"""Linearizable set model (add / remove / read-members).

The ingest matrix's ``set`` workload (redis ``SADD``/``SREM``/
``SMEMBERS`` traces, hazelcast-style set tests): ops are
``{:f :add :value e}``, ``{:f :remove :value e}`` (ok iff the element
was present — the observable SREM return), and
``{:f :read :value [members...]}`` observing the *exact* membership.

Encoding: membership is one int32 lane holding a bitmask over interned
element ids — bit ``i`` set ⇔ the element with table id ``i`` is a
member. That keeps the device path a pure bitwise step, at the cost of
a closed element universe: a history touching more than
:data:`MAX_ELEMENTS` distinct elements (table ids ≥ 31, which would
collide with the int32 sign bit and the ``UNKNOWN`` sentinel) is
inexpressible and raises :class:`EncodeError` — the checker's host
fallback takes it. Reads encode their observed membership as the same
bitmask, so a read is one equality.

``decode_state``/``encode_state`` round-trip the mask through the
semantic frozenset-of-members so cross-segment carries survive
re-interning (different segments may assign different ids).
"""

from __future__ import annotations

from typing import Optional

from . import EncodeError, Model, UNKNOWN, ValueTable, register_model
from ..history import OK

ADD, REMOVE, READ = 0, 1, 2

# Bits 0..30: int32-safe, and a full mask can never equal UNKNOWN.
MAX_ELEMENTS = 31


@register_model
class LwSet(Model):
    """A linearizable set over a 31-element interned-id bitmask lane."""

    name = "set"
    state_width = 1
    n_opcodes = 3

    def __init__(self, init=()):
        self.init = frozenset(init)

    def cache_args(self):
        return (tuple(sorted(self.init, key=repr)),)

    @classmethod
    def _from_cache_key(cls, args):
        return cls(args[0])

    def _bit(self, e, table: ValueTable) -> int:
        i = table.intern(e)
        if i >= MAX_ELEMENTS:
            raise EncodeError(
                f"set: more than {MAX_ELEMENTS} distinct elements "
                f"(id {i} for {e!r}) — bitmask lane exhausted")
        return 1 << i

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        mask = 0
        for e in sorted(self.init, key=repr):
            mask |= self._bit(e, table)
        return (mask,)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        f = iv.f
        if f == "add":
            return (ADD, self._bit(iv.value_in, table), 0)
        if f == "remove":
            return (REMOVE, self._bit(iv.value_in, table), 0)
        if f == "read":
            if iv.type != OK:
                return None  # indeterminate read constrains nothing
            v = iv.value_out
            if v is None:
                return (READ, UNKNOWN, 0)
            mask = 0
            for e in v:
                mask |= self._bit(e, table)
            return (READ, mask, 0)
        raise EncodeError(f"set: unknown f {f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        (m,) = state
        if opcode == ADD:
            return (True, (m | a1,))
        if opcode == REMOVE:
            return (bool(m & a1), (m & ~a1,))
        return (a1 == UNKNOWN or m == a1, state)

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        m = states[..., 0]
        is_add = opcodes == ADD
        is_remove = opcodes == REMOVE
        is_read = opcodes == READ
        ok = (
            is_add
            | (is_remove & ((m & a1s) != 0))
            | (is_read & ((a1s == UNKNOWN) | (m == a1s)))
        )
        m2 = jnp.where(is_add, m | a1s,
                       jnp.where(is_remove, m & ~a1s, m))
        return ok, m2[..., None]

    def decode_state(self, state, table):
        m = int(state[0])
        return (frozenset(table.lookup(i) for i in range(MAX_ELEMENTS)
                          if m & (1 << i) and i < len(table)),)

    def encode_state(self, decoded, table):
        mask = 0
        for e in sorted(decoded[0], key=repr):
            mask |= self._bit(e, table)
        return (mask,)

    def describe_op(self, opcode, a1, a2, table):
        if opcode == READ:
            if a1 == UNKNOWN:
                return "read -> ?"
            members = [table.lookup(i) for i in range(MAX_ELEMENTS)
                       if a1 & (1 << i) and i < len(table)]
            return f"read -> {members!r}"
        i = a1.bit_length() - 1
        e = table.lookup(i) if i < len(table) else f"bit{i}"
        return f"{'add' if opcode == ADD else 'remove'} {e!r}"
