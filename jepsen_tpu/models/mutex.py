"""Mutual-exclusion and semaphore models.

Equivalents of knossos ``model/mutex`` (consumed by the reference's
hazelcast suite, hazelcast/src/jepsen/hazelcast.clj:674-675) and the
hazelcast suite's custom CP-subsystem models (hazelcast.clj:515-649):
ReentrantMutex, OwnerAwareMutex, FencedMutex, AcquiredPermitsModel
(here: :class:`Semaphore`).

Op shapes: ``{:f :acquire}`` / ``{:f :release}``; fenced locks observe the
fence token as the ok-acquire's value; semaphores carry the permit count as
the op value (default 1).
"""

from __future__ import annotations

from typing import Optional

from . import EncodeError, Model, UNKNOWN, ValueTable, register_model
from ..history import OK

ACQUIRE, RELEASE = 0, 1


def _count(iv) -> int:
    v = iv.value_in if iv.value_in is not None else 1
    if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
        raise EncodeError(f"permit count must be a positive int, got {v!r}")
    return v


@register_model
class Mutex(Model):
    """knossos model/mutex: acquire fails when held, release fails when free."""

    name = "mutex"
    state_width = 1
    n_opcodes = 2

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return (0,)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        if iv.f == "acquire":
            return (ACQUIRE, 0, 0)
        if iv.f == "release":
            return (RELEASE, 0, 0)
        raise EncodeError(f"mutex: unknown f {iv.f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        (locked,) = state
        if opcode == ACQUIRE:
            return (locked == 0, (1,))
        return (locked == 1, (0,))

    def step_jax(self, states, opcodes, a1s, a2s):
        locked = states[..., 0]
        is_acq = opcodes == ACQUIRE
        ok = (is_acq & (locked == 0)) | (~is_acq & (locked == 1))
        locked2 = (is_acq).astype(states.dtype)
        return ok, locked2[..., None]

    def describe_op(self, opcode, a1, a2, table):
        return "acquire" if opcode == ACQUIRE else "release"


@register_model
class OwnerAwareMutex(Model):
    """Mutex whose release is only legal from the process holding it
    (hazelcast.clj:538-557). State lane = owner-id + 1, 0 when free."""

    name = "owner-aware-mutex"
    state_width = 1
    n_opcodes = 2

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return (0,)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        p = table.intern(("process", iv.process))
        if iv.f == "acquire":
            return (ACQUIRE, p, 0)
        if iv.f == "release":
            return (RELEASE, p, 0)
        raise EncodeError(f"owner-aware-mutex: unknown f {iv.f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        (owner,) = state
        if opcode == ACQUIRE:
            return (owner == 0, (a1 + 1,))
        return (owner == a1 + 1, (0,))

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        owner = states[..., 0]
        is_acq = opcodes == ACQUIRE
        ok = (is_acq & (owner == 0)) | (~is_acq & (owner == a1s + 1))
        owner2 = jnp.where(is_acq, a1s + 1, 0)
        return ok, owner2[..., None]

    # The owner lane embeds an interned value id, so cross-table state
    # carry (jepsen_tpu.online) must round-trip through the semantic
    # owner: None when free, the ("process", p) tuple when held.
    def decode_state(self, state, table):
        owner = int(state[0])
        return (table.lookup(owner - 1) if owner else None,)

    def encode_state(self, decoded, table):
        (owner,) = decoded
        return (0 if owner is None else table.intern(owner) + 1,)

    def describe_op(self, opcode, a1, a2, table):
        verb = "acquire" if opcode == ACQUIRE else "release"
        return f"{verb} by {table.lookup(a1)!r}"


@register_model
class ReentrantMutex(Model):
    """A lock the same holder may take up to ``max_depth`` times
    (hazelcast.clj:515-534; hazelcast CP locks allow depth 2)."""

    name = "reentrant-mutex"
    state_width = 1
    n_opcodes = 2

    def __init__(self, max_depth: int = 2):
        self.max_depth = max_depth

    def cache_args(self):
        return (self.max_depth,)

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return (0,)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        if iv.f == "acquire":
            return (ACQUIRE, 0, 0)
        if iv.f == "release":
            return (RELEASE, 0, 0)
        raise EncodeError(f"reentrant-mutex: unknown f {iv.f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        (depth,) = state
        if opcode == ACQUIRE:
            return (depth < self.max_depth, (depth + 1,))
        return (depth > 0, (max(depth - 1, 0),))

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        depth = states[..., 0]
        is_acq = opcodes == ACQUIRE
        ok = (is_acq & (depth < self.max_depth)) | (~is_acq & (depth > 0))
        depth2 = jnp.where(is_acq, depth + 1, jnp.maximum(depth - 1, 0))
        return ok, depth2[..., None]

    def describe_op(self, opcode, a1, a2, table):
        return "acquire" if opcode == ACQUIRE else "release"


@register_model
class FencedMutex(Model):
    """Owner-aware mutex whose successful acquires observe strictly
    increasing fence tokens (hazelcast.clj:565-586). State lanes:
    [owner+1, last-fence]. The fence is the raw int token from the ok
    acquire's value (UNKNOWN when unobserved)."""

    name = "fenced-mutex"
    state_width = 2
    n_opcodes = 2

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return (0, -1)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        p = table.intern(("process", iv.process))
        if iv.f == "acquire":
            fence = iv.value_out if iv.type == OK else None
            if fence is None:
                return (ACQUIRE, p, UNKNOWN)
            if not isinstance(fence, int) or isinstance(fence, bool) or fence < 0:
                raise EncodeError(f"fence token must be a non-negative int, got {fence!r}")
            return (ACQUIRE, p, fence)
        if iv.f == "release":
            return (RELEASE, p, 0)
        raise EncodeError(f"fenced-mutex: unknown f {iv.f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        owner, last = state
        if opcode == ACQUIRE:
            ok = owner == 0 and (a2 == UNKNOWN or a2 > last)
            new_last = last if a2 == UNKNOWN else a2
            return (ok, (a1 + 1, new_last))
        return (owner == a1 + 1, (0, last))

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        owner, last = states[..., 0], states[..., 1]
        is_acq = opcodes == ACQUIRE
        fence_ok = (a2s == UNKNOWN) | (a2s > last)
        ok = (is_acq & (owner == 0) & fence_ok) | (~is_acq & (owner == a1s + 1))
        owner2 = jnp.where(is_acq, a1s + 1, 0)
        last2 = jnp.where(is_acq & (a2s != UNKNOWN), a2s, last)
        return ok, jnp.stack([owner2, last2], axis=-1)

    # Owner lane is an interned value id; the fence lane is a raw int.
    def decode_state(self, state, table):
        owner, last = (int(x) for x in state)
        return (table.lookup(owner - 1) if owner else None, last)

    def encode_state(self, decoded, table):
        owner, last = decoded
        return ((0 if owner is None else table.intern(owner) + 1),
                int(last))

    def describe_op(self, opcode, a1, a2, table):
        if opcode == ACQUIRE:
            fence = "?" if a2 == UNKNOWN else a2
            return f"acquire (fence {fence}) by {table.lookup(a1)!r}"
        return f"release by {table.lookup(a1)!r}"


@register_model
class ReentrantFencedMutex(Model):
    """Reentrant fenced mutex: up to two holds by one owner, fences
    monotone over the highest observed fence (hazelcast.clj:590-626,
    ReentrantFencedMutex; lock-acquire limit 2). State lanes:
    [owner+1, lock-count, current-fence, highest-observed-fence]; fences
    are raw ints with UNKNOWN for acquires whose token wasn't observed,
    and highest-observed starts at -1 so any real fence exceeds it."""

    name = "reentrant-fenced-mutex"
    state_width = 4
    n_opcodes = 2
    LOCK_LIMIT = 2

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return (0, 0, UNKNOWN, -1)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        p = table.intern(("process", iv.process))
        if iv.f == "acquire":
            fence = iv.value_out if iv.type == OK else None
            if fence is None:
                return (ACQUIRE, p, UNKNOWN)
            if not isinstance(fence, int) or isinstance(fence, bool) or fence < 0:
                raise EncodeError(
                    f"fence token must be a non-negative int, got {fence!r}")
            return (ACQUIRE, p, fence)
        if iv.f == "release":
            return (RELEASE, p, 0)
        raise EncodeError(f"reentrant-fenced-mutex: unknown f {iv.f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        owner, count, cur, hof = state
        client = a1 + 1
        f = a2
        if opcode == ACQUIRE:
            if owner == 0:
                ok = f == UNKNOWN or f > hof
                hof2 = hof if f == UNKNOWN else max(f, hof)
                return (ok, (client, 1, f, hof2))
            if owner != client or count >= self.LOCK_LIMIT:
                return (False, state)
            if cur == UNKNOWN:
                ok = f == UNKNOWN or f > hof
                hof2 = hof if f == UNKNOWN else max(f, hof)
                return (ok, (client, count + 1, f, hof2))
            if f == UNKNOWN or f == cur:
                return (True, (client, count + 1, cur, hof))
            return (False, state)
        # release
        if owner == 0 or owner != client:
            return (False, state)
        if count == 1:
            return (True, (0, 0, UNKNOWN, hof))
        return (True, (owner, count - 1, cur, hof))

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        owner = states[..., 0]
        count = states[..., 1]
        cur = states[..., 2]
        hof = states[..., 3]
        client = a1s + 1
        f = a2s
        is_acq = opcodes == ACQUIRE
        f_known = f != UNKNOWN
        fresh_ok = ~f_known | (f > hof)

        # Case 1: unheld acquire.
        c1 = is_acq & (owner == 0)
        # Case 2: reacquire with unfenced current hold.
        c2 = is_acq & (owner == client) & (count < self.LOCK_LIMIT) & (
            cur == UNKNOWN)
        # Case 3: reacquire with fenced hold: same-or-unknown fence.
        c3 = is_acq & (owner == client) & (count < self.LOCK_LIMIT) & (
            cur != UNKNOWN) & (~f_known | (f == cur))
        rel_ok = ~is_acq & (owner == client) & (owner != 0)

        ok = (c1 & fresh_ok) | (c2 & fresh_ok) | c3 | rel_ok

        hof2 = jnp.where((c1 | c2) & f_known, jnp.maximum(f, hof), hof)
        owner2 = jnp.where(is_acq, client,
                           jnp.where(count == 1, 0, owner))
        count2 = jnp.where(c1, 1,
                           jnp.where(c2 | c3, count + 1,
                                     jnp.maximum(count - 1, 0)))
        cur2 = jnp.where(c1 | c2, f,
                         jnp.where(c3, cur,
                                   jnp.where(count == 1,
                                             jnp.int32(UNKNOWN), cur)))
        return ok, jnp.stack([owner2, count2, cur2, hof2], axis=-1)

    # Owner lane is an interned value id; count and both fence lanes
    # are raw ints (UNKNOWN/-1 sentinels included).
    def decode_state(self, state, table):
        owner, count, cur, hof = (int(x) for x in state)
        return (table.lookup(owner - 1) if owner else None, count, cur,
                hof)

    def encode_state(self, decoded, table):
        owner, count, cur, hof = decoded
        return ((0 if owner is None else table.intern(owner) + 1),
                int(count), int(cur), int(hof))

    def describe_op(self, opcode, a1, a2, table):
        if opcode == ACQUIRE:
            fence = "?" if a2 == UNKNOWN else a2
            return f"acquire (fence {fence}) by {table.lookup(a1)!r}"
        return f"release by {table.lookup(a1)!r}"


@register_model
class Semaphore(Model):
    """Counting semaphore with ``capacity`` permits (hazelcast
    AcquiredPermitsModel, hazelcast.clj:630-649). Op value = permit count."""

    name = "semaphore"
    state_width = 1
    n_opcodes = 2

    def __init__(self, capacity: int = 1):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity

    def cache_args(self):
        return (self.capacity,)

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return (0,)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        n = _count(iv)
        if iv.f == "acquire":
            return (ACQUIRE, n, 0)
        if iv.f == "release":
            return (RELEASE, n, 0)
        raise EncodeError(f"semaphore: unknown f {iv.f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        (acquired,) = state
        if opcode == ACQUIRE:
            return (acquired + a1 <= self.capacity, (acquired + a1,))
        return (acquired >= a1, (max(acquired - a1, 0),))

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        acquired = states[..., 0]
        is_acq = opcodes == ACQUIRE
        ok = (is_acq & (acquired + a1s <= self.capacity)) | (~is_acq & (acquired >= a1s))
        acq2 = jnp.where(is_acq, acquired + a1s, jnp.maximum(acquired - a1s, 0))
        return ok, acq2[..., None]

    def describe_op(self, opcode, a1, a2, table):
        verb = "acquire" if opcode == ACQUIRE else "release"
        return f"{verb} {a1} permit(s)"
