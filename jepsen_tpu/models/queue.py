"""Queue models (host-only).

Equivalents of knossos ``model/unordered-queue`` / ``model/fifo-queue``
(used by the reference's rabbitmq/disque-style queue workloads alongside
jepsen.checker/queue, checker.clj:215-235). Queue state is unbounded, so
these models don't pack into fixed int32 lanes; they run on the host WGL
checker only (``device_capable = False``) — the cheap queue *invariant*
checkers (jepsen_tpu.checker.invariants) cover the vectorized path.

Op shapes: ``{:f :enqueue :value v}``, ``{:f :dequeue :value v}`` (value
observed at completion).
"""

from __future__ import annotations

from typing import Optional

from . import EncodeError, Model, UNKNOWN, ValueTable, register_model
from ..history import OK

ENQUEUE, DEQUEUE = 0, 1


@register_model
class UnorderedQueue(Model):
    """A multiset queue: dequeue may return any enqueued element."""

    name = "unordered-queue"
    device_capable = False
    n_opcodes = 2

    def init_state(self, table: ValueTable) -> tuple:
        return ()

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        if iv.f == "enqueue":
            return (ENQUEUE, table.intern(iv.value_in), 0)
        if iv.f == "dequeue":
            if iv.type != OK:
                return None  # indeterminate dequeue observes nothing
            return (DEQUEUE, table.intern(iv.value_out), 0)
        raise EncodeError(f"queue: unknown f {iv.f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        if opcode == ENQUEUE:
            return (True, tuple(sorted(state + (a1,))))
        if a1 in state:
            out = list(state)
            out.remove(a1)
            return (True, tuple(out))
        return (False, state)

    def decode_state(self, state, table):
        return tuple(table.lookup(int(x)) for x in state)

    def encode_state(self, decoded, table):
        return tuple(table.intern(v) for v in decoded)

    def describe_op(self, opcode, a1, a2, table):
        verb = "enqueue" if opcode == ENQUEUE else "dequeue"
        return f"{verb} {table.lookup(a1)!r}"


@register_model
class FIFOQueue(UnorderedQueue):
    """A strict FIFO queue: dequeue must return the head."""

    name = "fifo-queue"

    def step_scalar(self, state, opcode, a1, a2):
        if opcode == ENQUEUE:
            return (True, state + (a1,))
        if state and state[0] == a1:
            return (True, state[1:])
        return (False, state)
