"""PN-counter model (grow/shrink counter with exact reads).

The ingest matrix's ``counter`` workload (redis ``INCR``/``INCRBY``
traces): ops are ``{:f :add :value delta}`` (signed) and
``{:f :read :value observed}``. Unlike the reference's eventually-
consistent counter checker this is a *linearizable* counter — a read
must observe exactly the sum of the adds linearized before it, which
is what a single-node redis or an etcd-backed counter actually
promises.

State is the raw running total in one int32 lane (no interning —
arithmetic needs the real value), so the default table-independent
``decode_state``/``encode_state`` carry is already correct.
"""

from __future__ import annotations

from typing import Optional

from . import EncodeError, Model, UNKNOWN, ValueTable, register_model
from ..history import OK

READ, ADD = 0, 1

# Raw lane arithmetic must stay inside int32 (and clear of UNKNOWN).
_LIMIT = 2**30


def _int(v, what: str) -> int:
    if not isinstance(v, int) or isinstance(v, bool) or abs(v) >= _LIMIT:
        raise EncodeError(f"counter: {what} must be an int32-safe "
                          f"integer, got {v!r}")
    return v


@register_model
class Counter(Model):
    """A linearizable add/read counter over one raw int lane."""

    name = "counter"
    state_width = 1
    n_opcodes = 2

    def __init__(self, init: int = 0):
        self.init = _int(init, "init")

    def cache_args(self):
        return (self.init,)

    def init_state(self, table: ValueTable) -> tuple[int, ...]:
        return (self.init,)

    def encode_op(self, iv, table: ValueTable) -> Optional[tuple[int, int, int]]:
        f = iv.f
        if f == "read":
            if iv.type != OK:
                return None  # indeterminate read constrains nothing
            v = iv.value_out
            return (READ, UNKNOWN if v is None else _int(v, "read"), 0)
        if f == "add":
            return (ADD, _int(iv.value_in, "delta"), 0)
        raise EncodeError(f"counter: unknown f {f!r}")

    def step_scalar(self, state, opcode, a1, a2):
        (v,) = state
        if opcode == READ:
            return (a1 == UNKNOWN or v == a1, state)
        return (True, (v + a1,))

    def step_jax(self, states, opcodes, a1s, a2s):
        import jax.numpy as jnp

        v = states[..., 0]
        is_read = opcodes == READ
        ok = jnp.where(is_read, (a1s == UNKNOWN) | (v == a1s), True)
        v2 = jnp.where(is_read, v, v + a1s)
        return ok, v2[..., None]

    def describe_op(self, opcode, a1, a2, table):
        if opcode == READ:
            return f"read -> {None if a1 == UNKNOWN else a1}"
        return f"add {a1:+d}"
