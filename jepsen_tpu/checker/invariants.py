"""O(n) invariant checkers (the reference's cheap checker family,
jepsen/src/jepsen/checker.clj:163-792).

These are host-side but vectorized with numpy where the access pattern pays
(counter bound tracking, set-full per-element timelines); the heavy search
checkers (linearizable, txn cycles) live on the device path instead.

History op shapes follow the reference workloads:

- set:         {:f :add :value v} / final {:f :read :value #{...}}
- set-full:    adds + many reads returning the full set
- queue:       {:f :enqueue|:dequeue :value v}, optional {:f :drain}
- unique-ids:  {:f :generate} -> ok :value id
- counter:     {:f :add :value n>=0} / {:f :read :value n}
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Optional

import numpy as np

from . import Checker, checker_fn, merge_valid
from ..history import History
from ..util import integer_interval_set_str


def _client_ops(history: History):
    return list(history.client_ops())


# ---------------------------------------------------------------------------
# queue (model-folding; checker.clj:215-235)


def queue(model=None) -> Checker:
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded and only ok dequeues succeeded, fold through the queue
    model (default unordered). O(n)."""

    def chk(test, history, opts):
        from ..models import UnorderedQueue, ValueTable
        from ..models.queue import DEQUEUE, ENQUEUE

        m = model or UnorderedQueue()
        table = ValueTable()
        state = m.init_state(table)
        for op in _client_ops(history):
            if op.f == "enqueue" and op.is_invoke:
                ok, state = m.step_scalar(state, ENQUEUE, table.intern(op.value), 0)
            elif op.f == "dequeue" and op.is_ok:
                ok, state = m.step_scalar(state, DEQUEUE, table.intern(op.value), 0)
            else:
                continue
            if not ok:
                return {
                    "valid": False,
                    "error": f"can't dequeue {op.value!r}",
                }
        return {
            "valid": True,
            "final_queue": [table.lookup(i) for i in state],
        }

    return checker_fn(chk, "queue")


# ---------------------------------------------------------------------------
# set (checker.clj:237-288)


def set_checker() -> Checker:
    """Adds followed by a final read: every acknowledged add must be read;
    only attempted elements may appear."""

    def chk(test, history, opts):
        attempts, adds = set(), set()
        final_read = None
        for op in _client_ops(history):
            if op.f == "add" and op.is_invoke:
                attempts.add(op.value)
            elif op.f == "add" and op.is_ok:
                adds.add(op.value)
            elif op.f == "read" and op.is_ok:
                final_read = op.value
        if final_read is None:
            return {"valid": "unknown", "error": "set was never read"}
        final = set(final_read)
        ok = final & attempts
        unexpected = final - attempts
        lost = adds - final
        recovered = ok - adds
        return {
            "valid": not lost and not unexpected,
            "attempt_count": len(attempts),
            "acknowledged_count": len(adds),
            "ok_count": len(ok),
            "lost_count": len(lost),
            "recovered_count": len(recovered),
            "unexpected_count": len(unexpected),
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
        }

    return checker_fn(chk, "set")


# ---------------------------------------------------------------------------
# set-full (checker.clj:291-589) — vectorized per-element timelines


def _quantiles(points, xs) -> Optional[dict]:
    xs = sorted(xs)
    if not xs:
        return None
    n = len(xs)
    return {p: xs[min(n - 1, int(n * p))] for p in points}


def set_full(checker_opts: Optional[dict] = None, **kw) -> Checker:
    """Per-element stable/lost/never-read timeline analysis.

    For each added element, find the *known* time (add completion or first
    observing read, whichever completes first), the last read invocation
    that observed it and the last ok-read invocation that missed it; an
    element is *stable* when no miss follows the final observation, *lost*
    when a miss follows both the observation and the known point, and
    *never-read* otherwise. Latencies are known->stable / known->lost in
    ms, reported as quantile maps. ``linearizable=True`` additionally fails
    stale (nonzero-stable-latency) elements.

    One divergence from checker.clj:562-570 noted: duplicate detection
    there compares multiplicities `< 1` (unreachable); here a value
    appearing more than once in a single read is a duplicate, as the
    surrounding docs intend.
    """
    o = dict(checker_opts or {})
    o.update(kw)
    linearizable = bool(o.get("linearizable", False))

    def chk(test, history, opts):
        ops = _client_ops(history)
        # Element table (one row per attempted add).
        elem_ids: dict[Any, int] = {}
        add_ok_idx: list[float] = []
        add_ok_time: list[float] = []
        add_ok_op: list[Any] = []
        for op in ops:
            if op.f == "add" and op.is_invoke and op.value not in elem_ids:
                elem_ids[op.value] = len(elem_ids)
                add_ok_idx.append(np.inf)
                add_ok_time.append(np.inf)
                add_ok_op.append(None)
        for op in ops:
            if op.f == "add" and op.is_ok and op.value in elem_ids:
                e = elem_ids[op.value]
                if op.index < add_ok_idx[e]:
                    add_ok_idx[e] = op.index
                    add_ok_time[e] = op.time
                    add_ok_op[e] = op
        E = len(elem_ids)

        # Ok reads, paired with their invocations.
        pending: dict[Any, Any] = {}
        reads = []  # (inv_idx, inv_time, ret_idx, ret_time, member-ids, dups)
        dups: Counter = Counter()
        for op in ops:
            if op.f != "read":
                continue
            if op.is_invoke:
                pending[op.process] = op
            elif op.is_fail:
                pending.pop(op.process, None)
            elif op.is_ok:
                inv = pending.pop(op.process, None)
                vals = op.value or []
                freq = Counter(vals)
                for v, c in freq.items():
                    if c > 1:
                        dups[v] = max(dups[v], c)
                members = {elem_ids[v] for v in freq if v in elem_ids}
                reads.append(
                    (
                        op.index if inv is None else inv.index,
                        op.time if inv is None else inv.time,
                        op.index,
                        op.time,
                        members,
                        inv if inv is not None else op,
                        op,
                    )
                )
        R = len(reads)

        last_present_idx = np.full(E, -1.0)
        last_present_time = np.full(E, -1.0)
        last_absent_idx = np.full(E, -1.0)
        last_absent_time = np.full(E, -1.0)
        first_obs_idx = np.full(E, np.inf)
        first_obs_time = np.full(E, np.inf)
        if E and R:
            # Chunk over reads so memory stays O(chunk * E) rather than
            # O(R * E): running E-wide max/min reductions across chunks.
            inv_idx = np.array([r[0] for r in reads], float)
            inv_time = np.array([r[1] for r in reads], float)
            ret_idx = np.array([r[2] for r in reads], float)
            ret_time = np.array([r[3] for r in reads], float)
            chunk = max(1, min(R, (1 << 24) // max(E, 1)))
            for lo in range(0, R, chunk):
                hi = min(lo + chunk, R)
                member = np.zeros((hi - lo, E), dtype=bool)
                for r in range(lo, hi):
                    members = reads[r][4]
                    if members:
                        member[r - lo, list(members)] = True
                ci, ct = inv_idx[lo:hi], inv_time[lo:hi]
                cri, crt = ret_idx[lo:hi], ret_time[lo:hi]
                pres = np.where(member, ci[:, None], -1.0)
                rbest = pres.argmax(axis=0)
                cmax = pres.max(axis=0)
                upd = cmax > last_present_idx
                last_present_idx = np.where(upd, cmax, last_present_idx)
                last_present_time = np.where(upd, ct[rbest], last_present_time)
                absn = np.where(~member, ci[:, None], -1.0)
                rabs = absn.argmax(axis=0)
                amax = absn.max(axis=0)
                upd = amax > last_absent_idx
                last_absent_idx = np.where(upd, amax, last_absent_idx)
                last_absent_time = np.where(upd, ct[rabs], last_absent_time)
                obs = np.where(member, cri[:, None], np.inf)
                robs = obs.argmin(axis=0)
                omin = obs.min(axis=0)
                upd = omin < first_obs_idx
                first_obs_idx = np.where(upd, omin, first_obs_idx)
                first_obs_time = np.where(upd, crt[robs], first_obs_time)

        add_ok_idx_a = np.array(add_ok_idx, float) if E else np.zeros(0)
        add_ok_time_a = np.array(add_ok_time, float) if E else np.zeros(0)
        known_idx = np.minimum(add_ok_idx_a, first_obs_idx)
        known_time = np.where(
            add_ok_idx_a <= first_obs_idx, add_ok_time_a, first_obs_time
        )
        known = np.isfinite(known_idx)

        stable = (last_present_idx >= 0) & (last_absent_idx < last_present_idx)
        lost = (
            known
            & (last_absent_idx >= 0)
            & (last_present_idx < last_absent_idx)
            & (known_idx < last_absent_idx)
        )
        never_read = ~(stable | lost)

        stable_time = np.where(last_absent_idx >= 0, last_absent_time + 1, 0.0)
        lost_time = np.where(last_present_idx >= 0, last_present_time + 1, 0.0)
        to_ms = lambda ns: int(max(ns, 0) // 1_000_000)
        elems = list(elem_ids)
        stable_lat = {
            elems[e]: to_ms(stable_time[e] - known_time[e])
            for e in np.flatnonzero(stable & known)
        }
        lost_lat = {
            elems[e]: to_ms(lost_time[e] - known_time[e])
            for e in np.flatnonzero(lost)
        }
        stale = sorted(
            (e for e, l in stable_lat.items() if l > 0), key=lambda e: stable_lat[e]
        )

        def known_op(e):
            if add_ok_idx_a[e] <= first_obs_idx[e]:
                return add_ok_op[e]
            return reads[int(robs[e])][6] if R else None

        def last_absent_op(e):
            return reads[int(rabs[e])][5] if R and last_absent_idx[e] >= 0 else None

        worst_stale = [
            {
                "element": e,
                "known": known_op(elem_ids[e]),
                "last_absent": last_absent_op(elem_ids[e]),
                "outcome": "stable",
                "stable_latency": stable_lat[e],
                "lost_latency": None,
            }
            for e in sorted(stale, key=lambda e: -stable_lat[e])[:8]
        ]

        n_stable = int(stable.sum())
        n_lost = int(lost.sum())
        valid: Any = True
        if n_lost > 0:
            valid = False
        elif n_stable == 0:
            valid = "unknown"
        elif linearizable and stale:
            valid = False
        points = [0, 0.5, 0.95, 0.99, 1]
        out = {
            "valid": False if dups else valid,
            "attempt_count": E,
            "stable_count": n_stable,
            "lost_count": n_lost,
            "lost": sorted(elems[e] for e in np.flatnonzero(lost)),
            "never_read_count": int(never_read.sum()),
            "never_read": sorted(elems[e] for e in np.flatnonzero(never_read)),
            "stale_count": len(stale),
            "stale": sorted(stale),
            "worst_stale": worst_stale,
            "duplicated_count": len(dups),
            "duplicated": dict(dups),
        }
        if stable_lat:
            out["stable_latencies"] = _quantiles(points, stable_lat.values())
        if lost_lat:
            out["lost_latencies"] = _quantiles(points, lost_lat.values())
        return out

    return checker_fn(chk, "set-full")


# ---------------------------------------------------------------------------
# total-queue (checker.clj:590-684) — multiset accounting


def _expand_drains(ops):
    """Expand ok :drain ops (value = list of elements) into dequeue
    invoke/ok pairs (checker.clj:590-620)."""
    out = []
    for op in ops:
        if op.f != "drain":
            out.append(op)
        elif op.is_invoke or op.is_fail:
            continue
        elif op.is_ok:
            for element in op.value or []:
                out.append(op.with_(type="invoke", f="dequeue", value=None))
                out.append(op.with_(type="ok", f="dequeue", value=element))
        else:
            raise ValueError(f"can't handle a crashed drain operation: {op!r}")
    return out


def total_queue() -> Checker:
    """What goes in must come out (given a full drain): every successful
    enqueue has a successful dequeue; no dequeues from nowhere."""

    def chk(test, history, opts):
        ops = _expand_drains(_client_ops(history))
        attempts: Counter = Counter()
        enqueues: Counter = Counter()
        dequeues: Counter = Counter()
        for op in ops:
            if op.f == "enqueue" and op.is_invoke:
                attempts[op.value] += 1
            elif op.f == "enqueue" and op.is_ok:
                enqueues[op.value] += 1
            elif op.f == "dequeue" and op.is_ok:
                dequeues[op.value] += 1
        ok = dequeues & attempts
        unexpected = Counter(
            {v: c for v, c in dequeues.items() if v not in attempts}
        )
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        return {
            "valid": not lost and not unexpected,
            "attempt_count": sum(attempts.values()),
            "acknowledged_count": sum(enqueues.values()),
            "ok_count": sum(ok.values()),
            "unexpected_count": sum(unexpected.values()),
            "duplicated_count": sum(duplicated.values()),
            "lost_count": sum(lost.values()),
            "recovered_count": sum(recovered.values()),
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
        }

    return checker_fn(chk, "total-queue")


# ---------------------------------------------------------------------------
# unique-ids (checker.clj:686-731)


def unique_ids() -> Checker:
    """A unique-id generator must actually emit unique ids."""

    def chk(test, history, opts):
        attempted = 0
        acks = []
        for op in _client_ops(history):
            if op.f != "generate":
                continue
            if op.is_invoke:
                attempted += 1
            elif op.is_ok:
                acks.append(op.value)
        counts = Counter(acks)
        dups = {v: c for v, c in counts.items() if c > 1}
        rng = [min(acks), max(acks)] if acks else None
        return {
            "valid": not dups,
            "attempted_count": attempted,
            "acknowledged_count": len(acks),
            "duplicated_count": len(dups),
            "duplicated": dict(
                sorted(dups.items(), key=lambda kv: -kv[1])[:48]
            ),
            "range": rng,
        }

    return checker_fn(chk, "unique-ids")


# ---------------------------------------------------------------------------
# counter (checker.clj:734-792) — vectorized bound tracking


def counter() -> Checker:
    """A monotonically-increasing counter: each read must land within
    [sum of ok increments at its invocation, sum of attempted increments at
    its completion].

    Vectorized: two prefix sums over the completed history (attempted
    increments at add-invokes, acknowledged increments at add-oks), then a
    gather per read pair — no per-op Python loop."""

    def chk(test, history, opts):
        ops = [op for op in history.complete() if op.is_client]
        n = len(ops)
        d_upper = np.zeros(n)
        d_lower = np.zeros(n)
        read_pairs = []  # (inv_pos, ok_pos, value)
        pending_inv: dict[Any, int] = {}
        pending_read: dict[Any, int] = {}
        for i, op in enumerate(ops):
            if op.f == "add":
                if op.is_invoke:
                    if op.value < 0:
                        raise ValueError("counter: negative add")
                    pending_inv[op.process] = i
                    d_upper[i] = op.value
                elif op.is_ok:
                    d_lower[i] = op.value
                elif op.is_fail:
                    # Un-count the attempted increment of a failed add.
                    j = pending_inv.pop(op.process, None)
                    if j is not None:
                        d_upper[j] = 0
            elif op.f == "read":
                if op.is_invoke:
                    pending_read[op.process] = i
                elif op.is_ok:
                    j = pending_read.pop(op.process, None)
                    if j is not None:
                        read_pairs.append((j, i, op.value))
                else:
                    pending_read.pop(op.process, None)
        cum_upper = np.cumsum(d_upper)
        cum_lower = np.cumsum(d_lower)
        reads = [
            [float(cum_lower[j]), v, float(cum_upper[i])] for j, i, v in read_pairs
        ]
        errors = [r for r in reads if not (r[0] <= r[1] <= r[2])]
        return {"valid": not errors, "reads": reads, "errors": errors}

    return checker_fn(chk, "counter")
