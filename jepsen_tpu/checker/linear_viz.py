"""Failure-witness rendering for linearizability refutations.

The reference renders the search's final configurations to ``linear.svg``
when a history is NOT linearizable (checker.clj:202-209, via
knossos.linear.report) — for a testing tool the *explanation* is the
product. This module renders the ``stuck_configs`` carried by all three
engines' refutations (native C DFS witness capture, device-kernel final
frontier, host oracle) into:

- ``linear.txt`` — a plain-text report: deepest configurations, model
  state, and why each pending op cannot extend the linearization;
- ``linear.svg`` — a per-process timeline around the stuck point:
  linearized ops, the pending ops that could not linearize (colored by
  reason), and open (:info) ops.

Both are written into the test's store directory by the ``linearizable``
checker (jepsen_tpu.checker.linearizable).
"""

from __future__ import annotations

from typing import Optional

from ..ops.encode import OPEN, encode_history

# Palette (matches the tutorial's timeline colors).
_C_LIN = "#78a878"       # linearized
_C_REJECT = "#c24f4f"    # pending, model rejects
_C_BLOCKED = "#d99a3d"   # pending, real-time blocked
_C_EXPLORED = "#7d7dc2"  # pending, all continuations explored
_C_OPEN = "#9a9a9a"      # open (:info), not linearized
_C_OTHER = "#d8d8d8"     # other unlinearized ops


def _pending_color(why: str) -> str:
    if why.startswith("real-time-blocked"):
        return _C_BLOCKED
    if why.startswith("model rejects"):
        return _C_REJECT
    return _C_EXPLORED


def failure_report(model, history_ops, res: dict) -> str:
    """Plain-text refutation explanation from a checker result map."""
    lines = [
        "Linearizability refuted.",
        f"  op count:        {res.get('op_count')}",
        f"  max linearized:  {res.get('max_linearized')}",
        f"  engine:          "
        f"{res.get('backend') or ('device' if res.get('device') else 'native' if res.get('native') else 'host')}",
        "",
    ]
    stuck = res.get("stuck_configs") or []
    if not stuck:
        lines.append("(no witness captured)")
        return "\n".join(lines)
    lines.append(f"Deepest configurations reached ({len(stuck)} shown):")
    for i, cfg in enumerate(stuck):
        lines.append(f"\nconfig {i}: state={cfg.get('state')} "
                     f"({len(cfg.get('linearized') or [])} ops linearized)")
        for p in cfg.get("pending") or []:
            if isinstance(p, dict):
                lines.append(f"  cannot linearize {p.get('op')}")
                lines.append(f"    because: {p.get('why')}")
            else:  # host-oracle entries are plain strings
                lines.append(f"  pending: {p}")
    return "\n".join(lines)


def render_linear_svg(model, history_ops, res: dict,
                      path: Optional[str] = None,
                      context_ops: int = 14) -> str:
    """Render the first stuck configuration as a per-process timeline
    SVG around the stuck point; returns the SVG text (and writes it to
    ``path`` when given)."""
    stuck = (res.get("stuck_configs") or [{}])[0]
    enc = encode_history(model, history_ops)
    n = enc.n
    lin = set(stuck.get("linearized") or [])
    pending = {p["row"]: p["why"] for p in (stuck.get("pending") or [])
               if isinstance(p, dict)}

    # Focus window: rows around the earliest pending op.
    anchor = min(pending) if pending else max(lin) if lin else 0
    lo = max(0, anchor - context_ops)
    hi = min(n, anchor + context_ops + 1)
    rows = [i for i in range(lo, hi)]
    procs = []
    for i in rows:
        pr = enc.intervals[i].process
        if pr not in procs:
            procs.append(pr)

    x0, y0, lane_h, px = 160, 40, 26, 9.0
    t_lo = int(enc.inv[rows[0]])
    t_hi = max(int(enc.ret[i]) if enc.ret[i] != OPEN else int(enc.inv[i]) + 4
               for i in rows)
    width = x0 + int((t_hi - t_lo + 2) * px) + 40
    height = y0 + lane_h * len(procs) + 70

    def esc(s):
        return (str(s).replace("&", "&amp;").replace("<", "&lt;")
                .replace(">", "&gt;"))

    svg = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{height}" font-family="monospace" font-size="11">',
        f'<text x="8" y="18" font-size="13">not linearizable — state '
        f'{esc(stuck.get("state"))}, {len(lin)} ops linearized '
        f'(showing ops {lo}..{hi - 1})</text>',
    ]
    for li, pr in enumerate(procs):
        y = y0 + li * lane_h
        svg.append(f'<text x="8" y="{y + 14}">proc {esc(pr)}</text>')
        svg.append(f'<line x1="{x0}" y1="{y + lane_h - 4}" '
                   f'x2="{width - 20}" y2="{y + lane_h - 4}" '
                   f'stroke="#eee"/>')
    for i in rows:
        iv = enc.intervals[i]
        li = procs.index(iv.process)
        y = y0 + li * lane_h
        xa = x0 + (int(enc.inv[i]) - t_lo) * px
        is_open = enc.ret[i] == OPEN
        xb = (width - 30 if is_open
              else x0 + (int(enc.ret[i]) - t_lo) * px)
        if i in lin:
            color = _C_LIN
        elif i in pending:
            color = _pending_color(pending[i])
        elif is_open:
            color = _C_OPEN
        else:
            color = _C_OTHER
        label = model.describe_op(int(enc.opcode[i]), int(enc.a1[i]),
                                  int(enc.a2[i]), enc.table)
        svg.append(
            f'<rect x="{xa:.0f}" y="{y}" width="{max(xb - xa, 6):.0f}" '
            f'height="{lane_h - 8}" rx="3" fill="{color}" '
            f'fill-opacity="0.75"><title>{esc(label)}'
            f'{" — " + esc(pending[i]) if i in pending else ""}'
            f'</title></rect>')
        svg.append(f'<text x="{xa + 2:.0f}" y="{y + 13}" '
                   f'font-size="9">{esc(label)[:18]}</text>')
    ly = y0 + lane_h * len(procs) + 18
    legend = [(_C_LIN, "linearized"), (_C_REJECT, "model rejects"),
              (_C_BLOCKED, "real-time blocked"),
              (_C_EXPLORED, "explored"), (_C_OPEN, "open (:info)")]
    lx = x0
    for color, name in legend:
        svg.append(f'<rect x="{lx}" y="{ly}" width="12" height="12" '
                   f'rx="2" fill="{color}"/>')
        svg.append(f'<text x="{lx + 16}" y="{ly + 10}">{name}</text>')
        lx += 24 + 8 * len(name)
    svg.append("</svg>")
    text = "\n".join(svg)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
