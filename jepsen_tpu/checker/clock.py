"""Clock-offset plot from :clock-offsets annotations.

Mirrors jepsen.checker.clock (jepsen/src/jepsen/checker/clock.clj): the
clock nemesis annotates ops with ``clock-offsets`` maps (node ->
seconds); this renders one line per node (clock.clj:13-75) into
``clock-skew.png``.
"""

from __future__ import annotations

from typing import Optional

from . import Checker, checker_fn
from .perf import _mpl, _shade_nemesis, _store_path


def history_to_datasets(history) -> dict:
    """node -> [(t_s, offset_s)] (clock.clj:13-34)."""
    out: dict = {}
    for op in history:
        offsets = op.get("clock-offsets") if hasattr(op, "get") else None
        if not offsets:
            continue
        t = op.time / 1e9
        for node, off in (offsets.items() if isinstance(offsets, dict)
                          else []):
            out.setdefault(str(node), []).append((t, off))
    return out


def plot(test: dict, history, path) -> bool:
    datasets = history_to_datasets(history)
    if not datasets:
        return False
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(10, 4))
    _shade_nemesis(ax, history)
    for node, pts in sorted(datasets.items()):
        xs, ys = zip(*pts)
        ax.plot(xs, ys, marker=".", label=node)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("clock offset (s)")
    ax.set_title(f"{test.get('name', 'test')} clock skew")
    ax.legend(fontsize=8)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return True


def clock_plot() -> Checker:
    """checker.clj:828-834."""

    def chk(test, history, opts):
        if not (test.get("name") and test.get("start-time")) or test.get(
            "no-store?"
        ):
            return {"valid": True}
        plot(test, history, _store_path(test, opts, "clock-skew.png"))
        return {"valid": True}

    return checker_fn(chk, "clock-plot")
