"""Checker protocol layer.

The reference seam this mirrors: ``Checker.check(test, history, opts)``
(jepsen/src/jepsen/checker.clj:49-64), the valid-merge priority lattice
true < :unknown < false (checker.clj:26-47), ``check-safe`` (:71-82),
``compose`` (:84-96) and ``concurrency-limit`` (:98-113). The
``linearizable`` checker dispatches through the ``:checker-backend`` option
onto the TPU WGL kernel (jepsen_tpu.ops.wgl) — the BASELINE dispatch story —
with the host oracle as fallback.

Result maps use the key ``"valid"`` with values True / False / "unknown"
(the EDN writers render it as ``:valid?``).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Optional

from ..history import History
from ..util import LOG, real_pmap

# Priority lattice: larger dominates when composing (checker.clj:26-31).
_VALID_PRIORITY = {True: 0, "unknown": 0.5, False: 1}


class Checker:
    """Base checker. Subclasses (or `checker_fn` wrappers) implement
    :meth:`check`."""

    def check(self, test: dict, history: History, opts: Optional[dict] = None) -> dict:
        raise NotImplementedError

    def __call__(self, test, history, opts=None):
        return self.check(test, history, opts)


class _FnChecker(Checker):
    __slots__ = ("fn", "_name")

    def __init__(self, fn: Callable, name: str = "checker"):
        self.fn = fn
        self._name = name

    def check(self, test, history, opts=None):
        return self.fn(test, history, opts or {})

    def __repr__(self):
        return f"<checker {self._name}>"


def checker_fn(fn: Callable, name: Optional[str] = None) -> Checker:
    """Lift ``fn(test, history, opts) -> result-map`` into a Checker."""
    return _FnChecker(fn, name or getattr(fn, "__name__", "checker"))


def merge_valid(valids) -> Any:
    """Merge valid values; highest priority (worst) wins
    (checker.clj:33-47)."""
    out = True
    for v in valids:
        if v not in _VALID_PRIORITY:
            raise ValueError(f"{v!r} is not a known valid value")
        if _VALID_PRIORITY[v] > _VALID_PRIORITY[out]:
            out = v
    return out


def noop() -> Checker:
    """Returns None from check (checker.clj:65-69)."""
    return checker_fn(lambda test, history, opts: None, "noop")


def unbridled_optimism() -> Checker:
    """Everything is awesoooommmmme! (checker.clj:115-119)"""
    return checker_fn(lambda test, history, opts: {"valid": True}, "unbridled-optimism")


def check_safe(checker: Checker, test: dict, history: History,
               opts: Optional[dict] = None) -> dict:
    """Like check, but exceptions become {"valid": "unknown", "error": ...}
    (checker.clj:71-82)."""
    try:
        return checker.check(test, history, opts or {})
    except Exception:
        LOG.warning("Error while checking history:", exc_info=True)
        return {"valid": "unknown", "error": traceback.format_exc()}


class _Compose(Checker):
    def __init__(self, checker_map: dict):
        self.checker_map = dict(checker_map)

    def check(self, test, history, opts=None):
        items = list(self.checker_map.items())
        results = real_pmap(
            lambda kv: (kv[0], check_safe(kv[1], test, history, opts)), items
        )
        out = dict(results)
        out["valid"] = merge_valid(
            r.get("valid") for _, r in results if r is not None
        )
        return out


def compose(checker_map: dict) -> Checker:
    """Map of names -> checkers; runs each (in parallel) and merges valid
    (checker.clj:84-96)."""
    return _Compose(checker_map)


class _ConcurrencyLimit(Checker):
    def __init__(self, limit: int, checker: Checker):
        self.sem = threading.Semaphore(limit)
        self.checker = checker

    def check(self, test, history, opts=None):
        with self.sem:
            return self.checker.check(test, history, opts)


def concurrency_limit(limit: int, checker: Checker) -> Checker:
    """Bound concurrent executions of a memory-hungry checker
    (checker.clj:98-113)."""
    return _ConcurrencyLimit(limit, checker)


# ---------------------------------------------------------------------------
# History statistics + exception surfacing (checker.clj:120-180)


def unhandled_exceptions() -> Checker:
    """Surface client exceptions recorded on :info ops, grouped by class,
    most frequent first (checker.clj:120-147)."""

    def chk(test, history, opts):
        groups: dict[Any, list] = {}
        for op in history:
            exc = op.get("exception")
            if exc is None or not op.is_info:
                continue
            cls = exc.get("type") if isinstance(exc, dict) else type(exc).__name__
            groups.setdefault(cls, []).append(op)
        exes = [
            {"count": len(ops), "class": cls, "example": ops[0]}
            for cls, ops in sorted(
                groups.items(), key=lambda kv: len(kv[1]), reverse=True
            )
        ]
        return {"valid": True, "exceptions": exes} if exes else {"valid": True}

    return checker_fn(chk, "unhandled-exceptions")


def _stats_counts(ops) -> dict:
    ok = sum(1 for op in ops if op.is_ok)
    fail = sum(1 for op in ops if op.is_fail)
    info = sum(1 for op in ops if op.is_info)
    return {
        # A group where nothing succeeded is *indeterminate*, not broken:
        # fail/info are legitimate op outcomes (e.g. a cas that never
        # matched on a short run), and correctness is the model checkers'
        # call. checker.clj:163-166 documents exactly this — "otherwise
        # they're :unknown".
        "valid": True if ok > 0 else "unknown",
        "count": ok + fail + info,
        "ok_count": ok,
        "fail_count": fail,
        "info_count": info,
    }


def stats() -> Checker:
    """Success/failure rates, overall and by :f; valid iff every :f has some
    ok ops, else "unknown" — never False (checker.clj:149-179)."""

    def chk(test, history, opts):
        ops = [op for op in history if not op.is_invoke and op.is_client]
        by_f: dict[Any, list] = {}
        for op in ops:
            by_f.setdefault(op.f, []).append(op)
        groups = {f: _stats_counts(sub) for f, sub in sorted(by_f.items(), key=lambda kv: str(kv[0]))}
        out = _stats_counts(ops)
        out["by_f"] = groups
        out["valid"] = merge_valid(g["valid"] for g in groups.values())
        return out

    return checker_fn(chk, "stats")


# ---------------------------------------------------------------------------
# Linearizability — the TPU-kernel seam (checker.clj:182-213)


def linearizable(options: Optional[dict] = None, **kw) -> Checker:
    """Validate linearizability on the WGL kernel.

    ``options`` / kwargs:

    - ``model``: a `jepsen_tpu.models.Model` (required).
    - ``backend``: "auto" (default) | "device" | "host" | "native" |
      "sharded" | "segmented" — overridden by the test map's
      ``checker_backend`` when present (the BASELINE ``:checker-backend
      :tpu`` dispatch; "tpu" is accepted as an alias for "device").
      "auto" prefers the native C search for single histories and the
      device kernel for batches; "sharded" runs the frontier-sharded
      multi-chip search (jepsen_tpu.parallel.frontier) over the test's
      ``mesh`` (or the default mesh); "segmented" plans the recorded
      history with the offline decrease-and-conquer planner
      (jepsen_tpu.offline, docs/offline.md) and decides the (stream ×
      key × segment) DAG through the multi-stream scheduler.

    Mirrors checker.clj:182-213 (including truncating bulky diagnostics).
    """
    o = dict(options or {})
    o.update(kw)
    model = o.get("model")
    if model is None:
        raise ValueError(
            f"the linearizable checker requires a model; received {model!r}"
        )
    default_backend = o.get("backend", "auto")

    def _resolve_backend(test):
        backend = (test or {}).get("checker_backend", default_backend)
        return "device" if backend == "tpu" else backend

    def _check_one(test, ops, backend, **kw):
        """The single-history dispatch, shared by chk() and the keyed
        batch's unknown-recheck path (so a backend added to one can't be
        forgotten in the other). ``kw`` carries telemetry wiring
        (metrics registry, heartbeat chunk_callback) into the device
        drivers; the native/host engines ignore it."""
        if backend == "sharded":
            from ..parallel.frontier import check_history_sharded

            return check_history_sharded(
                model, ops, mesh=(test or {}).get("mesh"),
                metrics=kw.get("metrics"))
        if backend == "segmented":
            # The offline decrease-and-conquer path (jepsen_tpu.
            # offline): plan the recorded history into a (stream × key
            # × segment) DAG and decide it through the multi-stream
            # scheduler — the checker surface of
            # ``check_history(parallel="segmented")``.
            from .. import offline

            return offline.check_offline(model, ops,
                                         metrics=kw.get("metrics"))
        from ..ops import wgl

        return wgl.check_history(model, ops, backend=backend, **kw)

    def chk(test, history, opts):
        import time as _time

        from .. import telemetry as jtelemetry

        backend = _resolve_backend(test)
        ops = history.client_ops()
        reg = jtelemetry.of_test(test)
        kw = {}
        if reg is not None:
            # Device paths get the registry plus a heartbeat: the
            # knossos-style "checking... 43%" progress line with ETA,
            # fed by the driver's per-chunk callback.
            kw["metrics"] = reg
            kw["chunk_callback"] = jtelemetry.Heartbeat(
                total=len(ops), registry=reg)
        t0 = _time.perf_counter()
        res = _check_one(test, ops, backend, **kw)
        if reg is not None:
            reg.histogram(
                "checker_seconds",
                "Checker wall seconds by checker and engine",
                labelnames=("checker", "backend"),
                buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0, 600.0),
            ).labels(
                checker="linearizable",
                backend=str(res.get("backend")
                            or ("device" if res.get("device") else backend)),
            ).observe(_time.perf_counter() - t0)
            reg.gauge("checker_op_count",
                      "Ops seen by the linearizable checker").set(
                          res.get("op_count") or len(ops))
        # Writing full search diagnostics "can take hours" in the reference
        # (checker.clj:210-213); keep attempts bounded likewise.
        if isinstance(res.get("attempts"), list):
            res["attempts"] = res["attempts"][:10]
        if (res.get("valid") is False and test.get("name")
                and test.get("start-time") and not test.get("no-store?")):
            # Render the refutation witness into the store — the
            # reference's linear.svg of the search's final configs
            # (checker.clj:202-209); linear.txt carries the per-op
            # reasons.
            try:
                from .. import store
                from .linear_viz import failure_report, render_linear_svg

                sub = (opts or {}).get("subdirectory")
                parts = ([str(sub)] if sub else [])
                with open(store.path_mk(
                        test, *parts, "linear.txt"), "w") as f:
                    f.write(failure_report(model, ops, res))
                if res.get("stuck_configs"):
                    render_linear_svg(
                        model, ops, res,
                        store.path_mk(test, *parts, "linear.svg"))
                    res["witness_files"] = ["linear.txt", "linear.svg"]
                else:
                    res["witness_files"] = ["linear.txt"]
            except Exception as e:  # diagnostics never mask the verdict
                res["witness_error"] = f"{type(e).__name__}: {e}"
        return res

    out = checker_fn(chk, "linearizable")

    def batch_check(test, keyed_histories: dict, opts=None) -> dict:
        """Decide many subhistories as ONE vmapped (mesh-shardable) device
        program — jepsen_tpu.independent's device-batched check axis.
        Returns {key: result-map}. Raises if the device path is
        unavailable so the caller can fall back to per-key checking."""
        backend = _resolve_backend(test)
        if backend == "host" or not model.device_capable:
            raise RuntimeError("batch check requires the device backend")
        import jax

        import time as _time

        from .. import telemetry as jtelemetry
        from ..ops import wgl
        from ..parallel import check_batch, make_mesh

        reg = jtelemetry.of_test(test)
        kw = {"metrics": reg} if reg is not None else {}
        t0 = _time.perf_counter()
        # Shard the batch over every local device (the reference's
        # bounded-pmap key axis, mapped onto the mesh's dp axis).
        mesh = make_mesh() if len(jax.devices()) > 1 else None
        ks = list(keyed_histories)
        # Overflowing keys re-batch up the frontier schedule as new
        # vmapped programs (parallel.batch) — the serial driver is the
        # batch path's own last resort now, not this layer's first move.
        results = check_batch(
            model, [keyed_histories[k].client_ops() for k in ks],
            mesh=mesh, metrics=reg
        )
        out_map = dict(zip(ks, results))
        # Keys the shared batch couldn't decide (didn't fit the common
        # shape bucket, schedule exhausted) get the full per-key path,
        # which includes the auto backend's host-oracle fallback.
        for k, r in out_map.items():
            if r.get("valid") == "unknown":
                out_map[k] = _check_one(
                    test, keyed_histories[k].client_ops(), backend, **kw)
        if reg is not None:
            reg.histogram(
                "checker_seconds",
                "Checker wall seconds by checker and engine",
                labelnames=("checker", "backend"),
                buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0, 600.0),
            ).labels(checker="linearizable", backend="batch").observe(
                _time.perf_counter() - t0)
            kc = reg.counter(
                "checker_batch_keys_total",
                "Keys decided through the batched device check",
                labelnames=("result",))
            for r in out_map.values():
                kc.labels(result=str(r.get("valid"))).inc()
        return out_map

    out.batch_check = batch_check
    return out


# Invariant checkers live in their own module; re-export the public set.
from .invariants import (  # noqa: E402
    counter,
    queue,
    set_checker,
    set_full,
    total_queue,
    unique_ids,
)

__all__ = [
    "Checker",
    "checker_fn",
    "check_safe",
    "compose",
    "concurrency_limit",
    "counter",
    "linearizable",
    "merge_valid",
    "noop",
    "queue",
    "set_checker",
    "set_full",
    "stats",
    "total_queue",
    "unbridled_optimism",
    "unhandled_exceptions",
    "unique_ids",
]
