"""Latency / rate plotting over histories.

Mirrors jepsen.checker.perf (jepsen/src/jepsen/checker/perf.clj), with
matplotlib standing in for gnuplot (a rendering detail — the reference
drives a gnuplot subprocess, perf.clj:418-484): raw latency points per
(f, type) (:485-513), bucketed latency quantiles (:514-559), throughput
rate (:560-600), and nemesis activity shaded onto every plot
(:184-326).
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import numpy as np

from . import Checker, checker_fn
from ..history import History
from ..util import nemesis_intervals

LOG = logging.getLogger("jepsen.checker.perf")

DT_S = 10.0  # quantile/rate bucket width, seconds (perf.clj:127-147)
QUANTILES = (0.5, 0.95, 0.99, 1.0)

_TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}


def _mpl():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _shade_nemesis(ax, history: History, test: Optional[dict] = None
                   ) -> None:
    """perf.clj:184-326 — translucent spans while the nemesis is active.

    Honors the nemesis packages' perf specs (combined.clj perf entries:
    {"name", "start": fs, "stop": fs, "color"}) via
    ``test["plot"]["nemeses"]``; falls back to the default start/stop
    pairing."""
    try:
        t_end = max((op.time for op in history if op.time >= 0), default=0)
        specs = ((test or {}).get("plot") or {}).get("nemeses")
        if specs:
            def _fset(v, default):
                if v is None:
                    v = default
                if isinstance(v, str):
                    v = (v,)
                return frozenset(v)

            for spec in specs:
                stop_set = _fset(spec.get("stop"), ("stop",))
                pairing = {start_f: stop_set
                           for start_f in _fset(spec.get("start"), ())}
                if not pairing:
                    continue
                for start, stop in nemesis_intervals(history, pairing):
                    t0 = start.time / 1e9
                    t1 = (stop.time if stop is not None else t_end) / 1e9
                    ax.axvspan(t0, t1, color=spec.get("color", "#f3c3c3"),
                               alpha=0.35, lw=0)
        else:
            for start, stop in nemesis_intervals(history):
                t0 = start.time / 1e9
                t1 = (stop.time if stop is not None else t_end) / 1e9
                ax.axvspan(t0, t1, color="#f3c3c3", alpha=0.4, lw=0)
    except Exception:
        LOG.debug("nemesis shading failed", exc_info=True)


def point_graph(test: dict, history: History, path) -> None:
    """Raw latency scatter, colored by completion type, one series per f
    (perf.clj:485-513)."""
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(10, 5))
    _shade_nemesis(ax, history, test)
    by = {}
    for iv in history.pairs():
        if not isinstance(iv.process, int) or iv.inv_time < 0:
            continue
        end = iv.ret_time
        if end == float("inf"):
            continue
        by.setdefault((iv.f, iv.type), []).append(
            (iv.inv_time / 1e9, max(end - iv.inv_time, 1) / 1e6))
    for (f, typ), pts in sorted(by.items(), key=lambda kv: str(kv[0])):
        xs, ys = zip(*pts)
        ax.scatter(xs, ys, s=6, label=f"{f} {typ}",
                   color=_TYPE_COLORS.get(typ), alpha=0.6,
                   marker={"ok": "o", "info": "^", "fail": "x"}.get(typ, "o"))
    ax.set_yscale("log")
    ax.set_xlabel("time (s)")
    ax.set_ylabel("latency (ms)")
    ax.set_title(f"{test.get('name', 'test')} latency (raw)")
    ax.legend(fontsize=7, ncol=2)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    plt.close(fig)


def quantiles_graph(test: dict, history: History, path) -> None:
    """Bucketed latency quantiles per f (perf.clj:514-559)."""
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(10, 5))
    _shade_nemesis(ax, history, test)
    by_f: dict = {}
    for iv in history.pairs():
        if not isinstance(iv.process, int) or iv.inv_time < 0:
            continue
        end = iv.ret_time
        if end == float("inf"):
            continue
        by_f.setdefault(iv.f, []).append(
            (iv.inv_time / 1e9, max(end - iv.inv_time, 1) / 1e6))
    for f, pts in sorted(by_f.items(), key=lambda kv: str(kv[0])):
        arr = np.array(pts)
        tmax = arr[:, 0].max() if len(arr) else 0
        for q in QUANTILES:
            xs, ys = [], []
            for lo in np.arange(0, tmax + DT_S, DT_S):
                sel = arr[(arr[:, 0] >= lo) & (arr[:, 0] < lo + DT_S)]
                if len(sel):
                    xs.append(lo + DT_S / 2)
                    ys.append(np.quantile(sel[:, 1], q))
            if xs:
                ax.plot(xs, ys, marker=".",
                        label=f"{f} q={q}", alpha=0.8)
    ax.set_yscale("log")
    ax.set_xlabel("time (s)")
    ax.set_ylabel("latency (ms)")
    ax.set_title(f"{test.get('name', 'test')} latency quantiles")
    ax.legend(fontsize=7, ncol=2)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    plt.close(fig)


def rate_graph(test: dict, history: History, path) -> None:
    """Throughput per (f, type) in DT_S buckets (perf.clj:560-600)."""
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(10, 5))
    _shade_nemesis(ax, history, test)
    by: dict = {}
    tmax = 0.0
    for op in history:
        if op.is_invoke or not op.is_client:
            continue
        t = op.time / 1e9
        tmax = max(tmax, t)
        by.setdefault((op.f, op.type), []).append(t)
    for (f, typ), ts in sorted(by.items(), key=lambda kv: str(kv[0])):
        edges = np.arange(0, tmax + DT_S, DT_S)
        counts, _ = np.histogram(ts, bins=edges)
        ax.plot(edges[:-1] + DT_S / 2, counts / DT_S, marker=".",
                color=_TYPE_COLORS.get(typ), alpha=0.8,
                label=f"{f} {typ}")
    ax.set_xlabel("time (s)")
    ax.set_ylabel("throughput (hz)")
    ax.set_title(f"{test.get('name', 'test')} rate")
    ax.legend(fontsize=7, ncol=2)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    plt.close(fig)


def _store_path(test: dict, opts: Optional[dict], fname: str):
    from .. import store

    sub = (opts or {}).get("subdirectory")
    parts = ([str(sub), fname] if sub else [fname])
    return store.path_mk(test, *parts)


def latency_graph() -> Checker:
    """checker.clj:794-806: latency-raw.png + latency-quantiles.png."""

    def chk(test, history, opts):
        if not (test.get("name") and test.get("start-time")) or test.get(
            "no-store?"
        ):
            return {"valid": True}
        point_graph(test, history,
                    _store_path(test, opts, "latency-raw.png"))
        quantiles_graph(test, history,
                        _store_path(test, opts, "latency-quantiles.png"))
        return {"valid": True}

    return checker_fn(chk, "latency-graph")


def rate_graph_checker() -> Checker:
    """checker.clj:807-818: rate.png."""

    def chk(test, history, opts):
        if not (test.get("name") and test.get("start-time")) or test.get(
            "no-store?"
        ):
            return {"valid": True}
        rate_graph(test, history, _store_path(test, opts, "rate.png"))
        return {"valid": True}

    return checker_fn(chk, "rate-graph")


def perf() -> Checker:
    """Composite of latency + rate graphs (checker.clj:819-826)."""
    from . import compose

    return compose({
        "latency-graph": latency_graph(),
        "rate-graph": rate_graph_checker(),
    })
