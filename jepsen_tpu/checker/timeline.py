"""HTML per-process op timeline.

Mirrors jepsen.checker.timeline (jepsen/src/jepsen/checker/timeline.clj):
pairs invocations with completions (timeline.clj:33-53), renders one
column per process with a colored div per op (:97-121), and writes
``timeline.html`` into the test's store directory (:159-179).
"""

from __future__ import annotations

import html as _html
from typing import Optional

from . import Checker, checker_fn
from ..history import History

_COLORS = {
    "ok": "#6DB6FE",
    "info": "#FFAA26",
    "fail": "#FEB5DA",
}

_STYLE = """
body { font-family: sans-serif; }
.ops { position: relative; }
.op { position: absolute; padding: 2px; border-radius: 2px;
      overflow: hidden; font-size: 10px; border: 1px solid #888; }
.op:hover { overflow: visible; z-index: 10; min-width: 12em; }
"""

PROCESS_WIDTH = 130  # px per process column
HEIGHT_PER_NS = 0.0000006  # vertical scale (timeline.clj:25-31)
MIN_HEIGHT = 16


def render(history: History, test: Optional[dict] = None) -> str:
    """Render the history as standalone HTML (timeline.clj:123-157)."""
    pairs = history.pairs()
    procs = sorted(
        {iv.process for iv in pairs},
        key=lambda p: (isinstance(p, str), p),
    )
    col_of = {p: i for i, p in enumerate(procs)}
    t0 = min((iv.inv_time for iv in pairs), default=0)
    t_max = max(
        (iv.ret_time for iv in pairs if iv.ret_time != float("inf")),
        default=t0,
    )
    divs = []
    for iv in pairs:
        left = col_of[iv.process] * PROCESS_WIDTH
        top = (iv.inv_time - t0) * HEIGHT_PER_NS
        end = iv.ret_time if iv.ret_time != float("inf") else t_max
        height = max((end - iv.inv_time) * HEIGHT_PER_NS, MIN_HEIGHT)
        color = _COLORS.get(iv.type, "#eee")
        title = (
            f"{iv.process} {iv.f} {iv.value_in!r} -> {iv.type} "
            f"{iv.value_out!r}"
        )
        divs.append(
            f'<div class="op" style="left:{left}px;top:{top + 40:.1f}px;'
            f"width:{PROCESS_WIDTH - 12}px;height:{height:.1f}px;"
            f'background:{color}" title="{_html.escape(title)}">'
            f"{_html.escape(str(iv.process))} {_html.escape(str(iv.f))} "
            f"{_html.escape(repr(iv.value_out if iv.type == 'ok' else iv.value_in))}"
            "</div>"
        )
    heads = "".join(
        f'<div style="position:absolute;left:{col_of[p] * PROCESS_WIDTH}px;'
        f'top:0;font-weight:bold">{_html.escape(str(p))}</div>'
        for p in procs
    )
    name = (test or {}).get("name", "test")
    return (
        f"<html><head><title>{_html.escape(str(name))} timeline</title>"
        f"<style>{_STYLE}</style></head><body>"
        f'<h1>{_html.escape(str(name))}</h1><div class="ops">{heads}'
        + "".join(divs)
        + "</div></body></html>"
    )


def html() -> Checker:
    """Checker writing timeline.html into the store (timeline.clj:159-179)."""

    def chk(test, history, opts):
        content = render(history, test)
        if test.get("name") and test.get("start-time") and not test.get(
            "no-store?"
        ):
            from .. import store

            sub = (opts or {}).get("subdirectory")
            parts = ([str(sub), "timeline.html"] if sub else
                     ["timeline.html"])
            path = store.path_mk(test, *parts)
            path.write_text(content)
            return {"valid": True, "file": str(path)}
        return {"valid": True}

    return checker_fn(chk, "timeline")
