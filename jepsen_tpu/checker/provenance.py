"""Verdict provenance: a CLOSED taxonomy of machine-readable causes for
every degraded verdict in the checking pipeline.

The checker's product is a verdict and its failure mode is ``unknown``
— after the escalation pipeline, the online fold, the multi-tenant
service and the fault-tolerance layer there are a dozen distinct
degradation paths, and each used to record its cause as a free-text
``info`` string no policy could consume. This module replaces that
prose with typed *causes*: every site that degrades a verdict attaches
``cause(code, **params)`` (a dict: ``code`` from :data:`TAXONOMY`,
``layer``, ``params`` — including the PR-6 trace ids where the fold has
them), and the scheduler / service folds union causes up to per-key,
per-segment, per-tenant and per-run *provenance* blocks
(``{"causes": {code: count}, "dominant": code, "total": n}``).

Consumers:

- ``verdict_causes_total{code,tenant}`` — one counter family (aggregate
  unlabeled total; ``tenant=""`` for non-service paths) every fold
  layer increments, so a dashboard sees the cause Pareto live;
- results / ``online.json`` / tenant snapshots / ledger records embed
  the ``provenance`` block; the web ``/verdicts`` page renders the
  Pareto with deep links into the op→segment→member→chunk trace chain;
- ``python -m jepsen_tpu.advisor`` joins provenance with the roofline
  attribution, utilization gap classes and ledger trends to emit
  concrete configuration recommendations — the data seam the
  ROADMAP-item-5 self-tuning policy will automate.

The taxonomy is CLOSED: :func:`cause` refuses unknown codes, so a new
degradation path must register its code (and document it in
docs/verdicts.md) before it can ship an unknown. ``unattributed``
exists as the mechanical backstop for a fold that received an unknown
with no structured cause — the chaos matrix asserts it never actually
appears (no pipeline path may produce a free-text-only unknown).

See docs/verdicts.md for the full taxonomy table and fold semantics.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

# code -> (layer, description). Layers name the subsystem that OWNS the
# degradation (where the advisor's fix applies), not where it was
# observed.
TAXONOMY: dict[str, tuple[str, str]] = {
    # -- kernel / device search -------------------------------------------
    "overflow_top_rung": (
        "kernel",
        "frontier overflowed the capacity schedule's top rung (or "
        "escalation was disabled at the shared batch capacity)"),
    "escalation_budget": (
        "kernel",
        "lossless capacity escalations exhausted (sharded "
        "max_escalations spent without a verdict)"),
    "beam_loss": (
        "kernel",
        "lossy beam exhausted after truncation — configs were dropped, "
        "so exhaustion is not a refutation"),
    "level_budget": (
        "kernel",
        "level budget exhausted without a verdict"),
    # -- host / native enumeration ----------------------------------------
    "max_configs": (
        "host",
        "host/native enumeration config budget exhausted"),
    "oom": (
        "host",
        "native engine out of memory"),
    # -- encoding ----------------------------------------------------------
    "encoding_unsupported": (
        "encode",
        "history/model does not fit the device encoding (plan "
        "rejected, unreadable archive, or model mismatch)"),
    # -- online fold --------------------------------------------------------
    "carry_lost": (
        "online",
        "carried initial-state set lost (budget-tripped enumeration, "
        "or an unknown upstream segment of the same key)"),
    "poisoned_key": (
        "online",
        "the stream's carries are poisoned (unaddressable journal key "
        "or replay poison): every later segment folds unknown"),
    "mixed_keys": (
        "online",
        "mixed keyed/keyless stream: the online split cannot match "
        "independent.subhistory, no definite verdict is safe"),
    # -- scheduler / failover ----------------------------------------------
    "round_failed": (
        "scheduler",
        "a dispatch round raised; its segments fold unknown and their "
        "keys' carries are lost"),
    "worker_died": (
        "scheduler",
        "the scheduler worker died past its bounded restart; streams "
        "fold unknown"),
    "failover_exhausted": (
        "scheduler",
        "the failover host re-dispatch also failed for this member"),
    # -- service ------------------------------------------------------------
    "lost_segments": (
        "service",
        "segments refused by a closed scheduler; a definite True can "
        "no longer cover the stream"),
    "undelivered_ops": (
        "service",
        "accepted ops never fed through the segmenter (drain deadline "
        "truncated the stream)"),
    "deadline": (
        "service",
        "a deadline truncated decision coverage (close/drain timed "
        "out with work in flight)"),
    # -- journal ------------------------------------------------------------
    "journal_gap": (
        "journal",
        "journal replay detected swallowed appends (seq gap); the "
        "restored fold is pinned off definite-True"),
    # -- router / scale-out --------------------------------------------------
    "backend_lost": (
        "router",
        "a backend service process was lost; the tenant restored from "
        "its journal checkpoint (anything undecided and unjournaled "
        "degrades to unknown — with no usable journal the whole "
        "stream does)"),
    "migration_interrupted": (
        "router",
        "a tenant migration failed partway (adopt refused, target "
        "unreachable, or JEPSEN_NO_MIGRATION); the tenant is orphaned "
        "and folds unknown until a later migration succeeds"),
    # -- elle cycle engine ---------------------------------------------------
    "elle_bucket_ceiling": (
        "elle",
        "a dependency graph outgrew the batched cycle engine's largest "
        "size bucket with no mesh available for the sharded closure; "
        "the verdict folded to the host Tarjan/BFS path"),
    "elle_device_oom": (
        "elle",
        "a batched/sharded closure dispatch kept failing past the "
        "chunk-halving escalation budget (device OOM or runtime "
        "fault); the verdict folded to the host Tarjan/BFS path"),
    # -- trace ingestion ------------------------------------------------------
    "ingest_unmapped_op": (
        "ingest",
        "a recorded trace line (or parsed op) no adapter rule or "
        "workload model explains; the op was dropped from the checked "
        "history, so no definite verdict can cover the recording — the "
        "fold is one-sidedly unknown, never a flip"),
    # -- testing ------------------------------------------------------------
    "chaos": (
        "testing",
        "an injected chaos fault was the proximate cause"),
    # -- backstop ------------------------------------------------------------
    "unattributed": (
        "unknown",
        "an unknown reached the fold with no structured cause — a "
        "taxonomy hole (file it; the chaos matrix asserts this never "
        "appears)"),
}

# Bounded per-row cause list (the per-stream counts stay exact).
MAX_CAUSES_PER_ROW = 8

METRIC_NAME = "verdict_causes_total"
_METRIC_HELP = ("Degraded-verdict causes by taxonomy code (see "
                "docs/verdicts.md); tenant=\"\" for non-service paths, "
                "unlabeled = all codes and tenants")


def cause(code: str, **params: Any) -> dict:
    """One typed cause. ``code`` must be in the closed
    :data:`TAXONOMY`; ``params`` are JSON-scalar diagnostics (capacity
    F, budget, seq, trace_span, …)."""
    try:
        layer, _desc = TAXONOMY[code]
    except KeyError:
        raise ValueError(
            f"unknown provenance code {code!r}; the taxonomy is closed "
            f"— register it in provenance.TAXONOMY (known: "
            f"{sorted(TAXONOMY)})") from None
    c: dict = {"code": code, "layer": layer}
    if params:
        c["params"] = params
    return c


def attach(result: dict, code: str, **params: Any) -> dict:
    """Attach one cause to a result dict (under ``"causes"``) and
    return it — the one-liner every degradation seam calls next to its
    human-readable ``info`` string."""
    result.setdefault("causes", []).append(cause(code, **params))
    return result


def of(result: Optional[dict]) -> list[dict]:
    """The causes attached to a result dict (never None)."""
    if not isinstance(result, dict):
        return []
    cs = result.get("causes")
    return list(cs) if isinstance(cs, list) else []


def annotate(causes: Iterable[dict], **params: Any) -> list[dict]:
    """Copies of ``causes`` with ``params`` merged into each cause's
    params (the fold layer stamps seq / trace_span here — copies,
    because cause dicts are shared through member result dicts)."""
    out = []
    for c in causes:
        if not isinstance(c, dict):
            continue
        merged = dict(c.get("params") or {})
        for k, v in params.items():
            merged.setdefault(k, v)
        c2 = {k: v for k, v in c.items() if k != "params"}
        if merged:
            c2["params"] = merged
        out.append(c2)
    return out


def add_counts(counts: dict, causes: Iterable[Any]) -> dict:
    """Fold causes (dicts or bare codes) into a ``{code: n}`` counter
    map — the per-stream/per-tenant union the fold layers keep."""
    for c in causes:
        code = c.get("code") if isinstance(c, dict) else c
        if isinstance(code, str):
            counts[code] = counts.get(code, 0) + 1
    return counts


def merge_counts(*maps: Optional[dict]) -> dict:
    out: dict = {}
    for m in maps:
        for code, n in (m or {}).items():
            if isinstance(n, (int, float)):
                out[code] = out.get(code, 0) + int(n)
    return out


def dominant(counts: Optional[dict]) -> Optional[str]:
    """The most frequent cause code (ties break lexically, so the
    answer is deterministic), or None."""
    if not counts:
        return None
    return min(counts, key=lambda c: (-counts[c], c))


def block(counts: Optional[dict]) -> Optional[dict]:
    """The ``provenance`` block results/snapshots embed, or None when
    nothing degraded (the common all-valid case stays clean)."""
    if not counts:
        return None
    return {
        "causes": {c: int(n) for c, n in sorted(counts.items())},
        "dominant": dominant(counts),
        "total": int(sum(counts.values())),
    }


def pareto(counts: Optional[dict]) -> list[dict]:
    """Sorted display rows for the ``/verdicts`` page: code, layer,
    count, share."""
    counts = counts or {}
    total = sum(counts.values()) or 1
    rows = []
    for code in sorted(counts, key=lambda c: (-counts[c], c)):
        layer, desc = TAXONOMY.get(code, ("?", "(unregistered code)"))
        rows.append({"code": code, "layer": layer, "count": counts[code],
                     "share": round(counts[code] / total, 4),
                     "description": desc})
    return rows


def count_metric(metrics, causes: Iterable[Any],
                 tenant: str = "") -> None:
    """Increment ``verdict_causes_total{code,tenant}`` (+ the
    aggregate unlabeled total) for each cause. No-op without a
    registry; never raises into a fold."""
    if metrics is None:
        return
    try:
        # Literal name (not METRIC_NAME) so the doc-drift guard's
        # static scan sees the family like every other registration.
        c = metrics.counter("verdict_causes_total", _METRIC_HELP,
                            labelnames=("code", "tenant"),
                            aggregate=True)
        for item in causes:
            code = item.get("code") if isinstance(item, dict) else item
            if not isinstance(code, str):
                continue
            c.inc()  # the unlabeled total
            c.labels(code=code, tenant=str(tenant)).inc()
    except Exception:  # noqa: BLE001 - observability never sinks a fold
        pass


def ensure(causes: list[dict], **params: Any) -> list[dict]:
    """The mechanical backstop: an unknown that reached the fold with
    no structured cause gets ``unattributed`` (the chaos matrix
    asserts this never actually fires)."""
    return causes if causes else [cause("unattributed", **params)]
