"""Redirect human-readable reports to a file (jepsen.report,
jepsen/src/jepsen/report.clj:7-16)."""

from __future__ import annotations

import contextlib
import io
from typing import Any


@contextlib.contextmanager
def to(path: Any):
    """Capture prints in the body to ``path`` as well as stdout."""
    import sys

    buf = io.StringIO()
    orig = sys.stdout

    class _Tee(io.TextIOBase):
        def write(self, s):
            buf.write(s)
            return orig.write(s)

        def flush(self):
            orig.flush()

    sys.stdout = _Tee()
    try:
        yield
    finally:
        sys.stdout = orig
        with open(path, "w") as f:
            f.write(buf.getvalue())
