"""Auto-reconnecting connection wrappers.

Mirrors jepsen.reconnect (jepsen/src/jepsen/reconnect.clj): a stateful
wrapper around an open/close lifecycle with a readers-writer lock —
operations share the connection under the read lock; a failure takes the
write lock, closes and reopens, and **rethrows** (reconnect.clj:16-31,
92-129). The operation is NOT re-executed: DB operations are generally
non-idempotent, and the caller (the interpreter's soundness rule) must
see the failure to record the op as indeterminate. The control plane's
sessions may retry because shell actions are request/response over a
fresh channel; this generic wrapper must not.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional

LOG = logging.getLogger("jepsen.reconnect")


class _RWLock:
    """Writer-preferring readers-writer lock."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class Wrapper:
    """reconnect.clj:16-31. ``open`` builds a connection; ``close`` tears
    one down; ``name``/``log`` control reopen logging."""

    def __init__(self, open: Callable[[], Any],
                 close: Optional[Callable[[Any], None]] = None,
                 name: Any = None, log: bool = True):
        self._open = open
        self._close = close or (lambda conn: None)
        self.name = name
        self.log = log
        self._rw = _RWLock()
        self._conn: Any = None

    def open(self) -> "Wrapper":
        """reconnect.clj:56-66."""
        self._rw.acquire_write()
        try:
            if self._conn is None:
                self._conn = self._open()
        finally:
            self._rw.release_write()
        return self

    def reopen(self) -> None:
        """Close and reopen under the write lock (reconnect.clj:68-80) —
        waits for in-flight users, so nobody's connection is yanked
        mid-operation."""
        self._rw.acquire_write()
        try:
            if self._conn is not None:
                try:
                    self._close(self._conn)
                except Exception:
                    pass
                self._conn = None
            self._conn = self._open()
        finally:
            self._rw.release_write()

    def close(self) -> None:
        self._rw.acquire_write()
        try:
            if self._conn is not None:
                try:
                    self._close(self._conn)
                finally:
                    self._conn = None
        finally:
            self._rw.release_write()

    def with_conn(self, f: Callable[[Any], Any]) -> Any:
        """Run ``f(conn)`` under the read lock. On failure, reopen the
        connection for FUTURE users and rethrow — the failed operation is
        never silently re-executed: DB ops are non-idempotent, and the
        caller must see the failure to record the op as indeterminate
        (reconnect.clj:92-129)."""
        self._rw.acquire_read()
        holding = True
        try:
            conn = self._conn
            if conn is None:
                # Lazily open: switch to the write path, then re-enter.
                self._rw.release_read()
                holding = False
                self.open()
                self._rw.acquire_read()
                holding = True
                conn = self._conn
                if conn is None:
                    raise RuntimeError(
                        f"connection {self.name!r} closed while opening")
            return f(conn)
        except Exception:
            if holding:
                self._rw.release_read()
                holding = False
            if self.log:
                LOG.warning("conn %r failed; reopening", self.name)
            try:
                self.reopen()
            except Exception:
                LOG.warning("could not reopen %r", self.name, exc_info=True)
            raise
        finally:
            if holding:
                self._rw.release_read()


def wrapper(open: Callable[[], Any], **kw: Any) -> Wrapper:
    return Wrapper(open, **kw)
