/* strobe_time: oscillate the system wall clock by +/- delta milliseconds
 * every period milliseconds, for duration seconds, using the MONOTONIC
 * clock as the reference for pacing and for when to stop (so the strobing
 * itself can't confuse the schedule). Equivalent role to the reference's
 * jepsen/resources/strobe-time.c, reimplemented over
 * clock_gettime/clock_settime/nanosleep.
 *
 * usage: strobe_time <delta-ms> <period-ms> <duration-s>
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

static long long mono_ns(void) {
    struct timespec t;
    clock_gettime(CLOCK_MONOTONIC, &t);
    return (long long)t.tv_sec * 1000000000LL + t.tv_nsec;
}

static int shift_wall(long long delta_ns) {
    struct timespec now, next;
    if (clock_gettime(CLOCK_REALTIME, &now) != 0) {
        perror("clock_gettime");
        return -1;
    }
    long long ns = (long long)now.tv_sec * 1000000000LL + now.tv_nsec;
    ns += delta_ns;
    if (ns < 0) ns = 0;
    next.tv_sec = ns / 1000000000LL;
    next.tv_nsec = ns % 1000000000LL;
    if (clock_settime(CLOCK_REALTIME, &next) != 0) {
        perror("clock_settime");
        return -1;
    }
    return 0;
}

int main(int argc, char **argv) {
    if (argc < 4) {
        fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-s>\n",
                argv[0]);
        return 1;
    }
    long long delta_ns = (long long)(atof(argv[1]) * 1e6);
    long long period_ns = (long long)(atof(argv[2]) * 1e6);
    long long duration_ns = (long long)(atof(argv[3]) * 1e9);
    if (period_ns <= 0) period_ns = 1000000;

    long long start = mono_ns();
    long long sign = 1;
    while (mono_ns() - start < duration_ns) {
        if (shift_wall(sign * delta_ns) != 0)
            return 2;
        sign = -sign;
        struct timespec nap;
        nap.tv_sec = period_ns / 1000000000LL;
        nap.tv_nsec = period_ns % 1000000000LL;
        nanosleep(&nap, NULL);
    }
    /* Leave the clock where an even number of strobes would have: if we
     * exit mid-cycle with an odd number of shifts applied, undo one. */
    if (sign < 0 && shift_wall(-delta_ns) != 0)
        return 2;
    return 0;
}
