"""Node-side bridge daemon for the ignite bank workload.

The reference bank test runs transactional getAll/put sequences through
the Ignite Java client (ignite/src/jepsen/ignite/bank.clj:64-108) — a
surface the REST connector cannot script (no transactions).  Same move
as hz_bridge.py / as_bridge.py: a tiny TCP daemon ON the DB node
translating newline commands into official-python-thin-client calls
(pyignite, installed during DB setup), with every read and transfer
wrapped in a PESSIMISTIC/REPEATABLE_READ transaction like the
reference's TransactionConcurrency/TransactionIsolation defaults.

Protocol (one request per line, one reply per line):

    INIT <n> <balance>        -> OK        (create cache, seed accounts once)
    READ <n>                  -> OK <json [balances]>
    XFER <from> <to> <amount> -> OK | NEG <account> <balance> | ERR <msg>

NEG mirrors bank.clj:97-101: the transfer COMMITS the unchanged state
and reports a definite :fail (insufficient funds is not an error).

Run: python3 ig_bridge.py [--port 10801] [--host 127.0.0.1]
"""

from __future__ import annotations

import argparse
import json
import socketserver
import sys
import threading

try:
    from pyignite import Client as IgniteClient
    from pyignite.datatypes import TransactionConcurrency, \
        TransactionIsolation
    from pyignite.datatypes.prop_codes import PROP_CACHE_ATOMICITY_MODE, \
        PROP_NAME
except ImportError:  # surfaced at startup, not per-request
    IgniteClient = None
    # Define the companion names too: a dispatch without pyignite must
    # fail with the startup's clean report, never a NameError.
    TransactionConcurrency = TransactionIsolation = None
    PROP_CACHE_ATOMICITY_MODE = "atomicity_mode"
    PROP_NAME = "name"

CACHE = "ACCOUNTS"
# CacheAtomicityMode ordinal: TRANSACTIONAL=0 (ATOMIC is 1 — with that,
# tx_start provides NO isolation and the harness would manufacture the
# very lost-updates it is checking for)
ATOMICITY_TRANSACTIONAL = 0


def connect_retry(host, port, deadline_s=90.0):
    """pyignite thin-client connect, retried while the server boots
    (the bridge daemon starts in the same breath as ignite.sh)."""
    import time

    t0 = time.monotonic()
    while True:
        client = IgniteClient()
        try:
            client.connect(host, port)
            return client
        except Exception:  # noqa: BLE001 - retry until deadline
            if time.monotonic() - t0 > deadline_s:
                raise
            time.sleep(2.0)


class Handler(socketserver.StreamRequestHandler):
    """One handler per bridge connection (1:1 with a jepsen client),
    each with its OWN pyignite client: the thin client is not
    thread-safe and its transactions are bound to the connection, so a
    shared client would interleave concurrent handlers' tx frames."""

    def handle(self):
        srv = self.server
        self.client = None
        for raw in self.rfile:
            line = raw.decode().strip()
            if not line:
                continue
            try:
                if self.client is None:
                    self.client = connect_retry(srv.db_host, srv.db_port)
                reply = self.dispatch(srv, line.split())
            except Exception as e:  # noqa: BLE001 - per-request report
                # newlines in driver messages would break the
                # one-line-per-reply framing (off-by-one replies)
                msg = f"{type(e).__name__}: {e}".replace("\n", " ")
                reply = f"ERR {msg}"
                # a dead DB connection must not poison later requests
                # (the DB may have been nemesis-killed and restarted)
                try:
                    if self.client is not None:
                        self.client.close()
                except Exception:  # noqa: BLE001
                    pass
                self.client = None
            self.wfile.write((reply + "\n").encode())
            self.wfile.flush()
        if self.client is not None:
            try:
                self.client.close()
            except Exception:  # noqa: BLE001
                pass

    def _tx(self, srv):
        # Finite timeout: Ignite only runs deadlock detection on
        # transactions with a timeout > 0, and the jepsen client gives
        # up at 10 s — a wedged tx must surface as ERR (:info) before
        # then, not hold its pessimistic locks forever.
        return self.client.tx_start(
            concurrency=TransactionConcurrency.PESSIMISTIC,
            isolation=TransactionIsolation.REPEATABLE_READ,
            timeout=5000)

    def dispatch(self, srv, words):
        cmd = words[0].upper()
        if cmd == "INIT":
            n, balance = int(words[1]), int(words[2])
            cache = self.client.get_or_create_cache({
                PROP_NAME: CACHE,
                PROP_CACHE_ATOMICITY_MODE: ATOMICITY_TRANSACTIONAL,
            })
            with srv.lock:
                if cache.get(0) is None:
                    for i in range(n):
                        cache.put(i, balance)
            return "OK"
        cache = self.client.get_cache(CACHE)
        if cmd == "READ":
            n = int(words[1])
            with self._tx(srv) as tx:
                vals = [cache.get(i) for i in range(n)]
                tx.commit()
            return "OK " + json.dumps(vals)
        if cmd == "XFER":
            frm, to, amount = int(words[1]), int(words[2]), int(words[3])
            if frm == to:
                # Self-transfer: balances unchanged either way, but the
                # reference still applies the insufficient-funds rule
                # (bank.clj:97-101 computes b1 = balance - amount before
                # looking at the destination) — an amount above the
                # balance must commit unchanged and report NEG, not OK.
                with self._tx(srv) as tx:
                    bal = cache.get(frm)
                    tx.commit()
                if bal - amount < 0:
                    return f"NEG {frm} {bal - amount}"
                return "OK"
            with self._tx(srv) as tx:
                # Acquire the two pessimistic key locks in KEY ORDER:
                # opposite-order transfers (A: 0->1, B: 1->0) would
                # otherwise lock one key each and block forever on the
                # other's (READ scans ascending, so it is compatible).
                bal = {k: cache.get(k) for k in sorted((frm, to))}
                b1 = bal[frm] - amount
                b2 = bal[to] + amount
                if b1 < 0:
                    tx.commit()
                    return f"NEG {frm} {b1}"
                if b2 < 0:
                    tx.commit()
                    return f"NEG {to} {b2}"
                cache.put(frm, b1)
                cache.put(to, b2)
                tx.commit()
            return "OK"
        return f"ERR unknown command {cmd}"


class Bridge(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=10801)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--db-port", type=int, default=10800)
    args = p.parse_args(argv)
    if IgniteClient is None:
        print("ig_bridge: the 'pyignite' client is not installed",
              file=sys.stderr)
        return 1
    srv = Bridge(("0.0.0.0", args.port), Handler)
    srv.db_host = args.host
    srv.db_port = args.db_port
    srv.lock = threading.Lock()
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    print(f"ig_bridge: serving on :{args.port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
