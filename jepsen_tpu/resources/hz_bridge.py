"""Node-side CP bridge daemon for the hazelcast suite.

The reference hazelcast suite ships its own server directory
(hazelcast/server/) because the stock wire protocol isn't scriptable;
this is the same move for this framework: a tiny TCP daemon running ON
THE DB NODE, translating the suite's newline-delimited commands into CP
subsystem calls through the official hazelcast-python-client (installed
on the node during DB setup, like the reference compiles its C helpers
on nodes, nemesis/time.clj:14-52).

Protocol (one request per line, one reply per line):

    LOCK <name>        -> OK <fence>   | ERR timeout | ERR <msg>
    UNLOCK <name>      -> OK           | ERR not-owner
    SEMACQ <name> <n>  -> OK           | ERR timeout
    SEMREL <name> <n>  -> OK
    ID <name>          -> OK <id>

Run: python3 hz_bridge.py [--port 5801] [--member 127.0.0.1:5701]
"""

from __future__ import annotations

import argparse
import socketserver
import sys
import threading

try:
    import hazelcast
except ImportError:  # surfaced at startup, not per-request
    hazelcast = None

LOCK_TIMEOUT_S = 5.0


class _MapLock:
    """AP pessimistic lock over an IMap key (map.lock/unlock) with a
    fence counter riding the map value — the non-CP lock shape the
    lock-no-quorum workload exercises. FencedLock API compatible for
    the bridge's purposes."""

    def __init__(self, imap, key: str):
        self.imap = imap
        self.key = key

    def try_lock_and_get_fence(self, timeout: float):
        if not self.imap.try_lock(self.key, lease_time=None,
                                  timeout=timeout):
            return 0
        fence = (self.imap.get(self.key) or 0) + 1
        self.imap.put(self.key, fence)
        return fence

    def unlock(self):
        self.imap.unlock(self.key)


class Bridge(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, member: str, sem_capacity: int = 2):
        super().__init__(addr, Handler)
        self.sem_capacity = sem_capacity
        self.client = hazelcast.HazelcastClient(
            cluster_members=[member],
            connection_timeout=10.0,
        )
        self.cp = self.client.cp_subsystem
        self.guard = threading.Lock()
        self.locks: dict = {}
        self.sems: dict = {}
        self.ids: dict = {}

    def lock(self, name):
        # The reference's lock-no-quorum scenario (hazelcast.clj:
        # 676-683) configured a 3.x ILock without a quorum rule; 3.x
        # locks and their XML are gone in 5.x, so the honest modern
        # translation is structural: names ending ".no-quorum" get an
        # AP map-based lock (keeps serving in minority partitions —
        # the misconfiguration under test) while everything else gets
        # the CP-subsystem FencedLock (Raft, majority by construction).
        with self.guard:
            if name not in self.locks:
                if name.endswith(".no-quorum"):
                    self.locks[name] = _MapLock(
                        self.client.get_map("jepsen-ap-locks").blocking(),
                        name)
                else:
                    self.locks[name] = self.cp.get_lock(name).blocking()
            return self.locks[name]

    def sem(self, name):
        with self.guard:
            if name not in self.sems:
                s = self.cp.get_semaphore(name).blocking()
                # CP semaphores start with 0 permits; init is a no-op
                # (returns False) when already initialized.
                s.init(self.sem_capacity)
                self.sems[name] = s
            return self.sems[name]

    def idgen(self, name):
        with self.guard:
            if name not in self.ids:
                self.ids[name] = self.client.get_flake_id_generator(
                    name).blocking()
            return self.ids[name]


class Handler(socketserver.StreamRequestHandler):
    def handle(self):
        srv: Bridge = self.server  # type: ignore[assignment]
        while True:
            line = self.rfile.readline()
            if not line:
                return
            try:
                reply = self.dispatch(srv, line.decode().split())
            except Exception as e:  # noqa: BLE001 - per-request isolation
                reply = f"ERR {type(e).__name__}: {e}"
            try:
                self.wfile.write((reply + "\n").encode())
            except OSError:
                return

    def dispatch(self, srv: Bridge, words) -> str:
        cmd, name = words[0].upper(), words[1]
        if cmd == "LOCK":
            # FencedLock.try_lock(timeout) returns the fence token, or
            # INVALID_FENCE (0) on timeout.
            fence = srv.lock(name).try_lock_and_get_fence(LOCK_TIMEOUT_S)
            if not fence:
                return "ERR timeout"
            return f"OK {fence}"
        if cmd == "UNLOCK":
            try:
                srv.lock(name).unlock()
            except Exception:  # noqa: BLE001 - not the holder
                return "ERR not-owner"
            return "OK"
        if cmd == "SEMACQ":
            n = int(words[2])
            if not srv.sem(name).try_acquire(n, LOCK_TIMEOUT_S):
                return "ERR timeout"
            return "OK"
        if cmd == "SEMREL":
            srv.sem(name).release(int(words[2]))
            return "OK"
        if cmd == "ID":
            return f"OK {srv.idgen(name).new_id()}"
        return "ERR unknown-command"


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=5801)
    p.add_argument("--member", default="127.0.0.1:5701")
    p.add_argument("--sem-capacity", type=int, default=2)
    args = p.parse_args(argv)
    if hazelcast is None:
        print("hazelcast-python-client is not installed", file=sys.stderr)
        return 1
    srv = Bridge(("0.0.0.0", args.port), args.member,
                 sem_capacity=args.sem_capacity)
    print(f"hz_bridge listening on {args.port} -> {args.member}", flush=True)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
