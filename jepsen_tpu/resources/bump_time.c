/* bump_time: jump the system wall clock by a signed delta, given in
 * milliseconds, then print the resulting time as unix seconds with
 * microsecond precision. Compiled on each DB node by the clock nemesis
 * (equivalent role to the reference's jepsen/resources/bump-time.c:1-54,
 * reimplemented over clock_gettime/clock_settime).
 *
 * usage: bump_time <delta-ms>
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

int main(int argc, char **argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
        return 1;
    }

    double delta_ms = atof(argv[1]);
    long long delta_ns = (long long)(delta_ms * 1e6);

    struct timespec now;
    if (clock_gettime(CLOCK_REALTIME, &now) != 0) {
        perror("clock_gettime");
        return 1;
    }

    long long ns = (long long)now.tv_sec * 1000000000LL + now.tv_nsec;
    ns += delta_ns;
    if (ns < 0) ns = 0;

    struct timespec next;
    next.tv_sec = ns / 1000000000LL;
    next.tv_nsec = ns % 1000000000LL;

    if (clock_settime(CLOCK_REALTIME, &next) != 0) {
        perror("clock_settime");
        return 2;
    }

    if (clock_gettime(CLOCK_REALTIME, &now) != 0) {
        perror("clock_gettime");
        return 1;
    }
    printf("%lld.%06ld\n", (long long)now.tv_sec, now.tv_nsec / 1000);
    return 0;
}
