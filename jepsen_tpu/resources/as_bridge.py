"""Node-side bridge daemon for the aerospike suite.

The reference's cas-register/counter workloads run generation-guarded
operate() calls through the official Java client
(aerospike/src/aerospike/support.clj:348-445) — a surface ``aql``
cannot script.  Same move as hz_bridge.py: a tiny TCP daemon ON the DB
node translating newline commands into official-python-client calls
(the client library is installed during DB setup, like the reference
compiles its C helpers on nodes).

Protocol (one request per line, one reply per line; values are JSON):

    GET <set> <key>                  -> OK <json {"gen": g, "bins": {...}}> | NIL
    PUT <set> <key> <json-bins>      -> OK
    CAS <set> <key> <json-expect> <json-new>
        -> OK | MISS (value mismatch)         [support.clj "skipping cas"]
         | GEN (generation conflict)          [result code 3]
         | ERR not-found                      [support.clj "cas not found"]
    ADD <set> <key> <bin> <delta>    -> OK

CAS mirrors support.clj's cas!: linearized fetch, compare the ``value``
bin, then a write whose WritePolicy pins EXPECT_GEN_EQUAL to the
fetched generation — lost the race => GEN, which definitively did not
write.

Run: python3 as_bridge.py [--port 5601] [--host 127.0.0.1]
"""

from __future__ import annotations

import argparse
import json
import socketserver
import sys
import threading

try:
    import aerospike
except ImportError:  # surfaced at startup, not per-request
    aerospike = None

NS = "test"


def _key(setname: str, raw: str):
    try:
        return (NS, setname, int(raw))
    except ValueError:
        return (NS, setname, raw)


def _connect(srv):
    return aerospike.client(
        {"hosts": [(srv.db_host, srv.db_port)],
         "policies": {"read": {"read_mode_sc":
                               aerospike.POLICY_READ_MODE_SC_LINEARIZE}}}
    ).connect()


def ensure_client(srv, deadline_s=90.0):
    """Shared client (the aerospike python client is thread-safe),
    created lazily with retry while asd boots and re-created after a
    request-level failure (a nemesis may have killed the daemon)."""
    import time

    with srv.client_lock:
        if srv.client is not None:
            return srv.client
        t0 = time.monotonic()
        while True:
            try:
                srv.client = _connect(srv)
                return srv.client
            except Exception:  # noqa: BLE001 - retry until deadline
                if time.monotonic() - t0 > deadline_s:
                    raise
                time.sleep(2.0)


class Handler(socketserver.StreamRequestHandler):
    def handle(self):
        srv = self.server
        for raw in self.rfile:
            line = raw.decode().strip()
            if not line:
                continue
            try:
                reply = self.dispatch(ensure_client(srv),
                                      line.split(" ", 4))
            except Exception as e:  # noqa: BLE001 - per-request report
                # newlines in driver messages would break the
                # one-line-per-reply framing (off-by-one replies)
                msg = f"{type(e).__name__}: {e}".replace("\n", " ")
                reply = f"ERR {msg}"
                with srv.client_lock:  # force a reconnect next request
                    try:
                        if srv.client is not None:
                            srv.client.close()
                    except Exception:  # noqa: BLE001
                        pass
                    srv.client = None
            self.wfile.write((reply + "\n").encode())
            self.wfile.flush()

    def dispatch(self, client, words):
        cmd = words[0].upper()
        if cmd == "GET":
            _, setname, k = words[:3]
            try:
                _key_, meta, bins = client.get(_key(setname, k))
            except aerospike.exception.RecordNotFound:
                return "NIL"
            return "OK " + json.dumps(
                {"gen": meta.get("gen"), "bins": bins})
        if cmd == "PUT":
            _, setname, k, payload = words[:4]
            client.put(_key(setname, k), json.loads(payload))
            return "OK"
        if cmd == "CAS":
            _, setname, k, expect, new = words[:5]
            key = _key(setname, k)
            try:
                _key_, meta, bins = client.get(key)
            except aerospike.exception.RecordNotFound:
                return "ERR not-found"
            if bins.get("value") != json.loads(expect):
                return "MISS"
            try:
                client.put(key, {"value": json.loads(new)},
                           meta={"gen": meta["gen"]},
                           policy={"gen": aerospike.POLICY_GEN_EQ})
            except aerospike.exception.RecordGenerationError:
                return "GEN"
            return "OK"
        if cmd == "ADD":
            _, setname, k, bin_, delta = words[:5]
            client.increment(_key(setname, k), bin_, int(delta))
            return "OK"
        return f"ERR unknown command {cmd}"


class Bridge(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=5601)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--db-port", type=int, default=3000)
    args = p.parse_args(argv)
    if aerospike is None:
        print("as_bridge: the 'aerospike' python client is not installed",
              file=sys.stderr)
        return 1
    srv = Bridge(("0.0.0.0", args.port), Handler)
    srv.db_host = args.host
    srv.db_port = args.db_port
    srv.client = None
    srv.client_lock = threading.Lock()
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    print(f"as_bridge: serving on :{args.port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
