/* adjtime: gradually skew the system wall clock by a signed delta given
 * in milliseconds, using adjtime(3) so the kernel slews the clock
 * instead of jumping it — the "skew" fault the cockroachdb suite drives
 * alongside its bump tool (equivalent role to the reference's
 * cockroachdb/resources/adjtime.c, consumed by
 * cockroach/nemesis.clj:101-140). Prints the remaining outstanding
 * adjustment (signed seconds, microsecond precision) from any previous
 * call.
 *
 * usage: adjtime <delta-ms>      start slewing by delta
 *        adjtime 0               report/cancel outstanding adjustment
 */
#include <stdio.h>
#include <stdlib.h>
#include <sys/time.h>

int main(int argc, char **argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
        return 1;
    }

    double delta_ms = atof(argv[1]);
    long long delta_us = (long long)(delta_ms * 1000.0);

    struct timeval delta, old;
    delta.tv_sec = delta_us / 1000000LL;
    delta.tv_usec = delta_us % 1000000LL;
    if (delta.tv_usec < 0) {
        delta.tv_sec -= 1;
        delta.tv_usec += 1000000;
    }

    if (adjtime(&delta, &old) != 0) {
        perror("adjtime");
        return 2;
    }

    /* Normalize to one signed microsecond count so the sign prints
     * correctly for negative outstanding adjustments. */
    long long old_us = (long long)old.tv_sec * 1000000LL + old.tv_usec;
    long long mag = old_us < 0 ? -old_us : old_us;
    printf("%s%lld.%06lld\n", old_us < 0 ? "-" : "",
           mag / 1000000LL, mag % 1000000LL);
    return 0;
}
