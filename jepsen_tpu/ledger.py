"""CLI shim: ``python -m jepsen_tpu.ledger`` — the cross-run perf
ledger's trend table and regression gate. The implementation lives in
``jepsen_tpu.telemetry.ledger`` (next to the utilization and profile
layers it summarizes); this module only provides the short ``-m``
entry point docs and CI invoke."""

from __future__ import annotations

import sys

from .telemetry.ledger import main  # noqa: F401 - re-exported entry

if __name__ == "__main__":
    sys.exit(main())
