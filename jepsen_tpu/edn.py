"""EDN reader/writer.

The reference persists histories and results as EDN (`history.edn`,
`results.edn`; jepsen/src/jepsen/store.clj:345-397) and its op maps use
keywords (`:type :invoke`, `:f :read`, ...). To let archived reference
histories replay directly on this framework (BASELINE config 5, "batch
replay"), we implement a self-contained EDN codec: no third-party deps.

Mapping EDN -> Python:

==============  ==========================================
EDN             Python
==============  ==========================================
nil             None
true/false      True/False
integers        int        (incl. N-suffixed bigints)
floats          float      (incl. M-suffixed decimals)
strings         str
characters      Char
keywords        Keyword    (interned; ``K("f")`` helper)
symbols         Symbol
list ()         tuple  (tagged as List via subclass EdnList)
vector []       list
map {}          dict   (keys must be hashable; list keys -> tuple)
set #{}         frozenset
#tag value      Tagged(tag, value)   (#inst/#uuid included)
==============  ==========================================
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Iterator


class Keyword:
    """An interned EDN keyword (``:foo`` or ``:ns/name``).

    Interning makes `Keyword("f") is Keyword("f")` true, so keyword-keyed
    dicts behave like Clojure maps with keyword keys.
    """

    __slots__ = ("name",)
    _interned: dict[str, "Keyword"] = {}
    _lock = threading.Lock()

    def __new__(cls, name: str) -> "Keyword":
        kw = cls._interned.get(name)
        if kw is None:
            with cls._lock:
                kw = cls._interned.get(name)
                if kw is None:
                    kw = object.__new__(cls)
                    kw.name = name
                    cls._interned[name] = kw
        return kw

    def __repr__(self) -> str:
        return ":" + self.name

    def __hash__(self) -> int:
        return hash((Keyword, self.name))

    def __eq__(self, other: object) -> bool:
        return self is other

    def __lt__(self, other: "Keyword") -> bool:
        return self.name < other.name

    def __reduce__(self):  # pickle support (Keyword is interned)
        return (Keyword, (self.name,))


def K(name: str) -> Keyword:
    """Shorthand constructor: ``K("invoke")`` == ``Keyword("invoke")``."""
    return Keyword(name)


class Symbol:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self) -> str:
        return self.name

    def __hash__(self) -> int:
        return hash((Symbol, self.name))

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Symbol) and other.name == self.name


class Char:
    __slots__ = ("c",)

    def __init__(self, c: str):
        self.c = c

    def __repr__(self) -> str:
        return "\\" + self.c

    def __hash__(self) -> int:
        return hash((Char, self.c))

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Char) and other.c == self.c


class EdnList(tuple):
    """An EDN list ``(...)`` — distinct from a vector, printed with parens."""


class FrozenMap(tuple):
    """An immutable map usable as a dict key / set member: a tuple of sorted
    (key, value) pairs that prints back as an EDN map, keeping map-keyed
    maps and sets-of-maps round-trippable."""

    def to_dict(self) -> dict:
        return {k: v for k, v in self}


@dataclass(frozen=True)
class Tagged:
    tag: str
    value: Any


_CHAR_NAMES = {
    "newline": "\n",
    "return": "\r",
    "space": " ",
    "tab": "\t",
    "backspace": "\b",
    "formfeed": "\f",
}
_CHAR_NAMES_INV = {v: k for k, v in _CHAR_NAMES.items()}

_DELIMS = set('()[]{}"; ')
_WS = set(" \t\n\r,")


class _Reader:
    __slots__ = ("s", "i", "n")

    def __init__(self, s: str):
        self.s = s
        self.i = 0
        self.n = len(s)

    def error(self, msg: str) -> Exception:
        line = self.s.count("\n", 0, self.i) + 1
        return ValueError(f"EDN parse error at pos {self.i} (line {line}): {msg}")

    def skip_ws(self) -> None:
        s, n = self.s, self.n
        i = self.i
        while i < n:
            c = s[i]
            if c in _WS:
                i += 1
            elif c == ";":  # comment to end of line
                j = s.find("\n", i)
                i = n if j < 0 else j + 1
            elif c == "#" and s.startswith("#_", i):  # discard next form
                self.i = i + 2
                self.skip_ws()
                self.read()  # read and drop
                i = self.i
            else:
                break
        self.i = i

    def read(self) -> Any:
        self.skip_ws()
        if self.i >= self.n:
            raise self.error("unexpected EOF")
        c = self.s[self.i]
        if c == "(":
            self.i += 1
            return EdnList(self._read_seq(")"))
        if c == "[":
            self.i += 1
            return self._read_seq("]")
        if c == "{":
            self.i += 1
            return self._read_map()
        if c == '"':
            return self._read_string()
        if c == "\\":
            return self._read_char()
        if c == "#":
            return self._read_dispatch()
        if c == ":":
            self.i += 1
            return Keyword(self._read_token())
        if c.isdigit() or (c in "+-" and self.i + 1 < self.n and self.s[self.i + 1].isdigit()):
            return self._read_number()
        tok = self._read_token()
        if not tok:
            raise self.error(f"unexpected {c!r}")
        if tok == "nil":
            return None
        if tok == "true":
            return True
        if tok == "false":
            return False
        return Symbol(tok)

    def _read_seq(self, close: str) -> list:
        out = []
        while True:
            self.skip_ws()
            if self.i >= self.n:
                raise self.error(f"unterminated sequence, expected {close!r}")
            if self.s[self.i] == close:
                self.i += 1
                return out
            out.append(self.read())

    def _read_map(self) -> dict:
        items = self._read_seq("}")
        if len(items) % 2:
            raise self.error("map literal with odd number of forms")
        out = {}
        for k, v in zip(items[::2], items[1::2]):
            out[_hashable(k)] = v
        return out

    def _read_string(self) -> str:
        s = self.s
        i = self.i + 1
        buf: list[str] = []
        while i < self.n:
            c = s[i]
            if c == '"':
                self.i = i + 1
                return "".join(buf)
            if c == "\\":
                i += 1
                if i >= self.n:
                    break
                e = s[i]
                if e == "n":
                    buf.append("\n")
                elif e == "t":
                    buf.append("\t")
                elif e == "r":
                    buf.append("\r")
                elif e == "b":
                    buf.append("\b")
                elif e == "f":
                    buf.append("\f")
                elif e == "u":
                    buf.append(chr(int(s[i + 1 : i + 5], 16)))
                    i += 4
                else:
                    buf.append(e)  # \" \\ \/ and anything else literal
                i += 1
            else:
                buf.append(c)
                i += 1
        raise self.error("unterminated string")

    def _read_char(self) -> Char:
        self.i += 1  # skip backslash
        if self.i >= self.n:
            raise self.error("EOF after \\")
        start = self.i
        if not self.s[start].isalnum():
            # single non-alphanumeric char, incl. delimiters: \( \" \, ...
            self.i += 1
            return Char(self.s[start])
        while self.i < self.n and self.s[self.i] not in _WS and self.s[self.i] not in _DELIMS:
            self.i += 1
        tok = self.s[start : self.i]
        if len(tok) == 1:
            return Char(tok)
        if tok in _CHAR_NAMES:
            return Char(_CHAR_NAMES[tok])
        if tok.startswith("u") and len(tok) == 5:
            return Char(chr(int(tok[1:], 16)))
        raise self.error(f"unknown character literal \\{tok}")

    def _read_dispatch(self) -> Any:
        s = self.s
        if s.startswith("#{", self.i):
            self.i += 2
            return frozenset(_hashable(x) for x in self._read_seq("}"))
        if s.startswith("##", self.i):
            self.i += 2
            tok = self._read_token()
            m = {"Inf": float("inf"), "-Inf": float("-inf"), "NaN": float("nan")}
            if tok in m:
                return m[tok]
            raise self.error(f"unknown ## literal {tok}")
        # tagged literal: #tag value
        self.i += 1
        tag = self._read_token()
        if not tag:
            raise self.error("bad dispatch")
        value = self.read()
        return Tagged(tag, value)

    def _read_token(self) -> str:
        start = self.i
        s, n = self.s, self.n
        i = self.i
        while i < n and s[i] not in _WS and s[i] not in _DELIMS:
            i += 1
        self.i = i
        return s[start:i]

    def _read_number(self) -> Any:
        start = self.i
        s, n = self.s, self.n
        i = self.i
        if s[i] in "+-":
            i += 1
        is_float = False
        while i < n and s[i] not in _WS and s[i] not in _DELIMS:
            if s[i] in ".eE" and not (s[i] in "eE" and s[i - 1] in "+-"):
                is_float = True
            i += 1
        tok = s[start:i]
        self.i = i
        if tok.endswith("N"):
            return int(tok[:-1])
        if tok.endswith("M"):
            return float(tok[:-1])
        if tok.lstrip("+-").lower().startswith("0x"):
            return int(tok, 16)
        if is_float or ("e" in tok or "E" in tok) or "." in tok:
            return float(tok)
        try:
            return int(tok)
        except ValueError:
            return float(tok)


def _hashable(x: Any) -> Any:
    """Coerce a parsed form into something usable as a dict key / set member."""
    if isinstance(x, EdnList):
        return EdnList(_hashable(e) for e in x)
    if isinstance(x, (list, tuple)):
        return tuple(_hashable(e) for e in x)
    if isinstance(x, dict):
        return FrozenMap(sorted(((k, _hashable(v)) for k, v in x.items()), key=repr))
    if isinstance(x, Tagged):
        return Tagged(x.tag, _hashable(x.value))
    return x


def _fast_reader():
    """The native (C extension) reader, or None. Accelerator only: it
    raises FastParseError on any grammar it doesn't cover (tagged
    literals, chars, ratios, bignums) and the callers below fall back to
    the full python reader — behavior is always the python reader's."""
    from . import native

    return native.load_edn_fast()


def read_string(s: str) -> Any:
    """Parse a single EDN form from ``s``; trailing non-whitespace is an error."""
    fast = _fast_reader()
    if fast is not None:
        try:
            forms = fast.parse_all(s)
        except fast.FastParseError:
            pass
        else:
            if len(forms) != 1:
                raise ValueError(
                    "trailing content after form" if forms
                    else "unexpected end of input")
            return forms[0]
    r = _Reader(s)
    v = r.read()
    r.skip_ws()
    if r.i < r.n:
        raise r.error("trailing content after form")
    return v


def read_all(s: str) -> Iterator[Any]:
    """Parse every top-level form in ``s`` (e.g. a history.edn file, one
    op map per line — store.clj:351-362 writes one form per line). Runs
    on the native reader when the grammar allows, the python reader
    otherwise.

    Laziness: the native fast path materializes EVERY form before the
    first is yielded (one C call parses the whole buffer); only the
    python fallback streams form-by-form. Batch consumers (the history
    loader, replay) read everything anyway, so peak memory is the same
    — but callers that want to stop early on multi-GB files should chunk
    the input per line themselves before calling."""
    fast = _fast_reader()
    if fast is not None:
        try:
            return iter(fast.parse_all(s))
        except fast.FastParseError:
            pass
    def gen():
        r = _Reader(s)
        while True:
            r.skip_ws()
            if r.i >= r.n:
                return
            yield r.read()

    return gen()


# ---------------------------------------------------------------------------
# Printer


def _needs_quotes_str(s: str) -> str:
    out = ['"']
    for c in s:
        if c == '"':
            out.append('\\"')
        elif c == "\\":
            out.append("\\\\")
        elif c == "\n":
            out.append("\\n")
        elif c == "\t":
            out.append("\\t")
        elif c == "\r":
            out.append("\\r")
        else:
            out.append(c)
    out.append('"')
    return "".join(out)


def write_string(x: Any) -> str:
    """Print ``x`` as EDN, round-trippable through :func:`read_string`."""
    buf: list[str] = []
    _write(x, buf)
    return "".join(buf)


def _write(x: Any, buf: list[str]) -> None:
    if x is None:
        buf.append("nil")
    elif x is True:
        buf.append("true")
    elif x is False:
        buf.append("false")
    elif isinstance(x, Keyword):
        buf.append(":" + x.name)
    elif isinstance(x, Symbol):
        buf.append(x.name)
    elif isinstance(x, Char):
        buf.append("\\" + _CHAR_NAMES_INV.get(x.c, x.c))
    elif isinstance(x, str):
        buf.append(_needs_quotes_str(x))
    elif isinstance(x, bool):  # pragma: no cover - caught above
        buf.append("true" if x else "false")
    elif isinstance(x, int):
        buf.append(str(x))
    elif isinstance(x, float):
        if x != x:
            buf.append("##NaN")
        elif x == float("inf"):
            buf.append("##Inf")
        elif x == float("-inf"):
            buf.append("##-Inf")
        else:
            buf.append(repr(x))
    elif isinstance(x, Tagged):
        buf.append("#" + x.tag + " ")
        _write(x.value, buf)
    elif isinstance(x, EdnList):
        buf.append("(")
        for j, e in enumerate(x):
            if j:
                buf.append(" ")
            _write(e, buf)
        buf.append(")")
    elif isinstance(x, FrozenMap):
        buf.append("{")
        for j, (k, v) in enumerate(x):
            if j:
                buf.append(", ")
            _write(k, buf)
            buf.append(" ")
            _write(v, buf)
        buf.append("}")
    elif isinstance(x, dict):
        buf.append("{")
        for j, (k, v) in enumerate(x.items()):
            if j:
                buf.append(", ")
            _write(k, buf)
            buf.append(" ")
            _write(v, buf)
        buf.append("}")
    elif isinstance(x, (frozenset, set)):
        buf.append("#{")
        for j, e in enumerate(sorted(x, key=repr)):
            if j:
                buf.append(" ")
            _write(e, buf)
        buf.append("}")
    elif isinstance(x, (list, tuple)):
        buf.append("[")
        for j, e in enumerate(x):
            if j:
                buf.append(" ")
            _write(e, buf)
        buf.append("]")
    else:
        # numpy scalars and other numerics degrade gracefully
        try:
            buf.append(str(int(x)) if float(x).is_integer() else repr(float(x)))
        except (TypeError, ValueError):
            buf.append(_needs_quotes_str(str(x)))
