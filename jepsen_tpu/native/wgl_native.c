/* Native WGL linearizability search.
 *
 * The reference's compute kernel is knossos (JVM) — this is the
 * native-runtime equivalent for the host side: a Wing & Gong / Lowe
 * breadth-first search over (prefix, window-bitset, open-set,
 * model-state) configurations, sharing the device kernel's
 * representation (jepsen_tpu/ops/wgl.py docstring): determinate ops
 * sorted by invocation, a prefix pointer p with a 64-bit window bitset,
 * a multi-word open-op set (64 * NO_WORDS ops), and a fixed-width int state vector. Model
 * transition functions mirror jepsen_tpu/models/{register,mutex}.py
 * step_scalar exactly; differential tests pin all three implementations
 * (python host / XLA device / native C) together.
 *
 * Compiled on demand by jepsen_tpu/native/__init__.py with cc; the ABI
 * is a single entry point:
 *
 *   int wgl_check(args...) -> 1 accepted | 0 not linearizable |
 *                             -1 budget exhausted | -2 unsupported |
 *                             -3 out of memory
 */

#include <pthread.h>
#include <stdatomic.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define S_MAX 8
#define OPEN_SENTINEL 2147483647
#define UNKNOWN_VAL (-2147483647 - 1)

#define NO_WORDS 4 /* open-op set: up to 256 :info ops */

typedef struct {
    int32_t p;
    uint64_t win;
    uint64_t open[NO_WORDS];
    int32_t st[S_MAX];
} cfg_t;

static inline int open_test(const cfg_t *c, int o) {
    return (int)((c->open[o >> 6] >> (o & 63)) & 1);
}

static inline void open_set_bit(cfg_t *c, int o) {
    c->open[o >> 6] |= 1ULL << (o & 63);
}

/* a's open-set is a subset of b's */
static inline int open_subset(const uint64_t *a, const uint64_t *b) {
    for (int w = 0; w < NO_WORDS; w++)
        if (a[w] & ~b[w])
            return 0;
    return 1;
}

static inline int open_eq(const uint64_t *a, const uint64_t *b) {
    for (int w = 0; w < NO_WORDS; w++)
        if (a[w] != b[w])
            return 0;
    return 1;
}

static inline int open_lt(const uint64_t *a, const uint64_t *b) {
    for (int w = NO_WORDS - 1; w >= 0; w--) {
        if (a[w] != b[w])
            return a[w] < b[w];
    }
    return 0;
}

/* ------------------------------------------------------------------ */
/* Models (mirror models/register.py + models/mutex.py step_scalar).   */

enum {
    MODEL_CAS_REGISTER = 1,   /* also plain register */
    MODEL_MUTEX = 2,
    MODEL_OWNER_MUTEX = 3,
    MODEL_REENTRANT_MUTEX = 4,
    MODEL_FENCED_MUTEX = 5,
    MODEL_REENTRANT_FENCED = 6,
    MODEL_SEMAPHORE = 7
};

/* opcode constants shared with the python encoders */
#define OP_READ 0
#define OP_WRITE 1
#define OP_CAS 2
#define OP_ACQUIRE 0
#define OP_RELEASE 1

int wgl_max_open(void) { return 64 * NO_WORDS; }

static int step_model(int model_id, int64_t param, const int32_t *st,
                      int32_t op, int32_t a1, int32_t a2, int32_t *out) {
    switch (model_id) {
    case MODEL_CAS_REGISTER: {
        int32_t v = st[0];
        if (op == OP_READ) {
            out[0] = v;
            return a1 == UNKNOWN_VAL || v == a1;
        }
        if (op == OP_WRITE) {
            out[0] = a1;
            return 1;
        }
        /* cas */
        if (v == a1) {
            out[0] = a2;
            return 1;
        }
        out[0] = v;
        return 0;
    }
    case MODEL_MUTEX: {
        int32_t locked = st[0];
        if (op == OP_ACQUIRE) {
            out[0] = 1;
            return locked == 0;
        }
        out[0] = 0;
        return locked == 1;
    }
    case MODEL_OWNER_MUTEX: {
        int32_t owner = st[0];
        if (op == OP_ACQUIRE) {
            out[0] = a1 + 1;
            return owner == 0;
        }
        out[0] = 0;
        return owner == a1 + 1;
    }
    case MODEL_REENTRANT_MUTEX: {
        int32_t depth = st[0];
        if (op == OP_ACQUIRE) {
            out[0] = depth + 1;
            return depth < (int32_t)param;
        }
        out[0] = depth > 0 ? depth - 1 : 0;
        return depth > 0;
    }
    case MODEL_FENCED_MUTEX: {
        int32_t owner = st[0], last = st[1];
        if (op == OP_ACQUIRE) {
            out[0] = a1 + 1;
            out[1] = (a2 == UNKNOWN_VAL) ? last : a2;
            return owner == 0 && (a2 == UNKNOWN_VAL || a2 > last);
        }
        out[0] = 0;
        out[1] = last;
        return owner == a1 + 1;
    }
    case MODEL_REENTRANT_FENCED: {
        /* state: owner+1, count, current fence, highest observed */
        int32_t owner = st[0], count = st[1], cur = st[2], hof = st[3];
        int32_t client = a1 + 1, f = a2;
        if (op == OP_ACQUIRE) {
            if (owner == 0) {
                out[0] = client;
                out[1] = 1;
                out[2] = f;
                out[3] = (f != UNKNOWN_VAL && f > hof) ? f : hof;
                return f == UNKNOWN_VAL || f > hof;
            }
            if (owner != client || count >= 2) {
                memcpy(out, st, sizeof(int32_t) * 4);
                return 0;
            }
            if (cur == UNKNOWN_VAL) {
                out[0] = client;
                out[1] = count + 1;
                out[2] = f;
                out[3] = (f != UNKNOWN_VAL && f > hof) ? f : hof;
                return f == UNKNOWN_VAL || f > hof;
            }
            if (f == UNKNOWN_VAL || f == cur) {
                out[0] = client;
                out[1] = count + 1;
                out[2] = cur;
                out[3] = hof;
                return 1;
            }
            memcpy(out, st, sizeof(int32_t) * 4);
            return 0;
        }
        /* release */
        if (owner == 0 || owner != client) {
            memcpy(out, st, sizeof(int32_t) * 4);
            return 0;
        }
        if (count == 1) {
            out[0] = 0;
            out[1] = 0;
            out[2] = UNKNOWN_VAL;
            out[3] = hof;
            return 1;
        }
        out[0] = owner;
        out[1] = count - 1;
        out[2] = cur;
        out[3] = hof;
        return 1;
    }
    case MODEL_SEMAPHORE: {
        int32_t acq = st[0];
        if (op == OP_ACQUIRE) {
            out[0] = acq + a1;
            return acq + a1 <= (int32_t)param;
        }
        out[0] = acq >= a1 ? acq - a1 : 0;
        return acq >= a1;
    }
    }
    return 0;
}

/* ------------------------------------------------------------------ */
/* Config hash set: open addressing, linear probing.                  */

typedef struct {
    cfg_t *slots;
    uint8_t *used;
    size_t cap; /* power of two */
    size_t count;
} set_t;

static uint64_t cfg_hash(const cfg_t *c, int S) {
    uint64_t h = 1469598103934665603ULL;
    const uint8_t *b = (const uint8_t *)c;
    size_t len = sizeof(int32_t) + sizeof(uint64_t) * 2 +
                 sizeof(int32_t) * (size_t)S;
    /* hash p, win, open, st[0..S) — the struct layout places them first */
    (void)len;
    h = (h ^ (uint64_t)(uint32_t)c->p) * 1099511628211ULL;
    h = (h ^ c->win) * 1099511628211ULL;
    for (int w = 0; w < NO_WORDS; w++)
        h = (h ^ c->open[w]) * 1099511628211ULL;
    for (int i = 0; i < S; i++)
        h = (h ^ (uint64_t)(uint32_t)c->st[i]) * 1099511628211ULL;
    (void)b;
    return h;
}

static int cfg_eq(const cfg_t *a, const cfg_t *b, int S) {
    if (a->p != b->p || a->win != b->win || !open_eq(a->open, b->open))
        return 0;
    return memcmp(a->st, b->st, sizeof(int32_t) * (size_t)S) == 0;
}

static int set_init(set_t *s, size_t cap) {
    s->cap = cap;
    s->count = 0;
    s->slots = (cfg_t *)malloc(sizeof(cfg_t) * cap);
    s->used = (uint8_t *)calloc(cap, 1);
    return s->slots && s->used;
}

static void set_free(set_t *s) {
    free(s->slots);
    free(s->used);
}

static int set_grow(set_t *s, int S);

/* returns 1 if inserted (new), 0 if already present, -1 on OOM */
static int set_insert(set_t *s, const cfg_t *c, int S) {
    if (s->count * 4 >= s->cap * 3) {
        if (!set_grow(s, S))
            return -1;
    }
    uint64_t h = cfg_hash(c, S);
    size_t i = (size_t)(h & (s->cap - 1));
    while (s->used[i]) {
        if (cfg_eq(&s->slots[i], c, S))
            return 0;
        i = (i + 1) & (s->cap - 1);
    }
    s->used[i] = 1;
    s->slots[i] = *c;
    s->count++;
    return 1;
}

static int set_grow(set_t *s, int S) {
    set_t bigger;
    if (!set_init(&bigger, s->cap * 2))
        return 0;
    for (size_t i = 0; i < s->cap; i++) {
        if (!s->used[i]) continue;
        uint64_t h = cfg_hash(&s->slots[i], S);
        size_t j = (size_t)(h & (bigger.cap - 1));
        while (bigger.used[j])
            j = (j + 1) & (bigger.cap - 1);
        bigger.used[j] = 1;
        bigger.slots[j] = s->slots[i];
        bigger.count++;
    }
    set_free(s);
    *s = bigger;
    return 1;
}

/* ------------------------------------------------------------------ */
/* Open-set dominance prune (mirrors the device kernel's): among
 * configs with equal (p, win, state), one whose open-set is a superset
 * of another's is subsumed — open ops are never required, so fewer
 * consumed opens dominates. Sort groups together, then drop entries
 * whose open-set contains the group minimum (or their predecessor). */

static int cfg_cmp(const void *pa, const void *pb) {
    /* No per-call state: lanes beyond the model's S are always zero
     * (the root config is memset and transitions write only S lanes),
     * so comparing the full S_MAX width is equivalent — and keeps the
     * comparator safe under concurrent checks. */
    const cfg_t *a = (const cfg_t *)pa, *b = (const cfg_t *)pb;
    if (a->p != b->p)
        return a->p < b->p ? -1 : 1;
    if (a->win != b->win)
        return a->win < b->win ? -1 : 1;
    int c = memcmp(a->st, b->st, sizeof(int32_t) * S_MAX);
    if (c)
        return c;
    if (!open_eq(a->open, b->open))
        return open_lt(a->open, b->open) ? -1 : 1;
    return 0;
}

static size_t dominance_prune(cfg_t *items, size_t len, int S) {
    if (len < 2)
        return len;
    qsort(items, len, sizeof(cfg_t), cfg_cmp);
    size_t out = 0;
    uint64_t head_open[NO_WORDS] = {0};
    const cfg_t *group = NULL;
    uint64_t prev_open[NO_WORDS] = {0};
    for (size_t i = 0; i < len; i++) {
        cfg_t *c = &items[i];
        int same = group && c->p == group->p && c->win == group->win &&
                   memcmp(c->st, group->st,
                          sizeof(int32_t) * (size_t)S) == 0;
        if (!same) {
            group = c;
            memcpy(head_open, c->open, sizeof(head_open));
            memcpy(prev_open, c->open, sizeof(prev_open));
            items[out++] = *c;
            continue;
        }
        /* drop exact dups, supersets of the group head, and supersets
         * of the previous (kept-or-dropped) entry — sound by induction */
        if (open_subset(head_open, c->open) ||
            open_subset(prev_open, c->open)) {
            memcpy(prev_open, c->open, sizeof(prev_open));
            continue;
        }
        memcpy(prev_open, c->open, sizeof(prev_open));
        items[out++] = *c;
    }
    return out;
}

/* ------------------------------------------------------------------ */
/* Dominance-aware memo for the DFS: a hash map keyed by
 * (p, win, state) whose value is an ANTICHAIN of open-masks, kept
 * sorted by popcount. A new config whose open-set is a superset of any
 * stored mask for its key is subsumed (open ops are never required and
 * never bound others: every future reachable from the superset is
 * reachable from the subset with identical state) — this collapses the
 * open-subset powerset that dominates refutation cost, where the
 * exact-equality memo had to visit every subset combination.
 * Stored masks that are supersets of a new mask are removed: the new
 * (dominating) entry prunes everything they would have pruned. */

/* Dominance-memo mask width: one word for the window-read complement
 * (the read-collapse reduction) plus the open-op set.  For register
 * models, a linearized READ never changes state, so config A dominates
 * B at the same (p, non-read window bits, state) whenever A has
 * linearized a SUPERSET of the window reads with a SUBSET of the open
 * ops: delete the extra reads from B's accepting completion and the
 * state trajectory is unchanged while every min-return bound only
 * loosens.  Encoding the read bits as their complement turns both
 * conditions into one componentwise subset test over [read-compl,
 * open words] — exactly the antichain machinery below. */
#define DOM_WORDS (NO_WORDS + 1)

typedef struct {
    int32_t p;
    uint64_t win;
    int32_t st[S_MAX];
    int32_t n;        /* stored masks */
    int32_t mcap;
    uint64_t *masks;  /* n * DOM_WORDS, popcount-ascending */
    uint8_t *pc;      /* popcount per mask */
} dom_slot_t;

typedef struct {
    dom_slot_t *slots;
    uint8_t *used;
    size_t cap;   /* power of two */
    size_t count; /* distinct keys */
} domset_t;

static uint64_t dom_key_hash(int32_t p, uint64_t win, const int32_t *st) {
    /* Always hashes S_MAX state lanes: lanes beyond the model's S are
     * zero everywhere (the root is memset and transitions write only S
     * lanes), so this is S-independent — the table can rehash without
     * knowing S. */
    uint64_t h = 1469598103934665603ULL;
    h = (h ^ (uint64_t)(uint32_t)p) * 1099511628211ULL;
    h = (h ^ win) * 1099511628211ULL;
    for (int i = 0; i < S_MAX; i++)
        h = (h ^ (uint64_t)(uint32_t)st[i]) * 1099511628211ULL;
    return h;
}

static int dom_init(domset_t *s, size_t cap) {
    s->cap = cap;
    s->count = 0;
    s->slots = (dom_slot_t *)malloc(sizeof(dom_slot_t) * cap);
    s->used = (uint8_t *)calloc(cap, 1);
    return s->slots && s->used;
}

static void dom_free(domset_t *s) {
    if (s->slots)
        for (size_t i = 0; i < s->cap; i++)
            if (s->used[i])
                free(s->slots[i].masks); /* pc rides the same block */
    free(s->slots);
    free(s->used);
}

static int dom_popcount(const uint64_t *m) {
    int n = 0;
    for (int w = 0; w < DOM_WORDS; w++)
        n += __builtin_popcountll(m[w]);
    /* Clamped to fit the uint8_t pc lanes: 320 set bits (a full
     * 5-word vector) would wrap and skip the whole subset scan. The
     * clamp only coarsens the scan bound — subset checks run on the
     * real masks. */
    return n > 255 ? 255 : n;
}

static inline int dom_subset(const uint64_t *a, const uint64_t *b) {
    for (int w = 0; w < DOM_WORDS; w++)
        if (a[w] & ~b[w])
            return 0;
    return 1;
}

static int dom_slot_grow(dom_slot_t *d) {
    int nc = d->mcap ? d->mcap * 2 : 4;
    /* one allocation: masks block then pc block */
    uint64_t *nm = (uint64_t *)malloc(
        (sizeof(uint64_t) * DOM_WORDS + 1) * (size_t)nc);
    if (!nm)
        return 0;
    uint8_t *npc = (uint8_t *)(nm + (size_t)nc * DOM_WORDS);
    if (d->n) {
        memcpy(nm, d->masks, sizeof(uint64_t) * DOM_WORDS * (size_t)d->n);
        memcpy(npc, d->pc, (size_t)d->n);
    }
    free(d->masks);
    d->masks = nm;
    d->pc = npc;
    d->mcap = nc;
    return 1;
}

static int dom_grow(domset_t *s);

/* Project a config onto the memo coordinates: win_key = window bits
 * with in-window READ bits removed; mvec = [read-complement, open
 * words].  romask[p] has bit j set when det row p+j is state-neutral
 * (a register read); NULL disables the read-collapse (non-register
 * models). */
static inline void dom_project(const cfg_t *c, const uint64_t *romask,
                               int32_t nD, int32_t W,
                               uint64_t *win_key, uint64_t *m) {
    uint64_t ro = 0;
    if (romask && c->p < nD) {
        int32_t wl = nD - c->p;
        if (wl > W)
            wl = W;
        uint64_t lim = (wl >= 64) ? ~0ULL : ((1ULL << wl) - 1);
        ro = romask[c->p] & lim;
    }
    *win_key = c->win & ~ro;
    m[0] = ro & ~c->win;
    for (int w = 0; w < NO_WORDS; w++)
        m[1 + w] = c->open[w];
}

/* 1 = inserted (explore), 0 = dominated (prune), -1 = OOM */
static int dom_insert(domset_t *s, int32_t p, uint64_t win_key,
                      const int32_t *st, const uint64_t *mvec) {
    if (s->count * 4 >= s->cap * 3) {
        if (!dom_grow(s))
            return -1;
    }
    uint64_t h = dom_key_hash(p, win_key, st);
    size_t i = (size_t)(h & (s->cap - 1));
    dom_slot_t *d = NULL;
    while (s->used[i]) {
        d = &s->slots[i];
        if (d->p == p && d->win == win_key &&
            memcmp(d->st, st, sizeof(d->st)) == 0)
            break;
        d = NULL;
        i = (i + 1) & (s->cap - 1);
    }
    int pc_new = dom_popcount(mvec);
    if (d == NULL) {
        /* fresh key */
        s->used[i] = 1;
        d = &s->slots[i];
        d->p = p;
        d->win = win_key;
        memcpy(d->st, st, sizeof(d->st));
        d->n = 0;
        d->mcap = 0;
        d->masks = NULL;
        d->pc = NULL;
        if (!dom_slot_grow(d))
            return -1;
        memcpy(d->masks, mvec, sizeof(uint64_t) * DOM_WORDS);
        d->pc[0] = (uint8_t)pc_new;
        d->n = 1;
        s->count++;
        return 1;
    }
    /* popcount-sorted scan: only masks with pc <= pc_new can be
     * subsets of the new mask */
    int32_t k = 0;
    for (; k < d->n && d->pc[k] <= pc_new; k++)
        if (dom_subset(d->masks + (size_t)k * DOM_WORDS, mvec))
            return 0; /* dominated */
    /* remove stored supersets (they are now redundant pruners) */
    int32_t w = k;
    for (int32_t j = k; j < d->n; j++) {
        if (dom_subset(mvec, d->masks + (size_t)j * DOM_WORDS))
            continue; /* superset of new: drop */
        if (w != j) {
            memcpy(d->masks + (size_t)w * DOM_WORDS,
                   d->masks + (size_t)j * DOM_WORDS,
                   sizeof(uint64_t) * DOM_WORDS);
            d->pc[w] = d->pc[j];
        }
        w++;
    }
    d->n = w;
    if (d->n == d->mcap && !dom_slot_grow(d))
        return -1;
    /* insert at position k (popcount order preserved) */
    memmove(d->masks + (size_t)(k + 1) * DOM_WORDS,
            d->masks + (size_t)k * DOM_WORDS,
            sizeof(uint64_t) * DOM_WORDS * (size_t)(d->n - k));
    memmove(d->pc + k + 1, d->pc + k, (size_t)(d->n - k));
    memcpy(d->masks + (size_t)k * DOM_WORDS, mvec,
           sizeof(uint64_t) * DOM_WORDS);
    d->pc[k] = (uint8_t)pc_new;
    d->n++;
    return 1;
}

static int dom_grow(domset_t *s) {
    domset_t bigger;
    if (!dom_init(&bigger, s->cap * 2))
        return 0;
    for (size_t i = 0; i < s->cap; i++) {
        if (!s->used[i])
            continue;
        dom_slot_t *d = &s->slots[i];
        uint64_t h = dom_key_hash(d->p, d->win, d->st);
        size_t j = (size_t)(h & (bigger.cap - 1));
        while (bigger.used[j])
            j = (j + 1) & (bigger.cap - 1);
        bigger.used[j] = 1;
        bigger.slots[j] = *d; /* masks pointer moves with the slot */
        bigger.count++;
    }
    free(s->slots);
    free(s->used);
    *s = bigger;
    return 1;
}

/* ------------------------------------------------------------------ */
/* The search.                                                         */

typedef struct {
    cfg_t *items;
    size_t len, cap;
} vec_t;

static int vec_push(vec_t *v, const cfg_t *c) {
    if (v->len == v->cap) {
        size_t nc = v->cap ? v->cap * 2 : 1024;
        cfg_t *ni = (cfg_t *)realloc(v->items, sizeof(cfg_t) * nc);
        if (!ni)
            return 0;
        v->items = ni;
        v->cap = nc;
    }
    v->items[v->len++] = *c;
    return 1;
}

/* ------------------------------------------------------------------ */
/* Shared expansion logic: candidate bounds and the per-slot transition
 * filter, used identically by the sequential DFS, the parallel DFS's
 * seeding sweep, and its workers (one copy — the three loops cannot
 * drift). */

/* Twin tables for the interval-containment symmetry reduction.
 *
 * Two ops with the same (op, a1, a2) have identical step behavior, so
 * they are interchangeable wherever both are applicable.  If i's
 * realtime interval is CONTAINED in j's (inv_i >= inv_j and
 * ret_i <= ret_j), any completion that linearizes j "now" and i at a
 * later point t can be rewritten with the two swapped: j at t is legal
 * because inv_j <= inv_i < min_ret_t, and every intermediate filter
 * only LOOSENS (the pending set trades i for j, and ret_i <= ret_j
 * can only raise the min-return bound).  So a search that, at each
 * config, skips candidate j whenever a contained same-class twin i is
 * itself applicable is still complete — it explores the innermost
 * applicable twin first and the rest never need to be branched on.
 * Open (:info) ops have ret = +inf, which makes every later-invoked
 * same-class open a contained twin, and every same-class determinate
 * op invoked after the open one too (det ops prune opens; opens never
 * prune dets).  This collapses the 2^k applied-subset blowup of
 * crashed ops around a refutation's stuck point. */
typedef struct {
    int32_t n_cls;
    int32_t *clsD;      /* [nD] class id per det row */
    int32_t *cposD;     /* [nD] row's position inside its class list */
    int32_t *crows_off; /* [n_cls+1] CSR offsets into crows */
    int32_t *crows;     /* det rows per class, ascending row (== inv) */
    int32_t *clsO;      /* [nO] class id per open op */
    int32_t *cposO;     /* [nO] open's position inside its class list */
    int32_t *copen_off; /* [n_cls+1] CSR offsets into copens */
    int32_t *copens;    /* open idxs per class, ascending idx (== inv) */
    int32_t *odet_start;/* [nO] first index in the open's class crows
                           with invD >= invO[o] */
} twins_t;

typedef struct {
    int32_t nD, nO, S, W;
    const int32_t *invD, *retD, *opD, *a1D, *a2D, *sufret;
    const int32_t *invO, *opO, *a1O, *a2O;
    int32_t model_id;
    int64_t model_param;
    const twins_t *tw; /* NULL = reduction disabled */
} tabs_t;

typedef struct {
    int32_t op, a1, a2, kind, idx; /* kind: 0 det, 1 open */
} tkey_t;

static int tkey_cmp(const void *pa, const void *pb) {
    const tkey_t *a = (const tkey_t *)pa, *b = (const tkey_t *)pb;
    if (a->op != b->op) return a->op < b->op ? -1 : 1;
    if (a->a1 != b->a1) return a->a1 < b->a1 ? -1 : 1;
    if (a->a2 != b->a2) return a->a2 < b->a2 ? -1 : 1;
    if (a->kind != b->kind) return a->kind - b->kind;
    return a->idx < b->idx ? -1 : (a->idx > b->idx);
}

static void twin_free(twins_t *X) {
    if (!X)
        return;
    free(X->clsD);
    free(X->cposD);
    free(X->crows_off);
    free(X->crows);
    free(X->clsO);
    free(X->cposO);
    free(X->copen_off);
    free(X->copens);
    free(X->odet_start);
    free(X);
}

/* Build the class tables; NULL on OOM or when the inv arrays are not
 * ascending (the encoders sort by invocation — verified here so the
 * reduction silently disables rather than mis-pruning if that ever
 * changes). */
static twins_t *twin_build(int32_t nD, int32_t nO,
                           const int32_t *opD, const int32_t *a1D,
                           const int32_t *a2D, const int32_t *invD,
                           const int32_t *opO, const int32_t *a1O,
                           const int32_t *a2O, const int32_t *invO) {
    for (int32_t i = 1; i < nD; i++)
        if (invD[i] < invD[i - 1])
            return NULL;
    for (int32_t i = 1; i < nO; i++)
        if (invO[i] < invO[i - 1])
            return NULL;
    size_t n = (size_t)nD + (size_t)nO;
    tkey_t *keys = (tkey_t *)malloc(sizeof(tkey_t) * (n ? n : 1));
    twins_t *X = (twins_t *)calloc(1, sizeof(twins_t));
    if (!keys || !X) {
        free(keys);
        free(X);
        return NULL;
    }
    for (int32_t i = 0; i < nD; i++)
        keys[i] = (tkey_t){opD[i], a1D[i], a2D[i], 0, i};
    for (int32_t i = 0; i < nO; i++)
        keys[nD + i] = (tkey_t){opO[i], a1O[i], a2O[i], 1, i};
    qsort(keys, n, sizeof(tkey_t), tkey_cmp);
    int32_t n_cls = 0;
    for (size_t i = 0; i < n; i++)
        if (i == 0 || keys[i].op != keys[i - 1].op ||
            keys[i].a1 != keys[i - 1].a1 || keys[i].a2 != keys[i - 1].a2)
            n_cls++;
    X->n_cls = n_cls;
    X->clsD = (int32_t *)malloc(sizeof(int32_t) * (nD ? nD : 1));
    X->cposD = (int32_t *)malloc(sizeof(int32_t) * (nD ? nD : 1));
    X->crows_off = (int32_t *)calloc((size_t)n_cls + 1, sizeof(int32_t));
    X->crows = (int32_t *)malloc(sizeof(int32_t) * (nD ? nD : 1));
    X->clsO = (int32_t *)malloc(sizeof(int32_t) * (nO ? nO : 1));
    X->cposO = (int32_t *)malloc(sizeof(int32_t) * (nO ? nO : 1));
    X->copen_off = (int32_t *)calloc((size_t)n_cls + 1, sizeof(int32_t));
    X->copens = (int32_t *)malloc(sizeof(int32_t) * (nO ? nO : 1));
    X->odet_start = (int32_t *)malloc(sizeof(int32_t) * (nO ? nO : 1));
    if (!X->clsD || !X->cposD || !X->crows_off || !X->crows || !X->clsO ||
        !X->cposO || !X->copen_off || !X->copens || !X->odet_start) {
        free(keys);
        twin_free(X);
        return NULL;
    }
    /* qsort's (op,a1,a2,kind,idx) total order yields ascending idx per
     * (class, kind) run — class member lists stay inv-sorted. */
    int32_t cls = -1, nd = 0, no = 0;
    for (size_t i = 0; i < n; i++) {
        if (i == 0 || keys[i].op != keys[i - 1].op ||
            keys[i].a1 != keys[i - 1].a1 || keys[i].a2 != keys[i - 1].a2)
            cls++;
        if (keys[i].kind == 0) {
            X->clsD[keys[i].idx] = cls;
            X->crows[nd++] = keys[i].idx;
            X->crows_off[cls + 1] = nd;
        } else {
            X->clsO[keys[i].idx] = cls;
            X->copens[no++] = keys[i].idx;
            X->copen_off[cls + 1] = no;
        }
    }
    /* fill gaps: classes with no det (or open) members inherit the
     * previous end so off[c]..off[c+1] is an empty range */
    for (int32_t c2 = 1; c2 <= n_cls; c2++) {
        if (X->crows_off[c2] < X->crows_off[c2 - 1])
            X->crows_off[c2] = X->crows_off[c2 - 1];
        if (X->copen_off[c2] < X->copen_off[c2 - 1])
            X->copen_off[c2] = X->copen_off[c2 - 1];
    }
    for (int32_t c2 = 0; c2 < n_cls; c2++) {
        for (int32_t q = X->crows_off[c2]; q < X->crows_off[c2 + 1]; q++)
            X->cposD[X->crows[q]] = q;
        for (int32_t q = X->copen_off[c2]; q < X->copen_off[c2 + 1]; q++)
            X->cposO[X->copens[q]] = q;
    }
    for (int32_t o = 0; o < nO; o++) {
        int32_t c2 = X->clsO[o];
        int32_t lo = X->crows_off[c2], hi = X->crows_off[c2 + 1];
        while (lo < hi) {
            int32_t mid = (lo + hi) >> 1;
            if (invD[X->crows[mid]] < invO[o])
                lo = mid + 1;
            else
                hi = mid;
        }
        X->odet_start[o] = lo;
    }
    free(keys);
    return X;
}

static inline void cfg_bounds(const tabs_t *T, const cfg_t *c,
                              int32_t *wlim_out, int32_t *min_ret_out,
                              int32_t *n_cand_out) {
    int32_t wlim = (T->nD - c->p < T->W) ? T->nD - c->p : T->W;
    int32_t min_ret =
        T->sufret[(c->p + T->W < T->nD) ? c->p + T->W : T->nD];
    for (int j = 0; j < wlim; j++)
        if (!((c->win >> j) & 1) && T->retD[c->p + j] < min_ret)
            min_ret = T->retD[c->p + j];
    /* invD ascends with row, and inv < ret makes the ret==min_ret
     * escape impossible once invD >= min_ret — so the candidate scan
     * can stop at the first too-late row (typ. 1/3 of the window).
     * Same for the invO-ascending open ops. */
    int32_t we = 0;
    while (we < wlim && T->invD[c->p + we] < min_ret)
        we++;
    int32_t ol = 0;
    while (ol < T->nO && T->invO[ol] < min_ret)
        ol++;
    *wlim_out = we;
    *min_ret_out = min_ret;
    *n_cand_out = we + ol;
}

/* Try candidate slot j (0..wlim-1 window ops, wlim..wlim+nO-1 open
 * ops). 0 = filtered, 1 = successor written to *out, 2 = the history
 * completed (accepting linearization found). */
static inline int cfg_try(const tabs_t *T, const cfg_t *c, int32_t wlim,
                          int32_t min_ret, int32_t j, cfg_t *out) {
    cfg_t c2 = *c;
    const twins_t *X = T->tw;
    if (j < wlim) {
        if ((c->win >> j) & 1)
            return 0;
        int32_t row = c->p + j;
        if (T->invD[row] >= min_ret && T->retD[row] != min_ret)
            return 0;
        if (X) {
            /* twin pruning: a later-invoked same-class det op whose
             * return is no later (contained interval) and which is
             * itself applicable makes this branch redundant */
            int32_t end = X->crows_off[X->clsD[row] + 1];
            for (int32_t q = X->cposD[row] + 1; q < end; q++) {
                int32_t r2 = X->crows[q];
                if (r2 - c->p >= wlim)
                    break; /* rows ascend: the rest are out of window */
                if (T->invD[r2] >= min_ret)
                    break; /* rows ascend in inv: the rest fail too */
                if (((c->win >> (r2 - c->p)) & 1))
                    continue; /* already linearized */
                if (T->retD[r2] <= T->retD[row])
                    return 0; /* contained applicable twin exists */
            }
        }
        if (!step_model(T->model_id, T->model_param, c->st, T->opD[row],
                        T->a1D[row], T->a2D[row], c2.st))
            return 0;
        c2.win = c->win | (1ULL << j);
        while (c2.win & 1) {
            c2.win >>= 1;
            c2.p++;
        }
        if (c2.p >= T->nD)
            return 2;
    } else {
        int o = j - wlim;
        if (open_test(c, o))
            return 0;
        if (T->invO[o] >= min_ret)
            return 0;
        if (T->model_id == MODEL_CAS_REGISTER && T->opO[o] == OP_READ)
            return 0; /* applying a state-neutral open changes nothing:
                         the parent config dominates the successor */
        if (X) {
            int32_t cls = X->clsO[o];
            /* later-invoked same-class opens: contained (ret = inf) */
            int32_t oend = X->copen_off[cls + 1];
            for (int32_t q = X->cposO[o] + 1; q < oend; q++) {
                int32_t o2 = X->copens[q];
                if (T->invO[o2] >= min_ret)
                    break; /* opens ascend in inv */
                if (!open_test(c, o2))
                    return 0;
            }
            /* determinate same-class ops invoked after this open: their
             * finite interval is contained in [invO, inf) */
            int32_t dend = X->crows_off[cls + 1];
            int32_t lo = X->odet_start[o], hi = dend;
            while (lo < hi) { /* first class row still in the window */
                int32_t mid = (lo + hi) >> 1;
                if (X->crows[mid] < c->p)
                    lo = mid + 1;
                else
                    hi = mid;
            }
            for (int32_t q = lo; q < dend; q++) {
                int32_t r2 = X->crows[q];
                if (r2 - c->p >= wlim)
                    break;
                if (T->invD[r2] >= min_ret)
                    break;
                if (!((c->win >> (r2 - c->p)) & 1))
                    return 0; /* applicable det twin exists */
            }
        }
        if (!step_model(T->model_id, T->model_param, c->st, T->opO[o],
                        T->a1O[o], T->a2O[o], c2.st))
            return 0;
        open_set_bit(&c2, o);
    }
    *out = c2;
    return 1;
}

static inline int32_t cfg_depth(const cfg_t *c) {
    int32_t d = c->p;
    uint64_t w = c->win;
    while (w) {
        d += (int32_t)(w & 1);
        w >>= 1;
    }
    return d;
}

/* ------------------------------------------------------------------ */
/* Depth-first search with memoization (Lowe / knossos-"linear" style):
 * follow one linearization, backtracking on dead ends; the memo set
 * guarantees each configuration is expanded at most once, so valid
 * histories are near-linear (real-time candidate order first) and
 * invalid ones terminate after covering the reachable space. */

typedef struct {
    cfg_t cfg;
    int32_t next_j; /* next candidate slot to try: 0..n_cand */
    int32_t min_ret;
    int32_t wlim;
    int32_t n_cand;
    /* eager-read cache: the successor computed by the first-visit scan
     * (avoids running cfg_try twice on the hot read path) */
    cfg_t eager;
    int32_t eager_j; /* -1 = none */
    int32_t eager_r;
} frame_t;

/* Per-row "state-neutral" mask for the read-collapse dominance: bit j
 * of romask[p] is set when det row p+j is a register READ.  NULL for
 * models with no state-neutral ops. */
static uint64_t *romask_build(int32_t nD, int32_t model_id,
                              const int32_t *opD) {
    if (model_id != MODEL_CAS_REGISTER)
        return NULL;
    uint64_t *ro = (uint64_t *)malloc(sizeof(uint64_t) * (nD ? nD : 1));
    if (!ro)
        return NULL;
    uint64_t acc = 0;
    for (int32_t p = nD - 1; p >= 0; p--) {
        acc = (acc << 1) | (uint64_t)(opD[p] == OP_READ);
        ro[p] = acc;
    }
    return ro;
}

/* Witness buffer entry stride, in int32 lanes:
 * [p, win_lo, win_hi, open x 2*NO_WORDS, st x S_MAX] */
int wgl_witness_stride(void) { return 3 + 2 * NO_WORDS + S_MAX; }

static void wit_record(int32_t *buf, int32_t cap, int32_t *len,
                       int32_t *depth_seen, int32_t d, const cfg_t *c) {
    if (!buf || cap <= 0)
        return;
    if (d > *depth_seen) {
        *depth_seen = d;
        *len = 0; /* deeper configs supersede shallower witnesses */
    } else if (d < *depth_seen || *len >= cap) {
        return;
    }
    int32_t *e = buf + (size_t)(*len) * (size_t)wgl_witness_stride();
    e[0] = c->p;
    e[1] = (int32_t)(uint32_t)(c->win & 0xFFFFFFFFULL);
    e[2] = (int32_t)(uint32_t)(c->win >> 32);
    for (int w = 0; w < NO_WORDS; w++) {
        e[3 + 2 * w] = (int32_t)(uint32_t)(c->open[w] & 0xFFFFFFFFULL);
        e[4 + 2 * w] = (int32_t)(uint32_t)(c->open[w] >> 32);
    }
    memcpy(e + 3 + 2 * NO_WORDS, c->st, sizeof(int32_t) * S_MAX);
    (*len)++;
}

int wgl_check_dfs(
    int32_t nD, int32_t nO, int32_t S, int32_t W,
    const int32_t *invD, const int32_t *retD, const int32_t *opD,
    const int32_t *a1D, const int32_t *a2D,
    const int32_t *sufret,
    const int32_t *invO, const int32_t *opO,
    const int32_t *a1O, const int32_t *a2O,
    const int32_t *init_state,
    int32_t model_id, int64_t model_param,
    int64_t max_configs,
    int64_t *configs_explored, int32_t *frontier_max,
    int32_t *max_linearized,
    /* optional deepest-config capture (the refutation witness the
     * reference renders as linear.svg, checker.clj:202-209): up to
     * wit_cap entries of wgl_witness_stride() lanes each; NULL/0 to
     * disable */
    int32_t *wit_buf, int32_t wit_cap, int32_t *wit_len,
    /* optional cooperative cancellation: when *cancel becomes nonzero
     * the search returns -1 (budget semantics) at the next poll — the
     * competition race uses this so a losing DFS stops promptly
     * instead of grinding to its full config budget. NULL = never. */
    const volatile int32_t *cancel) {
    if (W > 64 || nO > 64 * NO_WORDS || S > S_MAX)
        return -2;
    *configs_explored = 0;
    *frontier_max = 0;
    *max_linearized = 0;
    if (wit_len)
        *wit_len = 0;
    if (nD == 0)
        return 1;

    domset_t seen;
    if (!dom_init(&seen, 1 << 16))
        return -3;

    size_t depth_cap = (size_t)nD + (size_t)nO + 2;
    frame_t *stack = (frame_t *)malloc(sizeof(frame_t) * depth_cap);
    if (!stack) {
        dom_free(&seen);
        return -3;
    }
    size_t sp = 0;

    tabs_t T = {nD, nO, S, W, invD, retD, opD, a1D, a2D, sufret,
                invO, opO, a1O, a2O, model_id, model_param, NULL};
    twins_t *X = twin_build(nD, nO, opD, a1D, a2D, invD,
                            opO, a1O, a2O, invO);
    T.tw = X; /* NULL (OOM / unsorted inv) just disables the reduction */
    uint64_t *romask = romask_build(nD, model_id, opD);

    frame_t root;
    memset(&root, 0, sizeof(root));
    memcpy(root.cfg.st, init_state, sizeof(int32_t) * (size_t)S);
    root.next_j = -1; /* compute bounds lazily on first visit */
    stack[sp++] = root;
    {
        uint64_t wk, mv[DOM_WORDS];
        dom_project(&root.cfg, romask, nD, W, &wk, mv);
        dom_insert(&seen, root.cfg.p, wk, root.cfg.st, mv);
    }

    int64_t explored = 0;
    int verdict = 0;

    while (sp) {
        frame_t *fr = &stack[sp - 1];
        cfg_t *c = &fr->cfg;
        if (fr->next_j < 0) {
            /* first visit: compute window limit + min completion */
            explored++;
            if (explored > max_configs ||
                ((explored & 0x3FF) == 0 && cancel && *cancel)) {
                verdict = -1;
                break;
            }
            cfg_bounds(&T, c, &fr->wlim, &fr->min_ret, &fr->n_cand);
            fr->next_j = 0;
            fr->eager_j = -1;
            if (romask && fr->wlim > 0) {
                /* eager-read propagation: an applicable window READ can
                 * be moved to the front of any accepting completion
                 * (state-neutral; dropping it from the pending set only
                 * loosens min-return bounds), so this config has
                 * exactly ONE successor worth branching on. */
                for (int32_t j = 0; j < fr->wlim; j++) {
                    if (!((romask[c->p] >> j) & 1))
                        continue;
                    int r = cfg_try(&T, c, fr->wlim, fr->min_ret, j,
                                    &fr->eager);
                    if (r) {
                        fr->next_j = j;
                        fr->n_cand = j + 1;
                        fr->eager_j = j;
                        fr->eager_r = r;
                        break;
                    }
                }
            }
            {
                int32_t d = cfg_depth(c);
                wit_record(wit_buf, wit_cap, wit_len, max_linearized, d, c);
                if (d > *max_linearized)
                    *max_linearized = d;
            }
        }
        int advanced = 0;
        while (fr->next_j < fr->n_cand) {
            int j = fr->next_j++;
            cfg_t c2;
            int r;
            if (j == fr->eager_j) {
                c2 = fr->eager;
                r = fr->eager_r;
            } else {
                r = cfg_try(&T, c, fr->wlim, fr->min_ret, j, &c2);
            }
            if (r == 0)
                continue;
            if (r == 2) {
                verdict = 1;
                break;
            }
            uint64_t wk, mv[DOM_WORDS];
            dom_project(&c2, romask, nD, W, &wk, mv);
            int ins = dom_insert(&seen, c2.p, wk, c2.st, mv);
            if (ins < 0) {
                verdict = -3;
                break;
            }
            if (!ins)
                continue; /* dominated: an explored config with equal
                             (p, win, state) and open-subset covers
                             every future of this one */
            frame_t nf;
            nf.cfg = c2;
            nf.next_j = -1;
            nf.min_ret = 0;
            nf.wlim = 0;
            stack[sp++] = nf;
            advanced = 1;
            break;
        }
        if (verdict)
            break;
        if (!advanced)
            sp--; /* dead end: backtrack */
        if ((int32_t)sp > *frontier_max)
            *frontier_max = (int32_t)sp; /* stack depth as diagnostic */
    }

    *configs_explored = explored;
    free(stack);
    dom_free(&seen);
    twin_free(X);
    free(romask);
    return verdict;
}

/* ------------------------------------------------------------------ */
/* Parallel DFS: the same memoized search fanned over worker threads.
 *
 * Discovered-but-unexpanded configs live on ONE shared LIFO stack;
 * workers pop small batches off the top, expand them, and push
 * successors back in reverse candidate order (so the stack top is the
 * real-time-first candidate — the ordering that makes valid histories
 * near-linear in the sequential DFS). The dominance memo is ONE
 * logical set striped into PAR_STRIPES independently-growing hash
 * tables, each under its own mutex — a worker that finds a config
 * dominated can rely on whichever worker inserted the dominating
 * config to (have) explore(d) its whole subtree, exactly the
 * sequential argument. Refutation (verdict 0) is only claimed when the
 * stack empties with zero configs mid-expansion and no budget trip or
 * cancellation, so concurrent pruning can never manufacture a false
 * "invalid". Valid verdicts short-circuit all workers. */

#define PAR_STRIPES 128
#define PAR_POP_BATCH 16
/* successors of one config: <= W window + nO open candidates */
#define PAR_MAX_SUCC (64 + 64 * NO_WORDS)

typedef struct {
    tabs_t T;
    const uint64_t *romask; /* read-collapse mask, NULL for lock models */
    int64_t max_configs;
    const volatile int32_t *cancel;
    domset_t sets[PAR_STRIPES];
    pthread_mutex_t mus[PAR_STRIPES];
    /* shared work stack + in-flight accounting */
    pthread_mutex_t qmu;
    vec_t q;
    size_t q_peak;
    atomic_llong pending; /* configs on the stack or mid-expansion */
    atomic_llong explored;
    atomic_int decided; /* 0 running | 1 valid | -1 budget/cancel | -3 oom */
    /* deepest-config witness capture (shared; mutex-guarded) */
    pthread_mutex_t wit_mu;
    int32_t *wit_buf;
    int32_t wit_cap;
    int32_t *wit_len;
    int32_t maxlin_plain;
    atomic_int maxlin;
} par_t;

static int par_insert(par_t *P, const cfg_t *c) {
    uint64_t wk, mv[DOM_WORDS];
    dom_project(c, P->romask, P->T.nD, P->T.W, &wk, mv);
    uint64_t h = dom_key_hash(c->p, wk, c->st);
    int s = (int)(h >> 56) & (PAR_STRIPES - 1);
    pthread_mutex_lock(&P->mus[s]);
    int r = dom_insert(&P->sets[s], c->p, wk, c->st, mv);
    pthread_mutex_unlock(&P->mus[s]);
    return r;
}

static void par_witness(par_t *P, const cfg_t *c) {
    int32_t d = cfg_depth(c);
    int32_t ml = atomic_load_explicit(&P->maxlin, memory_order_relaxed);
    if (d < ml)
        return;
    if (d == ml && !P->wit_buf)
        return;
    pthread_mutex_lock(&P->wit_mu);
    wit_record(P->wit_buf, P->wit_cap, P->wit_len, &P->maxlin_plain, d, c);
    if (d > P->maxlin_plain)
        P->maxlin_plain = d;
    atomic_store_explicit(&P->maxlin, P->maxlin_plain,
                          memory_order_relaxed);
    pthread_mutex_unlock(&P->wit_mu);
}

/* Pop up to max_k configs off the top of the shared stack. */
static int par_pop(par_t *P, cfg_t *out, int max_k) {
    pthread_mutex_lock(&P->qmu);
    int k = (int)((P->q.len < (size_t)max_k) ? P->q.len : (size_t)max_k);
    for (int i = 0; i < k; i++)
        out[i] = P->q.items[--P->q.len];
    pthread_mutex_unlock(&P->qmu);
    return k;
}

/* Push k configs; 0 on OOM. */
static int par_push(par_t *P, const cfg_t *cs, int k) {
    pthread_mutex_lock(&P->qmu);
    for (int i = 0; i < k; i++) {
        if (!vec_push(&P->q, &cs[i])) {
            pthread_mutex_unlock(&P->qmu);
            return 0;
        }
    }
    if (P->q.len > P->q_peak)
        P->q_peak = P->q.len;
    pthread_mutex_unlock(&P->qmu);
    return 1;
}

static void *par_worker(void *arg) {
    par_t *P = (par_t *)arg;
    const tabs_t *T = &P->T;
    cfg_t *batch = (cfg_t *)malloc(sizeof(cfg_t) * PAR_POP_BATCH);
    cfg_t *succ = (cfg_t *)malloc(sizeof(cfg_t) * PAR_MAX_SUCC);
    if (!batch || !succ) {
        free(batch);
        free(succ);
        atomic_store(&P->decided, -3);
        return NULL;
    }
    int64_t local = 0, flushed = 0;
    while (!atomic_load_explicit(&P->decided, memory_order_relaxed)) {
        int k = par_pop(P, batch, PAR_POP_BATCH);
        if (k == 0) {
            if (atomic_load_explicit(&P->pending, memory_order_acquire)
                    == 0)
                break; /* nothing queued, nothing mid-expansion: done */
            struct timespec ts = {0, 50000}; /* 50 us */
            nanosleep(&ts, NULL);
            continue;
        }
        for (int bi = 0; bi < k; bi++) {
            if (atomic_load_explicit(&P->decided, memory_order_relaxed))
                break; /* decided != 0: refutation is off the table, so
                          the un-decremented pending is harmless */
            cfg_t *c = &batch[bi];
            local++;
            if ((local & 0x3FF) == 0) {
                atomic_fetch_add(&P->explored, local - flushed);
                flushed = local;
                if (atomic_load_explicit(&P->explored,
                                         memory_order_relaxed)
                        > P->max_configs ||
                    (P->cancel && *P->cancel)) {
                    atomic_store(&P->decided, -1);
                    break;
                }
            }
            int32_t wlim, min_ret, n_cand;
            cfg_bounds(T, c, &wlim, &min_ret, &n_cand);
            par_witness(P, c);
            int j0 = 0;
            cfg_t eager;
            int32_t eager_j = -1, eager_r = 0;
            if (P->romask && wlim > 0) {
                /* eager-read propagation (see the sequential DFS) */
                for (int32_t j = 0; j < wlim; j++) {
                    if (!((P->romask[c->p] >> j) & 1))
                        continue;
                    int r = cfg_try(T, c, wlim, min_ret, j, &eager);
                    if (r) {
                        j0 = j;
                        n_cand = j + 1;
                        eager_j = j;
                        eager_r = r;
                        break;
                    }
                }
            }
            int ns = 0;
            for (int j = j0; j < n_cand; j++) {
                cfg_t c2;
                int r;
                if (j == eager_j) {
                    c2 = eager;
                    r = eager_r;
                } else {
                    r = cfg_try(T, c, wlim, min_ret, j, &c2);
                }
                if (r == 0)
                    continue;
                if (r == 2) {
                    atomic_store(&P->decided, 1);
                    break;
                }
                int ins = par_insert(P, &c2);
                if (ins < 0) {
                    atomic_store(&P->decided, -3);
                    break;
                }
                if (ins)
                    succ[ns++] = c2;
            }
            if (atomic_load_explicit(&P->decided, memory_order_relaxed))
                break;
            if (ns) {
                /* reverse so the stack top is the lowest-j candidate
                 * (the real-time-first descent order) */
                for (int a = 0, b = ns - 1; a < b; a++, b--) {
                    cfg_t tmp = succ[a];
                    succ[a] = succ[b];
                    succ[b] = tmp;
                }
                atomic_fetch_add_explicit(&P->pending, ns,
                                          memory_order_release);
                if (!par_push(P, succ, ns)) {
                    atomic_store(&P->decided, -3);
                    break;
                }
            }
            atomic_fetch_sub_explicit(&P->pending, 1,
                                      memory_order_release);
        }
    }
    atomic_fetch_add(&P->explored, local - flushed);
    free(batch);
    free(succ);
    return NULL;
}

int wgl_check_dfs_par(
    int32_t nD, int32_t nO, int32_t S, int32_t W,
    const int32_t *invD, const int32_t *retD, const int32_t *opD,
    const int32_t *a1D, const int32_t *a2D,
    const int32_t *sufret,
    const int32_t *invO, const int32_t *opO,
    const int32_t *a1O, const int32_t *a2O,
    const int32_t *init_state,
    int32_t model_id, int64_t model_param,
    int64_t max_configs,
    int64_t *configs_explored, int32_t *frontier_max,
    int32_t *max_linearized,
    int32_t *wit_buf, int32_t wit_cap, int32_t *wit_len,
    const volatile int32_t *cancel,
    int32_t n_threads) {
    if (W > 64 || nO > 64 * NO_WORDS || S > S_MAX)
        return -2;
    *configs_explored = 0;
    *frontier_max = 0;
    *max_linearized = 0;
    if (wit_len)
        *wit_len = 0;
    if (nD == 0)
        return 1;
    if (n_threads < 1)
        n_threads = 1;
    if (n_threads > 64)
        n_threads = 64;

    par_t *P = (par_t *)calloc(1, sizeof(par_t));
    if (!P)
        return -3;
    tabs_t T = {nD, nO, S, W, invD, retD, opD, a1D, a2D, sufret,
                invO, opO, a1O, a2O, model_id, model_param, NULL};
    twins_t *Xp = twin_build(nD, nO, opD, a1D, a2D, invD,
                             opO, a1O, a2O, invO);
    T.tw = Xp;
    P->T = T;
    P->romask = romask_build(nD, model_id, opD);
    P->max_configs = max_configs;
    P->cancel = cancel;
    P->wit_buf = wit_buf;
    P->wit_cap = wit_cap;
    P->wit_len = wit_len;
    atomic_init(&P->pending, 0);
    atomic_init(&P->explored, 0);
    atomic_init(&P->decided, 0);
    atomic_init(&P->maxlin, 0);
    pthread_mutex_init(&P->wit_mu, NULL);
    pthread_mutex_init(&P->qmu, NULL);
    for (int i = 0; i < PAR_STRIPES; i++) {
        pthread_mutex_init(&P->mus[i], NULL);
        if (!dom_init(&P->sets[i], 1 << 8)) {
            for (int j = 0; j < i; j++)
                dom_free(&P->sets[j]);
            free(P);
            return -3;
        }
    }

    int verdict;
    {
        cfg_t root_cfg;
        memset(&root_cfg, 0, sizeof(root_cfg));
        memcpy(root_cfg.st, init_state, sizeof(int32_t) * (size_t)S);
        par_insert(P, &root_cfg);
        atomic_store(&P->pending, 1);
        if (!par_push(P, &root_cfg, 1)) {
            verdict = -3;
            goto out;
        }
    }

    {
        pthread_t tids[64];
        int started = 0;
        for (int i = 0; i < n_threads; i++) {
            if (pthread_create(&tids[i], NULL, par_worker, P) != 0)
                break;
            started++;
        }
        if (started == 0)
            atomic_store(&P->decided, -3);
        for (int i = 0; i < started; i++)
            pthread_join(tids[i], NULL);
        verdict = atomic_load(&P->decided); /* 0 = space exhausted */
    }

out:
    *configs_explored = atomic_load(&P->explored);
    *max_linearized = atomic_load(&P->maxlin);
    /* diagnostic: deepest the shared work stack ever got */
    *frontier_max = (int32_t)(P->q_peak > 0x7FFFFFFF
                                  ? 0x7FFFFFFF : P->q_peak);
    twin_free(Xp);
    free((void *)P->romask);
    free(P->q.items);
    for (int i = 0; i < PAR_STRIPES; i++) {
        dom_free(&P->sets[i]);
        pthread_mutex_destroy(&P->mus[i]);
    }
    pthread_mutex_destroy(&P->wit_mu);
    pthread_mutex_destroy(&P->qmu);
    free(P);
    return verdict;
}

int wgl_check(
    int32_t nD, int32_t nO, int32_t S, int32_t W,
    const int32_t *invD, const int32_t *retD, const int32_t *opD,
    const int32_t *a1D, const int32_t *a2D,
    const int32_t *sufret, /* [nD+1] suffix min of retD */
    const int32_t *invO, const int32_t *opO,
    const int32_t *a1O, const int32_t *a2O,
    const int32_t *init_state,
    int32_t model_id, int64_t model_param,
    int64_t max_configs,
    /* out */ int64_t *configs_explored, int32_t *frontier_max,
    int32_t *max_linearized) {
    if (W > 64 || nO > 64 * NO_WORDS || S > S_MAX)
        return -2;

    *configs_explored = 0;
    *frontier_max = 1;
    *max_linearized = 0;

    cfg_t start;
    memset(&start, 0, sizeof(start));
    memcpy(start.st, init_state, sizeof(int32_t) * (size_t)S);

    if (nD == 0)
        return 1; /* empty required set: trivially accepted */

    vec_t cur = {0}, nxt = {0};
    set_t seen;
    if (!set_init(&seen, 1 << 12))
        return -3;
    if (!vec_push(&cur, &start)) {
        set_free(&seen);
        return -3;
    }
    set_insert(&seen, &start, S);

    int verdict = 0;
    int64_t explored = 0;
    int lvl = 0;

    while (cur.len) {
        nxt.len = 0;
        int progressed = 0;
        for (size_t ci = 0; ci < cur.len && !verdict; ci++) {
            cfg_t *c = &cur.items[ci];
            explored++;
            if (explored > max_configs) {
                verdict = -1;
                break;
            }
            /* min completion among unlinearized determinate ops */
            int32_t tail = sufret[(c->p + W < nD) ? c->p + W : nD];
            int32_t min_ret = tail;
            int wlim = (nD - c->p < W) ? nD - c->p : W;
            for (int j = 0; j < wlim; j++) {
                if (!((c->win >> j) & 1) && retD[c->p + j] < min_ret)
                    min_ret = retD[c->p + j];
            }
            /* determinate candidates */
            for (int j = 0; j < wlim; j++) {
                if ((c->win >> j) & 1)
                    continue;
                int32_t row = c->p + j;
                /* allowed iff inv < min_ret, or own ret IS the min
                 * (event ranks are unique; inv[j] < ret[j] always) */
                if (invD[row] >= min_ret && retD[row] != min_ret)
                    continue;
                cfg_t c2 = *c;
                if (!step_model(model_id, model_param, c->st, opD[row],
                                a1D[row], a2D[row], c2.st))
                    continue;
                c2.win = c->win | (1ULL << j);
                /* renormalize prefix over trailing ones */
                while (c2.win & 1) {
                    c2.win >>= 1;
                    c2.p++;
                }
                if (c2.p >= nD) {
                    verdict = 1;
                    break;
                }
                int ins = set_insert(&seen, &c2, S);
                if (ins < 0) {
                    verdict = -3;
                    break;
                }
                if (ins && !vec_push(&nxt, &c2)) {
                    verdict = -3;
                    break;
                }
                if (ins)
                    progressed = 1;
            }
            if (verdict)
                break;
            /* open-op candidates */
            for (int o = 0; o < nO; o++) {
                if (open_test(c, o))
                    continue;
                if (invO[o] >= min_ret)
                    continue;
                cfg_t c2 = *c;
                if (!step_model(model_id, model_param, c->st, opO[o],
                                a1O[o], a2O[o], c2.st))
                    continue;
                open_set_bit(&c2, o);
                int ins = set_insert(&seen, &c2, S);
                if (ins < 0) {
                    verdict = -3;
                    break;
                }
                if (ins && !vec_push(&nxt, &c2)) {
                    verdict = -3;
                    break;
                }
                if (ins)
                    progressed = 1;
            }
        }
        if (verdict)
            break;
        if (progressed)
            lvl++;
        nxt.len = dominance_prune(nxt.items, nxt.len, S);
        if ((int32_t)nxt.len > *frontier_max)
            *frontier_max = (int32_t)nxt.len;
        /* swap */
        vec_t tmp = cur;
        cur = nxt;
        nxt = tmp;
        if (cur.len) {
            /* deepest prefix reached (diagnostic) */
            int32_t best = 0;
            for (size_t i = 0; i < cur.len; i++) {
                int32_t d = cur.items[i].p;
                uint64_t w = cur.items[i].win;
                while (w) {
                    d += (int32_t)(w & 1);
                    w >>= 1;
                }
                if (d > best)
                    best = d;
            }
            if (best > *max_linearized)
                *max_linearized = best;
        }
    }

    *configs_explored = explored;
    free(cur.items);
    free(nxt.items);
    set_free(&seen);
    return verdict;
}
