"""Native (C) runtime components.

The reference's heavy compute lives in native-adjacent runtimes (knossos
on the JVM with 32 GB heaps, C clock tools, C++ CharybdeFS). This package
holds the C equivalents compiled on demand with the system compiler:

- ``wgl_native.c`` — the host-side WGL linearizability search (the third
  implementation alongside the python oracle and the XLA device kernel,
  differentially tested against both; used as the fast host fallback).

Build: ``cc -O2 -shared -fPIC`` into ``~/.cache/jepsen_tpu_native/``,
keyed by a hash of the source, loaded via ctypes. No toolchain → the
callers fall back to the pure-python paths.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
from pathlib import Path
from typing import Optional

LOG = logging.getLogger("jepsen.native")

_SRC = Path(__file__).resolve().parent / "wgl_native.c"
_lib = None
_lib_tried = False


def _compile(src_path: Path, stem: str, extra_args=()) -> Optional[Path]:
    """Compile a C source into the shared cache (content-hashed name,
    tmp-then-rename so concurrent builds can't serve a half-written .so);
    returns the .so path or None when the toolchain is missing."""
    src = src_path.read_text()
    digest = hashlib.sha256(src.encode()).hexdigest()[:16]
    cache = Path(os.path.expanduser("~")) / ".cache" / "jepsen_tpu_native"
    cache.mkdir(parents=True, exist_ok=True)
    so = cache / f"{stem}-{digest}.so"
    if not so.exists():
        tmp = so.with_suffix(f".{os.getpid()}.tmp")
        cmd = ["cc", "-O2", "-shared", "-fPIC", *extra_args,
               "-o", str(tmp), str(src_path)]
        proc = subprocess.run(cmd, capture_output=True)
        if proc.returncode != 0:
            LOG.warning("native build of %s failed: %s", stem,
                        proc.stderr.decode(errors="replace"))
            return None
        tmp.replace(so)
    return so


def _build() -> Optional[ctypes.CDLL]:
    so = _compile(_SRC, "wgl_native", ("-pthread",))
    if so is None:
        return None
    lib = ctypes.CDLL(str(so))
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.wgl_check.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        i32p, i32p, i32p, i32p, i32p,  # det tables
        i32p,  # sufret
        i32p, i32p, i32p, i32p,  # open tables
        i32p,  # init state
        ctypes.c_int32, ctypes.c_int64,  # model id, param
        ctypes.c_int64,  # max configs
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.wgl_check.restype = ctypes.c_int
    # The DFS additionally captures the deepest configs reached (the
    # refutation witness): wit_buf, wit_cap (entries), wit_len out —
    # plus an optional cooperative-cancel flag (competition mode).
    lib.wgl_check_dfs.argtypes = lib.wgl_check.argtypes + [
        i32p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.wgl_check_dfs.restype = ctypes.c_int
    # The parallel DFS: same signature plus a thread count.
    lib.wgl_check_dfs_par.argtypes = lib.wgl_check_dfs.argtypes + [
        ctypes.c_int32,
    ]
    lib.wgl_check_dfs_par.restype = ctypes.c_int
    lib.wgl_witness_stride.argtypes = []
    lib.wgl_witness_stride.restype = ctypes.c_int
    lib.wgl_max_open.argtypes = []
    lib.wgl_max_open.restype = ctypes.c_int
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The compiled library, building it on first use; None when no
    compiler is available."""
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        try:
            _lib = _build()
        except Exception:
            LOG.warning("native build errored", exc_info=True)
            _lib = None
    return _lib


# ---------------------------------------------------------------------------
# edn_fast: the CPython-extension EDN reader (native data loader)

_edn_mod = None
_edn_tried = False


def load_edn_fast():
    """Build (once) + import the edn_fast extension; None when no
    toolchain/headers. Callers fall back to the pure-python reader."""
    global _edn_mod, _edn_tried
    if _edn_tried:
        return _edn_mod
    _edn_tried = True
    import importlib.util
    import sysconfig

    src_path = Path(__file__).resolve().parent / "edn_fast.c"
    try:
        inc = sysconfig.get_paths()["include"]
        so = _compile(src_path, "edn_fast", (f"-I{inc}",))
        if so is None:
            return None
        spec = importlib.util.spec_from_file_location("edn_fast", str(so))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        from .. import edn as _edn

        mod.configure(_edn.K, _edn.Symbol, _edn.EdnList, _edn._hashable)
        _edn_mod = mod
        return mod
    except Exception:  # pragma: no cover - defensive: always have a reader
        LOG.warning("edn_fast unavailable", exc_info=True)
        return None
