/* edn_fast: a CPython-extension EDN reader — the framework's native
 * data loader.
 *
 * The replay/analyze seams parse many multi-megabyte history.edn files
 * (store.clj:351-362 format: newline-separated op maps); the pure-python
 * reader runs at ~2 MB/s, which makes the parse — not the TPU decision —
 * the batch-replay bottleneck. This recursive-descent reader builds
 * Python objects directly via the C API at tens of MB/s.
 *
 * It covers the grammar history/results files actually use (nil, bools,
 * 64-bit ints, floats, strings, keywords, symbols, lists, vectors, maps,
 * sets, comments). Anything richer — tagged literals, char literals,
 * ratios, bignums — raises FastParseError and the Python wrapper falls
 * back to the full reader (jepsen_tpu/edn.py), so behavior is always
 * THAT reader's; this is purely an accelerator.
 *
 * Object mapping is configured from Python (edn_fast.configure) so the
 * two readers produce identical object graphs: keywords/symbols/EdnList
 * come from jepsen_tpu.edn, unhashable map keys go through the same
 * _hashable coercion.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

static PyObject *FastParseError;
static PyObject *kw_fn;        /* name -> Keyword (interned) */
static PyObject *sym_fn;       /* name -> Symbol */
static PyObject *ednlist_cls;  /* tuple -> EdnList */
static PyObject *hashable_fn;  /* form -> hashable form */

typedef struct {
    const char *s;
    Py_ssize_t i, n;
    int depth;
} P;

static PyObject *parse_form(P *p);

static void skip_ws(P *p) {
    while (p->i < p->n) {
        char c = p->s[p->i];
        if (c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == ',') {
            p->i++;
        } else if (c == ';') {
            while (p->i < p->n && p->s[p->i] != '\n') p->i++;
        } else {
            break;
        }
    }
}

static int is_delim(char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == ',' ||
           c == '(' || c == ')' || c == '[' || c == ']' || c == '{' ||
           c == '}' || c == '"' || c == ';' || c == '\0';
}

static PyObject *err(P *p, const char *msg) {
    PyErr_Format(FastParseError, "%s at offset %zd", msg, p->i);
    return NULL;
}

/* ---- scalars ---------------------------------------------------------- */

static PyObject *parse_string(P *p) {
    /* p->s[p->i] == '"' */
    Py_ssize_t start = ++p->i;
    /* fast path: no escapes */
    Py_ssize_t j = start;
    while (j < p->n && p->s[j] != '"' && p->s[j] != '\\') j++;
    if (j >= p->n) return err(p, "unterminated string");
    if (p->s[j] == '"') {
        PyObject *o = PyUnicode_DecodeUTF8(p->s + start, j - start, NULL);
        p->i = j + 1;
        return o;
    }
    /* slow path with escapes: build into a scratch buffer */
    Py_ssize_t cap = 64, len = 0;
    char *buf = PyMem_Malloc(cap);
    if (!buf) return PyErr_NoMemory();
    Py_ssize_t k = start;
    while (k < p->n && p->s[k] != '"') {
        char c = p->s[k];
        char out[4];
        int outn = 1;
        if (c == '\\') {
            if (++k >= p->n) { PyMem_Free(buf); return err(p, "bad escape"); }
            char e = p->s[k];
            switch (e) {
            case 'n': out[0] = '\n'; break;
            case 't': out[0] = '\t'; break;
            case 'r': out[0] = '\r'; break;
            case 'b': out[0] = '\b'; break;
            case 'f': out[0] = '\f'; break;
            case '"': out[0] = '"'; break;
            case '\\': out[0] = '\\'; break;
            case '/': out[0] = '/'; break;
            case 'u': {
                if (k + 4 >= p->n) { PyMem_Free(buf); return err(p, "bad \\u"); }
                unsigned v = 0;
                for (int h = 1; h <= 4; h++) {
                    char hc = p->s[k + h];
                    v <<= 4;
                    if (hc >= '0' && hc <= '9') v |= hc - '0';
                    else if (hc >= 'a' && hc <= 'f') v |= hc - 'a' + 10;
                    else if (hc >= 'A' && hc <= 'F') v |= hc - 'A' + 10;
                    else { PyMem_Free(buf); return err(p, "bad \\u"); }
                }
                k += 4;
                /* encode v as UTF-8 (BMP only; surrogates fall back) */
                if (v >= 0xD800 && v <= 0xDFFF) {
                    PyMem_Free(buf);
                    return err(p, "surrogate \\u");
                }
                if (v < 0x80) { out[0] = (char)v; }
                else if (v < 0x800) {
                    out[0] = (char)(0xC0 | (v >> 6));
                    out[1] = (char)(0x80 | (v & 0x3F));
                    outn = 2;
                } else {
                    out[0] = (char)(0xE0 | (v >> 12));
                    out[1] = (char)(0x80 | ((v >> 6) & 0x3F));
                    out[2] = (char)(0x80 | (v & 0x3F));
                    outn = 3;
                }
                break;
            }
            default:
                PyMem_Free(buf);
                return err(p, "unsupported escape");
            }
            k++;
        } else {
            out[0] = c;
            k++;
        }
        if (len + outn > cap) {
            cap *= 2;
            char *nb = PyMem_Realloc(buf, cap);
            if (!nb) { PyMem_Free(buf); return PyErr_NoMemory(); }
            buf = nb;
        }
        memcpy(buf + len, out, outn);
        len += outn;
    }
    if (k >= p->n) { PyMem_Free(buf); return err(p, "unterminated string"); }
    PyObject *o = PyUnicode_DecodeUTF8(buf, len, NULL);
    PyMem_Free(buf);
    p->i = k + 1;
    return o;
}

static PyObject *parse_number(P *p) {
    Py_ssize_t start = p->i;
    Py_ssize_t j = p->i;
    if (j < p->n && (p->s[j] == '+' || p->s[j] == '-')) j++;
    int is_float = 0;
    while (j < p->n && !is_delim(p->s[j])) {
        char c = p->s[j];
        if (c == '.' || c == 'e' || c == 'E') is_float = 1;
        else if (c == '/' || c == 'N' || c == 'M' || c == 'r' || c == 'R')
            return err(p, "ratio/bignum/radix literal");  /* fall back */
        else if (!((c >= '0' && c <= '9') || c == '+' || c == '-'))
            return err(p, "bad number");
        j++;
    }
    char tmp[64];
    Py_ssize_t L = j - start;
    if (L >= (Py_ssize_t)sizeof(tmp)) return err(p, "number too long");
    memcpy(tmp, p->s + start, L);
    tmp[L] = '\0';
    p->i = j;
    if (is_float) {
        char *end = NULL;
        double d = PyOS_string_to_double(tmp, &end, NULL);
        if (end != tmp + L) return err(p, "bad float");
        return PyFloat_FromDouble(d);
    }
    errno = 0;
    char *end = NULL;
    long long v = strtoll(tmp, &end, 10);
    if (errno != 0 || end != tmp + L) return err(p, "int overflow");
    return PyLong_FromLongLong(v);
}

static PyObject *parse_ident(P *p, int keyword) {
    Py_ssize_t start = p->i;
    while (p->i < p->n && !is_delim(p->s[p->i])) p->i++;
    PyObject *name = PyUnicode_DecodeUTF8(p->s + start, p->i - start, NULL);
    if (!name) return NULL;
    PyObject *out = PyObject_CallFunctionObjArgs(
        keyword ? kw_fn : sym_fn, name, NULL);
    Py_DECREF(name);
    return out;
}

/* ---- collections ------------------------------------------------------ */

static PyObject *ensure_key(PyObject *k) {
    /* Containers may hold unhashable children (a vector inside an
     * EdnList key, say); route every container through the python
     * reader's recursive _hashable coercion for identical semantics. */
    if (PyList_Check(k) || PyDict_Check(k) || PyTuple_Check(k) ||
        PyAnySet_Check(k)) {
        PyObject *hk = PyObject_CallFunctionObjArgs(hashable_fn, k, NULL);
        Py_DECREF(k);
        return hk;
    }
    return k;
}

static PyObject *parse_seq(P *p, char close, int as_ednlist) {
    p->i++;  /* opening bracket */
    PyObject *lst = PyList_New(0);
    if (!lst) return NULL;
    for (;;) {
        skip_ws(p);
        if (p->i >= p->n) { Py_DECREF(lst); return err(p, "unterminated seq"); }
        if (p->s[p->i] == close) { p->i++; break; }
        PyObject *item = parse_form(p);
        if (!item) { Py_DECREF(lst); return NULL; }
        int rc = PyList_Append(lst, item);
        Py_DECREF(item);
        if (rc < 0) { Py_DECREF(lst); return NULL; }
    }
    if (as_ednlist) {
        PyObject *tup = PyList_AsTuple(lst);
        Py_DECREF(lst);
        if (!tup) return NULL;
        PyObject *out = PyObject_CallFunctionObjArgs(ednlist_cls, tup, NULL);
        Py_DECREF(tup);
        return out;
    }
    return lst;
}

static PyObject *parse_map(P *p) {
    p->i++;  /* '{' */
    PyObject *d = PyDict_New();
    if (!d) return NULL;
    for (;;) {
        skip_ws(p);
        if (p->i >= p->n) { Py_DECREF(d); return err(p, "unterminated map"); }
        if (p->s[p->i] == '}') { p->i++; break; }
        PyObject *k = parse_form(p);
        if (!k) { Py_DECREF(d); return NULL; }
        k = ensure_key(k);
        if (!k) { Py_DECREF(d); return NULL; }
        skip_ws(p);
        if (p->i >= p->n || p->s[p->i] == '}') {
            Py_DECREF(k); Py_DECREF(d);
            return err(p, "map with odd number of forms");
        }
        PyObject *v = parse_form(p);
        if (!v) { Py_DECREF(k); Py_DECREF(d); return NULL; }
        int rc = PyDict_SetItem(d, k, v);
        Py_DECREF(k);
        Py_DECREF(v);
        if (rc < 0) { Py_DECREF(d); return NULL; }
    }
    return d;
}

static PyObject *parse_set(P *p) {
    p->i++;  /* '{' after '#' */
    PyObject *lst = PyList_New(0);
    if (!lst) return NULL;
    for (;;) {
        skip_ws(p);
        if (p->i >= p->n) { Py_DECREF(lst); return err(p, "unterminated set"); }
        if (p->s[p->i] == '}') { p->i++; break; }
        PyObject *item = parse_form(p);
        if (!item) { Py_DECREF(lst); return NULL; }
        item = ensure_key(item);
        if (!item) { Py_DECREF(lst); return NULL; }
        int rc = PyList_Append(lst, item);
        Py_DECREF(item);
        if (rc < 0) { Py_DECREF(lst); return NULL; }
    }
    PyObject *out = PyFrozenSet_New(lst);
    Py_DECREF(lst);
    return out;
}

/* ---- dispatcher ------------------------------------------------------- */

static PyObject *parse_form(P *p) {
    if (p->depth > 100) return err(p, "nesting too deep");
    skip_ws(p);
    if (p->i >= p->n) return err(p, "unexpected end of input");
    char c = p->s[p->i];
    p->depth++;
    PyObject *out = NULL;
    if (c == '"') out = parse_string(p);
    else if (c == '[') out = parse_seq(p, ']', 0);
    else if (c == '(') out = parse_seq(p, ')', 1);
    else if (c == '{') out = parse_map(p);
    else if (c == '#') {
        if (p->i + 1 < p->n && p->s[p->i + 1] == '{') {
            p->i++;
            out = parse_set(p);
        } else if (p->i + 1 < p->n && p->s[p->i + 1] == '_') {
            /* discard form: #_ <form> — parse and drop, then retry */
            p->i += 2;
            PyObject *skip = parse_form(p);
            if (skip) {
                Py_DECREF(skip);
                p->depth--;
                return parse_form(p);
            }
            out = NULL;
        } else {
            out = err(p, "tagged literal");  /* fall back to python */
        }
    }
    else if (c == ':') { p->i++; out = parse_ident(p, 1); }
    else if (c == '\\') out = err(p, "char literal");
    else if ((c >= '0' && c <= '9') ||
             ((c == '+' || c == '-') && p->i + 1 < p->n &&
              p->s[p->i + 1] >= '0' && p->s[p->i + 1] <= '9'))
        out = parse_number(p);
    else {
        /* nil / true / false / symbol */
        Py_ssize_t start = p->i;
        while (p->i < p->n && !is_delim(p->s[p->i])) p->i++;
        Py_ssize_t L = p->i - start;
        const char *w = p->s + start;
        if (L == 3 && memcmp(w, "nil", 3) == 0) { Py_INCREF(Py_None); out = Py_None; }
        else if (L == 4 && memcmp(w, "true", 4) == 0) { Py_INCREF(Py_True); out = Py_True; }
        else if (L == 5 && memcmp(w, "false", 5) == 0) { Py_INCREF(Py_False); out = Py_False; }
        else if (L == 0) out = err(p, "unexpected character");
        else {
            p->i = start;
            out = parse_ident(p, 0);
        }
    }
    p->depth--;
    return out;
}

/* ---- module API ------------------------------------------------------- */

static int get_utf8(PyObject *arg, const char **s, Py_ssize_t *n) {
    if (!PyUnicode_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected str");
        return -1;
    }
    *s = PyUnicode_AsUTF8AndSize(arg, n);
    return *s ? 0 : -1;
}

static PyObject *py_parse(PyObject *self, PyObject *arg) {
    const char *s; Py_ssize_t n;
    if (get_utf8(arg, &s, &n) < 0) return NULL;
    P p = {s, 0, n, 0};
    PyObject *out = parse_form(&p);
    return out;
}

static PyObject *py_parse_all(PyObject *self, PyObject *arg) {
    const char *s; Py_ssize_t n;
    if (get_utf8(arg, &s, &n) < 0) return NULL;
    P p = {s, 0, n, 0};
    PyObject *lst = PyList_New(0);
    if (!lst) return NULL;
    for (;;) {
        skip_ws(&p);
        if (p.i >= p.n) break;
        PyObject *form = parse_form(&p);
        if (!form) { Py_DECREF(lst); return NULL; }
        int rc = PyList_Append(lst, form);
        Py_DECREF(form);
        if (rc < 0) { Py_DECREF(lst); return NULL; }
    }
    return lst;
}

static PyObject *py_configure(PyObject *self, PyObject *args) {
    PyObject *k, *sy, *el, *h;
    if (!PyArg_ParseTuple(args, "OOOO", &k, &sy, &el, &h)) return NULL;
    Py_XINCREF(k); Py_XINCREF(sy); Py_XINCREF(el); Py_XINCREF(h);
    Py_XDECREF(kw_fn); Py_XDECREF(sym_fn);
    Py_XDECREF(ednlist_cls); Py_XDECREF(hashable_fn);
    kw_fn = k; sym_fn = sy; ednlist_cls = el; hashable_fn = h;
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"parse", py_parse, METH_O, "Parse the first EDN form of a string."},
    {"parse_all", py_parse_all, METH_O,
     "Parse every EDN form of a string into a list."},
    {"configure", py_configure, METH_VARARGS,
     "configure(keyword_fn, symbol_fn, ednlist_cls, hashable_fn)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "edn_fast", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit_edn_fast(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    FastParseError = PyErr_NewException("edn_fast.FastParseError",
                                        PyExc_ValueError, NULL);
    Py_INCREF(FastParseError);
    PyModule_AddObject(m, "FastParseError", FastParseError);
    return m;
}
