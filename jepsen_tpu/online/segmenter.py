"""Streaming history segmenter: quiescent cuts + P-compositional key split.

The decrease-and-conquer observation (PAPERS.md "Efficient
Decrease-and-Conquer Linearizability Monitoring"): a history need not be
decided as one monolithic search. Whenever the stream reaches a
*quiescent* point — no invocation is open — real time totally orders
everything before the cut against everything after it, so the history
factors into closed segments that can be decided independently, provided
each segment starts from a state the previous segment could actually
have ended in. On top of that, P-compositionality (the
``jepsen.independent`` key axis) splits each closed segment into per-key
subsegments via the SAME ``history_keys``/``subhistory`` helpers the
offline lifted checker uses, so the two paths cannot drift.

Cut rules (the soundness contract, pinned by tests/test_online.py):

- An invocation opens its process's interval; an ``:ok``/``:fail``
  completion closes it. A cut is legal only at stream positions where no
  interval is open.
- An ``:info`` completion is indeterminate — knossos semantics keep its
  interval open to the end of time — so the first ``:info`` *poisons*
  quiescence: no further cut is ever legal, and the remainder of the
  stream becomes one terminal segment (the no-quiescence slow path; the
  process-pause nemesis exercises the transient version of this, where a
  stalled invocation merely straddles a would-be cut point).

State carry: segment k+1 must be checked from the states segment k could
have ended in. :func:`segment_states` enumerates the EXACT feasible
end-state set of a decided-valid segment (an exhaustive version of the
host oracle's BFS — it keeps searching past the first accept and
collects every accepting configuration's state, decoded to the semantic
value domain via ``Model.decode_state`` so it survives the per-segment
``ValueTable`` rebuild). Carrying the full set — not one arbitrary
linearization's end state — is what makes the online verdict equal the
offline one: two concurrent writes closing a segment leave {v1, v2} as
legal initial states for the next.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, replace
from typing import Any, Iterable, Optional

import numpy as np

from .. import independent as ind
from ..history import History, Op
from ..models import Model
from ..ops.encode import EncodedHistory, encode_history

# Key used for unkeyed (non-[k v]) histories: one stream, one carry.
SINGLE_KEY = "__single__"


class NonMonotoneHistoryError(ValueError):
    """A strict-mode segmenter saw a pre-indexed op BELOW the stream's
    high-water mark.

    The live path silently drops such ops as covered duplicates — the
    resume protocol makes index < already-observed mean "resubmission",
    never new work. A fully *recorded* history makes the opposite
    promise: every op is new, in index order, exactly once — so an
    out-of-order index there is corrupt input (a mis-merged log, a
    shuffled ndjson), and dropping it would silently mis-cut the
    history. Offline ingestion (``jepsen_tpu.offline.plan``) rejects it
    with this typed error instead.
    """

    def __init__(self, index: int, floor: int) -> None:
        self.index = index
        self.floor = floor
        super().__init__(
            f"non-monotone recorded history: op index {index} arrived "
            f"after index {floor - 1} was already observed (offline "
            "histories must be in index order; re-sort the recording "
            "or strip stale duplicates)")


@dataclass(frozen=True)
class KeySegment:
    """One key's slice of one closed segment of the stream.

    ``ops`` are the key's subhistory ops with ``[k v]`` tuples unwrapped
    (exactly what ``independent.subhistory`` hands the offline checker);
    ``seq`` is the global segment ordinal (all KeySegments of one cut
    share it); ``start_index``/``end_index`` bound the history indexes
    the global segment covers; ``terminal`` marks the stream-end segment
    (which may be non-quiescent: open/:info intervals are legal there).
    """

    key: Any
    seq: int
    ops: tuple
    start_index: int
    end_index: int
    terminal: bool = False
    # Monotonic ns when the cut closed (all KeySegments of one cut share
    # it) — the start of the segment's trace span, so queue-wait before
    # the scheduler picks it up is visible in the decision-latency chain.
    cut_ns: int = 0

    @property
    def n_ops(self) -> int:
        return len(self.ops)


class Segmenter:
    """Incremental stream consumer: feed ops with :meth:`offer`, collect
    closed :class:`KeySegment` lists; :meth:`finish` flushes the terminal
    segment. Tracks in-flight invocations per process and cuts at
    quiescent points only (see module docstring for the rules)."""

    def __init__(self, strict: bool = False) -> None:
        # Offline/recorded-history mode: a pre-indexed op below the
        # high-water mark raises NonMonotoneHistoryError instead of
        # being dropped as a resume-protocol duplicate (see the
        # exception's docstring for why the two paths must differ).
        self.strict = strict
        self._buffer: list[Op] = []
        self._open: set = set()  # processes with an open invocation
        self._poisoned = False  # an :info interval is open to end of time
        self._seq = 0
        self._next_index = 0  # assigned when ops arrive unindexed
        self.ops_seen = 0
        self._saw_keyed = False
        self._saw_keyless = False
        # The (index-assigned) Op the last offer() consumed — the
        # monitor reads its index/kind for decision-latency tracking
        # without re-parsing the raw dict. None before the first offer
        # (and after an offer that DROPPED a journal-covered
        # resubmission).
        self.last_op: Optional[Op] = None
        # Journal-restore floor (resume()): pre-indexed ops BELOW it
        # are already covered by the replayed watermark and are
        # dropped — re-checking them as fresh ops from the restored
        # post-state carries could wrongly REFUTE a valid history.
        self._floor = 0
        self.dropped_covered = 0

    def resume(self, next_index: int, next_seq: int) -> None:
        """Restart support (the service's verdict journal): continue
        index assignment and segment numbering where a journaled
        stream left off, so a reconnecting client's ops land AFTER the
        replayed watermark and new cuts extend the journaled seq
        chain. Pre-indexed ops BELOW ``next_index`` are dropped by
        :meth:`offer` from here on (counted in ``dropped_covered``): a
        client that resubmits its covered prefix anyway would
        otherwise have those ops re-checked from the restored
        POST-state carries, which can refute a valid history — the
        server enforces the resume protocol instead of trusting it.
        Must precede the first :meth:`offer`."""
        if self._buffer or self.ops_seen:
            raise RuntimeError("resume() must precede the first offer")
        self._next_index = max(0, int(next_index))
        self._seq = max(0, int(next_seq))
        self._floor = self._next_index

    @property
    def open_ops(self) -> int:
        """Ops buffered in the not-yet-closed segment (telemetry)."""
        return len(self._buffer)

    @property
    def open_invocations(self) -> int:
        return len(self._open)

    @property
    def poisoned(self) -> bool:
        return self._poisoned

    @property
    def segments_emitted(self) -> int:
        return self._seq

    @property
    def next_index(self) -> int:
        """The index the next unindexed op would be assigned (the
        journal-lag telemetry reads it)."""
        return self._next_index

    @property
    def mixed_keys(self) -> bool:
        """True when the stream mixes keyed (``[k v]``) and keyless
        client ops. Offline, ``independent.subhistory`` folds every
        keyless op into EVERY key's subhistory — including keys that
        first appear later in the stream — which a streaming split
        cannot reproduce, so the monitor degrades the fold to
        "unknown" rather than risk a verdict offline contradicts."""
        return self._saw_keyed and self._saw_keyless

    def _as_op(self, op) -> Op:
        if not isinstance(op, Op):
            op = Op.from_dict(op)
        if op.index < 0:
            op = op.with_(index=self._next_index)
        self._next_index = max(self._next_index, op.index + 1)
        return op

    def offer(self, op) -> list[KeySegment]:
        """Consume one history op (Op or plain scheduler dict); returns
        the KeySegments of a newly closed segment, usually ``[]``.
        A pre-indexed op BELOW the stream's high-water mark — the
        restored-journal floor after :meth:`resume`, or simply an
        index this segmenter has already observed — is a covered
        duplicate: DROPPED (never buffered — ``last_op`` reads None
        for it), not re-checked. The live-stream half matters as much
        as the restore half: a client whose POST was ingested but
        whose response was lost (or whose reconnect rewind overlaps
        the watermark) resubmits ops this stream already consumed, and
        re-checking them from the CURRENT carries could refute a valid
        history — a flip, not a degradation. Indexed streams are
        in-order by contract, so index < already-observed is always a
        duplicate, never new work."""
        if isinstance(op, Op):
            had_index = op.index >= 0
        else:
            # Explicit None check, not `or` — index 0 is falsy but
            # very much an index (the nemesis_interval lesson).
            _idx = op.get("index") if isinstance(op, dict) else None
            had_index = isinstance(_idx, int) and _idx >= 0
        seen_through = self._next_index  # BEFORE _as_op advances it
        op = self._as_op(op)
        if had_index and op.index < max(self._floor, seen_through):
            if self.strict:
                raise NonMonotoneHistoryError(
                    op.index, max(self._floor, seen_through))
            self.dropped_covered += 1
            self.last_op = None
            return []
        self.last_op = op
        self.ops_seen += 1
        if not op.is_client:
            return []  # nemesis ops have no invoke/complete discipline
        if ind.is_tuple(op.value):
            self._saw_keyed = True
        else:
            self._saw_keyless = True
        self._buffer.append(op)
        if op.is_invoke:
            self._open.add(op.process)
            return []
        self._open.discard(op.process)
        if op.is_info:
            # Indeterminate: the interval stays open forever; quiescence
            # is unreachable from here on (knossos OPEN-ret semantics).
            self._poisoned = True
        if self._open or self._poisoned or not self._buffer:
            return []
        return self._cut(terminal=False)

    def finish(self) -> list[KeySegment]:
        """Flush whatever remains as the terminal segment (legal even
        when non-quiescent: open intervals encode as OPEN there, exactly
        like the offline checker sees them)."""
        if not self._buffer:
            return []
        return self._cut(terminal=True)

    def _cut(self, terminal: bool) -> list[KeySegment]:
        ops, self._buffer = self._buffer, []
        seq = self._seq
        self._seq += 1
        start = ops[0].index
        end = ops[-1].index
        cut_ns = _time.monotonic_ns()
        keys = sorted(ind.history_keys(ops), key=repr)
        if not keys:
            return [KeySegment(SINGLE_KEY, seq, tuple(ops), start, end,
                               terminal, cut_ns)]
        out = []
        for k in keys:
            sub = ind.subhistory(k, History(ops, reindex=False))
            out.append(KeySegment(k, seq, tuple(sub), start, end, terminal,
                                  cut_ns))
        return out


# ---------------------------------------------------------------------------
# State carry: encoding a segment from carried states, and enumerating
# the feasible end states of a decided segment.


def encode_segment(model: Model, seg: KeySegment,
                   carried: Optional[Iterable[tuple]]) -> list[EncodedHistory]:
    """Encode ``seg`` once per carried initial state.

    ``carried`` is an iterable of *decoded* (semantic) states from the
    previous segment's :func:`segment_states`, or None for the stream's
    first segment (the model's own init). Each returned member shares
    the segment's op rows but starts from one candidate state — the
    batch members the scheduler hands to the PR-2 pipeline; the segment
    is valid iff ANY member is.
    """
    base = encode_history(model, History(list(seg.ops), reindex=False))
    if carried is None:
        return [base]
    out = []
    for st in carried:
        lanes = model.encode_state(st, base.table)
        out.append(replace(base, init_state=np.asarray(lanes,
                                                       dtype=np.int32)))
    return out


def segment_states(enc: EncodedHistory,
                   max_configs: int = 500_000) -> dict:
    """Exhaustively decide one encoded segment AND enumerate its feasible
    end states.

    Unlike the host oracle (ops/wgl_host.py), which stops at the first
    accepting configuration, this BFS runs the whole reachable config
    space so the returned ``end_states`` is the EXACT set of states some
    valid linearization ends in — the next segment's legal initial
    states. Returns ``{"valid": True|False|"unknown", "end_states":
    [decoded states] | None, "configs_explored": n}``; ``end_states`` is
    None on a budget trip (the caller then carries "unknown" forward).

    Closed segments contain no ``:info`` ops (an :info poisons
    quiescence, so only terminal segments can carry them); skippable
    rows are handled anyway for the terminal case.
    """
    model = enc.model
    n = enc.n
    init = tuple(int(x) for x in enc.init_state)
    if n == 0:
        return {"valid": True,
                "end_states": [model.decode_state(init, enc.table)],
                "configs_explored": 0}
    from ..ops import wgl_host

    required = frozenset(i for i in range(n) if not enc.skippable[i])
    ret_order = sorted(range(n), key=lambda i: int(enc.ret[i]))
    start = (frozenset(), init)
    frontier = {start}
    seen = {start}
    explored = 0
    accepting_states: set = set()

    def accepting(cfg) -> bool:
        return required <= cfg[0]

    if accepting(start):
        accepting_states.add(init)
    while frontier:
        nxt = set()
        for linearized, state in frontier:
            explored += 1
            if explored > max_configs:
                from ..checker import provenance as _prov

                return _prov.attach(
                    {"valid": "unknown", "end_states": None,
                     "configs_explored": explored,
                     "info": f"config budget {max_configs} exhausted"},
                    "max_configs", budget=max_configs,
                    engine="enumerator")
            # Successor rule shared with the first-accept oracle
            # (wgl_host.expand) — the differential contract depends on
            # the two searches agreeing.
            for j, state2 in wgl_host.expand(enc, linearized, state,
                                             ret_order):
                cfg2 = (linearized | {j}, state2)
                if cfg2 in seen:
                    continue
                seen.add(cfg2)
                if accepting(cfg2):
                    accepting_states.add(state2)
                nxt.add(cfg2)
        frontier = nxt
    if not accepting_states:
        return {"valid": False, "end_states": [],
                "configs_explored": explored}
    return {
        "valid": True,
        "end_states": sorted(
            (model.decode_state(s, enc.table) for s in accepting_states),
            key=repr),
        "configs_explored": explored,
    }
